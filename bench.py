"""Round benchmark: ALBERT-base MLM training throughput on one chip.

Prints ONE JSON line: tokens/sec/chip for the flagship collaborative-pretraining
model (fwd+bwd+optax update, bf16 compute), plus achieved MFU relative to the 35%
north-star target (BASELINE.json: ALBERT-base tokens/sec/chip at >=35% MFU)."""

import json
import time


def flops_per_token(config, seq_len: int, head_fraction: float = 1.0) -> float:
    """fwd+bwd FLOPs per token ~= 6 * (matmul params-equivalent per token).

    ``head_fraction``: the MLM head (transform + tied decoder) runs only on this
    fraction of positions when the train step uses the masked-only loss path
    (models/albert.py loss_masked_only) — count what actually executes."""
    h, i, L = config.hidden_size, config.intermediate_size, config.num_layers
    per_layer = 4 * h * h + 2 * h * i  # qkv+out projections + ffn (MACs per token)
    attention_quadratic = 2 * seq_len * h  # QK^T + PV MACs per token (x6 below -> FLOPs)
    head = h * config.embedding_size + config.embedding_size * config.vocab_size
    total_params_equiv = L * (per_layer + attention_quadratic) + head_fraction * head
    return 6.0 * total_params_equiv


_PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s by device kind substring
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, value in _PEAK_BF16_FLOPS.items():
        if key in kind:
            return value
    return 197e12  # default: v5e-class


def _tpu_reachable(attempts: int = 3, timeout: float = 120.0) -> bool:
    """Probe TPU initialization in a SUBPROCESS: if the accelerator tunnel is wedged,
    jax.devices() hangs forever and would take the whole benchmark (and its driver)
    with it. A hung probe is killed and retried with backoff (a busy tunnel often
    recovers); only after all attempts fail does the bench fall back to CPU — and
    then it says so loudly in the output instead of grading the CPU number."""
    import subprocess
    import sys

    for attempt in range(attempts):
        if attempt:
            time.sleep(10.0 * attempt)
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; assert jax.devices()[0].platform != 'cpu'"],
                timeout=timeout,
                capture_output=True,
            )
            if probe.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
    return False


def _averaging_gbps(timeout: float = 420.0):
    """Second driver metric: butterfly all-reduce GB/s/peer (CPU/network-bound, does
    not need the TPU). Run in a subprocess so a swarm hang can't take down the bench."""
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "benchmark_averaging.py")
    try:
        run = subprocess.run(
            [sys.executable, script, "--num_peers", "4", "--target_group_size", "4",
             "--num_rounds", "3", "--num_params", "4000000",
             "--min_matchmaking_time", "1.0"],
            timeout=timeout, capture_output=True, text=True,
        )
        for line in run.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except (subprocess.TimeoutExpired, json.JSONDecodeError, OSError):
        pass
    return None


def measure_main(force_cpu: bool = False) -> dict:
    """The device measurement (no averaging metric): returns the result dict.
    Run via ``bench.py --_measure`` in a subprocess so a TPU runtime that wedges
    AFTER the reachability probe cannot hang the whole benchmark — a hang inside
    device init blocks in C code where no Python signal handler runs, so the only
    reliable watchdog is a process boundary."""
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from hivemind_tpu.models import AlbertConfig, make_synthetic_mlm_batch, make_train_step

    device = jax.devices()[0]
    on_tpu = device.platform != "cpu"
    seq_len = 512 if on_tpu else 128
    masked_fraction = 0.25  # loss_masked_only budget (see flops_per_token)

    config = AlbertConfig.base(max_position=seq_len)
    optimizer = optax.adamw(1e-4)

    _steps = {}  # remat -> (model, train_step); built lazily, jit-cached across probes

    def get_step(remat: bool):
        if remat not in _steps:
            cfg = AlbertConfig.base(max_position=seq_len, remat=remat)
            _steps[remat] = make_train_step(cfg, optimizer, masked_loss_fraction=masked_fraction)
        return _steps[remat]

    def _is_oom(error: Exception) -> bool:
        text = str(error)
        return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

    def measure(batch_size: int, num_steps: int, remat: bool = False):
        """Throughput of one config; fresh state each time (buffers are donated)."""
        model, train_step = get_step(remat)
        batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, batch_size, seq_len)
        params = model.init(jax.random.PRNGKey(1), batch["input_ids"][:1, :8])["params"]
        opt_state = optimizer.init(params)
        step = jax.jit(train_step, donate_argnums=(0, 1))
        loss, params, opt_state = step(params, opt_state, batch)  # compile
        jax.block_until_ready(loss)
        loss, params, opt_state = step(params, opt_state, batch)  # settle caches
        jax.block_until_ready(loss)
        start = time.perf_counter()
        for _ in range(num_steps):
            loss, params, opt_state = step(params, opt_state, batch)
        jax.block_until_ready(loss)
        elapsed = time.perf_counter() - start
        return batch_size * seq_len * num_steps / elapsed, float(loss)

    if on_tpu:
        # auto-tune (batch size, remat) on the actual chip: the MXU/HBM sweet spot
        # varies by generation. Plain candidates ascend until OOM; remat trades
        # recompute FLOPs for activation memory, so it unlocks the larger batches —
        # probe it from the last plain size upward and keep whichever wins.
        best = None
        plain_limit = None
        for candidate in (32, 64, 128, 256):
            try:
                tps, _ = measure(candidate, num_steps=5, remat=False)
            except Exception as e:
                if _is_oom(e):
                    plain_limit = candidate
                    break  # larger plain candidates will also fail
                print(f"# batch {candidate} probe failed (non-OOM), skipping: {e!r}",
                      file=__import__("sys").stderr)
                continue
            if best is None or tps > best[1]:
                best = (candidate, tps, False)
        remat_start = plain_limit if plain_limit is not None else 256
        for candidate in (c for c in (128, 256, 512) if c >= remat_start):
            try:
                tps, _ = measure(candidate, num_steps=5, remat=True)
            except Exception as e:
                if _is_oom(e):
                    break
                print(f"# remat batch {candidate} probe failed (non-OOM), skipping: {e!r}",
                      file=__import__("sys").stderr)
                continue
            if best is None or tps > best[1]:
                best = (candidate, tps, True)
        batch_size, _, use_remat = best if best is not None else (32, 0.0, False)
        num_steps = 20
    else:
        batch_size, num_steps, use_remat = 4, 5, False

    tokens_per_sec, final_loss = measure(batch_size, num_steps, remat=use_remat)

    result = {
        "metric": "albert_base_mlm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "extra": {
            "device": str(getattr(device, "device_kind", device.platform)),
            "batch_size": batch_size,
            "remat": use_remat,
            "seq_len": seq_len,
            "final_loss": round(float(final_loss), 4),
        },
    }
    if on_tpu:
        mfu = (
            tokens_per_sec
            * flops_per_token(config, seq_len, head_fraction=masked_fraction)
            / peak_flops(device)
        )
        result["vs_baseline"] = round(mfu / 0.35, 4)
        result["extra"]["mfu"] = round(mfu, 4)
        result["extra"]["masked_loss_fraction"] = masked_fraction
    else:
        # TPU unreachable after retries: refuse to grade a CPU number against a TPU
        # baseline (round-1 lesson: a silent fallback reads as a 2000x regression).
        result["tpu_unavailable"] = True
        result["fallback"] = "cpu"
        result["vs_baseline"] = 0.0
    return result


def _measure_in_subprocess(timeout: float = 1800.0):
    """Run measure_main in a child process; returns its result dict or None on
    hang/crash. The child is killed on timeout, so a wedged TPU runtime costs at
    most `timeout` seconds instead of the whole round."""
    import os
    import subprocess
    import sys

    try:
        run = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_measure"],
            timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        print("# TPU measurement subprocess timed out (runtime wedged mid-run)",
              file=sys.stderr)
        return None
    for line in run.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                pass
    print(f"# TPU measurement subprocess failed (rc={run.returncode}): "
          f"{run.stderr[-500:]}", file=sys.stderr)
    return None


def main() -> None:
    result = None
    if _tpu_reachable():
        for _attempt in range(2):
            candidate = _measure_in_subprocess()
            if candidate is not None:
                # keep a completed result even when it is the tpu_unavailable CPU
                # fallback (it is already honest and complete); retry once in case
                # the TPU grab was transient, but never discard finished work
                result = candidate
                if not candidate.get("tpu_unavailable"):
                    break
    if result is None:
        # child hung or crashed: run the CPU fallback inline (CPU jax cannot hang)
        result = measure_main(force_cpu=True)

    averaging = _averaging_gbps()
    result.setdefault("extra", {})
    result["extra"]["averaging_gbps_per_peer"] = (averaging or {}).get("value")
    result["extra"]["averaging_extra"] = (averaging or {}).get("extra")
    print(json.dumps(result))


if __name__ == "__main__":
    import sys

    if "--_measure" in sys.argv:
        print(json.dumps(measure_main()))
    else:
        main()
