"""Round benchmark: ALBERT-base MLM training throughput on one chip.

Prints ONE JSON line: tokens/sec/chip for the flagship collaborative-pretraining
model (fwd+bwd+optax update, bf16 compute), plus achieved MFU relative to the 35%
north-star target (BASELINE.json: ALBERT-base tokens/sec/chip at >=35% MFU)."""

import json
import time


def flops_per_token(config, seq_len: int, head_fraction: float = 1.0) -> float:
    """fwd+bwd FLOPs per token ~= 6 * (matmul params-equivalent per token).

    ``head_fraction``: the MLM head (transform + tied decoder) runs only on this
    fraction of positions when the train step uses the masked-only loss path
    (models/albert.py loss_masked_only) — count what actually executes."""
    h, i, L = config.hidden_size, config.intermediate_size, config.num_layers
    per_layer = 4 * h * h + 2 * h * i  # qkv+out projections + ffn (MACs per token)
    attention_quadratic = 2 * seq_len * h  # QK^T + PV MACs per token (x6 below -> FLOPs)
    head = h * config.embedding_size + config.embedding_size * config.vocab_size
    total_params_equiv = L * (per_layer + attention_quadratic) + head_fraction * head
    return 6.0 * total_params_equiv


_PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s by device kind substring
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, value in _PEAK_BF16_FLOPS.items():
        if key in kind:
            return value
    return 197e12  # default: v5e-class


def _tpu_probe(attempts: int = 3, timeout: float = 120.0):
    """Probe TPU initialization in a SUBPROCESS: if the accelerator tunnel is wedged,
    jax.devices() hangs forever and would take the whole benchmark (and its driver)
    with it. A hung probe is killed and retried with backoff (a busy tunnel often
    recovers); only after all attempts fail does the bench fall back to CPU — and
    then it says so loudly in the output instead of grading the CPU number.

    Returns ``(reachable, errors)`` where ``errors`` records every failed attempt's
    returncode and stderr tail — two rounds of artifacts contained zero bytes of
    evidence about WHY the chip never answered (VERDICT r2 weak #1); the emitted
    JSON now carries the verbatim failure."""
    import subprocess
    import sys

    errors = []
    for attempt in range(attempts):
        if attempt:
            time.sleep(10.0 * attempt)
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices()[0]; assert d.platform != 'cpu', d"],
                timeout=timeout,
                capture_output=True,
                text=True,
            )
            if probe.returncode == 0:
                return True, errors
            errors.append({
                "attempt": attempt, "rc": probe.returncode,
                "stderr": probe.stderr[-500:],
            })
        except subprocess.TimeoutExpired as e:
            stderr = e.stderr
            if isinstance(stderr, bytes):
                stderr = stderr.decode(errors="replace")
            errors.append({
                "attempt": attempt, "rc": None,
                "stderr": f"probe hung >{timeout:.0f}s (tunnel wedged); "
                          f"partial stderr: {(stderr or '')[-400:]}",
            })
    return False, errors


def _run_driver_json(script_name: str, argv: list, timeout: float, env: dict = None):
    """Run one benchmarks/ driver in a subprocess (a hang can't take down the
    bench) and harvest its first JSON stdout line; None on any failure."""
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", script_name)
    try:
        run = subprocess.run(
            [sys.executable, script, *argv],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
        for line in run.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except (subprocess.TimeoutExpired, json.JSONDecodeError, OSError):
        pass
    return None


def _averaging_gbps(timeout: float = 420.0, compression: str = "FLOAT16"):
    """Second driver metric: butterfly all-reduce GB/s/peer (CPU/network-bound,
    does not need the TPU)."""
    return _run_driver_json(
        "benchmark_averaging.py",
        ["--num_peers", "4", "--target_group_size", "4", "--num_rounds", "3",
         "--num_params", "4000000", "--min_matchmaking_time", "1.0",
         "--compression", compression],
        timeout,
    )


def _averaging_gbps_q8(timeout: float = 420.0):
    """The quantized tier of the same A/B (ISSUE 11): identical swarm/payload
    with the uniform8 wire codec (per-link error feedback on), so BENCH
    artifacts track the 8-bit GB/s/peer (fp32-equivalent) next to fp16."""
    return _averaging_gbps(timeout=timeout, compression="uniform8")


def _llama_serving(timeout: float = 420.0):
    """Third driver metric: Petals-style checkpoint-served KV-cache decode tok/s
    (CPU-bound RPC + device dispatch, does not need the TPU), carrying the
    serving-attribution summary (ISSUE 9) in its extra."""
    return _run_driver_json(
        "benchmark_llama_serving.py",
        ["--platform", "cpu", "--hidden_dim", "256", "--inner", "704",
         "--layers", "2", "--generate", "32"],
        timeout,
    )


def _swarm_sim(timeout: float = 420.0):
    """Fourth driver metric (ISSUE 12): the in-process swarm simulator's scale
    numbers — peers simulated, sim-seconds per wall-second, beam-search routing
    recall@beam vs the oracle, and same-seed determinism. Pure CPU + virtual
    clock; the bench config is a mid-size soak (the full 1k-peer/10k-expert
    acceptance run lives in the slow chaos suite)."""
    import os

    return _run_driver_json(
        "benchmark_swarm_sim.py",
        ["--scenario", "soak", "--peers", "300", "--grid", "8", "8", "40",
         "--beam_size", "8", "--trials", "4"],
        timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def measure_main(force_cpu: bool = False) -> dict:
    """The device measurement (no averaging metric): returns the result dict.
    Run via ``bench.py --_measure`` in a subprocess so a TPU runtime that wedges
    AFTER the reachability probe cannot hang the whole benchmark — a hang inside
    device init blocks in C code where no Python signal handler runs, so the only
    reliable watchdog is a process boundary."""
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from hivemind_tpu.models import AlbertConfig, make_synthetic_mlm_batch, make_train_step

    device = jax.devices()[0]
    on_tpu = device.platform != "cpu"
    seq_len = 512 if on_tpu else 128
    masked_fraction = 0.25  # loss_masked_only budget (see flops_per_token)

    config = AlbertConfig.base(max_position=seq_len)
    optimizer = optax.adamw(1e-4)

    _steps = {}  # (remat, flash) -> (model, train_step); built lazily, jit-cached

    def get_step(remat: bool, flash: bool = True):
        key = (remat, flash)
        if key not in _steps:
            # the flash/plain split happens at TRACE time (attention_auto reads the
            # env var then) — measure() pins the env var right before compiling
            cfg = AlbertConfig.base(max_position=seq_len, remat=remat)
            _steps[key] = make_train_step(cfg, optimizer, masked_loss_fraction=masked_fraction)
        return _steps[key]

    def _is_oom(error: Exception) -> bool:
        text = str(error)
        return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

    def measure(batch_size: int, num_steps: int, remat: bool = False, flash: bool = True):
        """Throughput of one config; fresh state each time (buffers are donated)."""
        import os

        model, train_step = get_step(remat, flash)
        batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, batch_size, seq_len)
        params = model.init(jax.random.PRNGKey(1), batch["input_ids"][:1, :8])["params"]
        opt_state = optimizer.init(params)
        step = jax.jit(train_step, donate_argnums=(0, 1))
        # attention_auto reads the env var when the step is TRACED — i.e. at this
        # first call — so pin it here, per variant
        os.environ["HIVEMIND_TPU_FLASH_ATTENTION"] = "1" if flash else "0"
        loss, params, opt_state = step(params, opt_state, batch)  # compile
        jax.block_until_ready(loss)
        loss, params, opt_state = step(params, opt_state, batch)  # settle caches
        jax.block_until_ready(loss)
        start = time.perf_counter()
        for _ in range(num_steps):
            loss, params, opt_state = step(params, opt_state, batch)
        jax.block_until_ready(loss)
        elapsed = time.perf_counter() - start
        return batch_size * seq_len * num_steps / elapsed, float(loss)

    attention_extra = {}
    if on_tpu:
        # gate the flash default on an ON-DEVICE validation of the Mosaic-compiled
        # kernels (interpret-mode parity is necessary, not sufficient): if any
        # flash check fails on this chip, the whole bench runs the einsum core
        # and the artifact records why
        try:
            from hivemind_tpu.ops.device_check import validate_on_device

            validation = validate_on_device(seq=seq_len)
        except Exception as e:
            validation = {"ok": False, "attention_ok": False, "errors": {"validate": repr(e)[:500]}}
        flash_ok = bool(validation.get("attention_ok"))
        attention_extra["device_validation"] = validation

        # auto-tune (batch size, remat) on the actual chip: the MXU/HBM sweet spot
        # varies by generation. Plain candidates ascend until OOM; remat trades
        # recompute FLOPs for activation memory, so it unlocks the larger batches —
        # probe it from the last plain size upward and keep whichever wins.
        best = None
        plain_limit = None
        for candidate in (32, 64, 128, 256):
            try:
                tps, _ = measure(candidate, num_steps=5, remat=False, flash=flash_ok)
            except Exception as e:
                if _is_oom(e):
                    plain_limit = candidate
                    break  # larger plain candidates will also fail
                print(f"# batch {candidate} probe failed (non-OOM), skipping: {e!r}",
                      file=__import__("sys").stderr)
                continue
            if best is None or tps > best[1]:
                best = (candidate, tps, False)
        remat_start = plain_limit if plain_limit is not None else 256
        for candidate in (c for c in (128, 256, 512) if c >= remat_start):
            try:
                tps, _ = measure(candidate, num_steps=5, remat=True, flash=flash_ok)
            except Exception as e:
                if _is_oom(e):
                    break
                print(f"# remat batch {candidate} probe failed (non-OOM), skipping: {e!r}",
                      file=__import__("sys").stderr)
                continue
            if best is None or tps > best[1]:
                best = (candidate, tps, True)
        batch_size, _, use_remat = best if best is not None else (32, 0.0, False)
        num_steps = 20

        # flash-vs-einsum A/B at the tuned config: the headline number uses the
        # WINNER, and the artifact records both sides (VERDICT r2 item 2)
        ab = {}
        for flash in ([True, False] if flash_ok else [False]):
            name = "flash" if flash else "plain"
            try:
                ab[name], _ = measure(batch_size, num_steps=10, remat=use_remat, flash=flash)
            except Exception as e:
                attention_extra[f"attention_{name}_error"] = repr(e)[:500]
        use_flash = flash_ok and ab.get("flash", 0.0) >= ab.get("plain", 0.0)
        attention_extra["attention"] = "flash" if use_flash else "plain"
        attention_extra["attention_tokens_per_sec"] = {k: round(v, 1) for k, v in ab.items()}
        if flash_ok and not use_flash:
            attention_extra["attention_note"] = "einsum core won the A/B on this chip"
    else:
        batch_size, num_steps, use_remat, use_flash = 4, 5, False, False

    tokens_per_sec, final_loss = measure(batch_size, num_steps, remat=use_remat, flash=use_flash)

    result = {
        "metric": "albert_base_mlm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "extra": {
            "device": str(getattr(device, "device_kind", device.platform)),
            "batch_size": batch_size,
            "remat": use_remat,
            "seq_len": seq_len,
            "final_loss": round(float(final_loss), 4),
            **attention_extra,
        },
    }
    if on_tpu:
        mfu = (
            tokens_per_sec
            * flops_per_token(config, seq_len, head_fraction=masked_fraction)
            / peak_flops(device)
        )
        result["vs_baseline"] = round(mfu / 0.35, 4)
        result["extra"]["mfu"] = round(mfu, 4)
        result["extra"]["masked_loss_fraction"] = masked_fraction
    else:
        # TPU unreachable after retries: refuse to grade a CPU number against a TPU
        # baseline (round-1 lesson: a silent fallback reads as a 2000x regression).
        result["tpu_unavailable"] = True
        result["fallback"] = "cpu"
        result["vs_baseline"] = 0.0
    return result


def _measure_in_subprocess(timeout: float = 1800.0):
    """Run measure_main in a child process; returns ``(result_dict_or_None,
    error_or_None)``. The child is killed on timeout, so a wedged TPU runtime
    costs at most `timeout` seconds instead of the whole round — and the failure
    text is RETURNED so the emitted JSON can carry it."""
    import os
    import subprocess
    import sys

    try:
        run = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_measure"],
            timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return None, f"measurement subprocess hung >{timeout:.0f}s (runtime wedged mid-run)"
    for line in run.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                pass
    return None, f"measurement subprocess failed (rc={run.returncode}): {run.stderr[-500:]}"


def _try_measure(diagnostics: list):
    """Up to two measurement attempts; every failure is appended to diagnostics."""
    result = None
    for _attempt in range(2):
        candidate, error = _measure_in_subprocess()
        if error is not None:
            diagnostics.append(error)
        if candidate is not None:
            # keep a completed result even when it is the tpu_unavailable CPU
            # fallback (it is already honest and complete); retry once in case
            # the TPU grab was transient, but never discard finished work
            result = candidate
            if not candidate.get("tpu_unavailable"):
                break
    return result


def _host_control() -> dict:
    """A fixed-config compute control (VERDICT r3 next-round #2): the same ~1 s
    single-core matmul and AEAD-seal workloads every round, so artifact-to-artifact
    swings in the OFFICIAL numbers can be attributed — if the control dropped 30%
    too, the host was co-tenanted, not the code regressed. Pure host work, cannot
    hang, no jax import."""
    import os

    import numpy as np

    control: dict = {
        "unix_time": round(time.time(), 1),
        "loadavg": [round(x, 2) for x in os.getloadavg()],
        "cpu_count": os.cpu_count(),
    }
    a = np.random.RandomState(0).randn(768, 768).astype(np.float32)
    start = time.perf_counter()
    iterations = 0
    while time.perf_counter() - start < 1.0:
        a = a @ a * 1e-3  # keep values bounded; the product forces real FLOPs
        iterations += 1
    elapsed = time.perf_counter() - start
    control["matmul_gflops"] = round(2 * 768**3 * iterations / elapsed / 1e9, 2)
    try:
        from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

        aead = ChaCha20Poly1305(bytes(32))
        payload = bytes(1 << 20)
        start = time.perf_counter()
        sealed = 0
        while time.perf_counter() - start < 1.0:
            aead.encrypt(bytes(12), payload, None)
            sealed += 1
        control["aead_seal_mb_s"] = round(sealed / (time.perf_counter() - start), 1)
    except Exception as e:  # pragma: no cover - cryptography is baked in
        control["aead_seal_mb_s"] = None
        control["aead_error"] = repr(e)[:200]
    return control


def _probe_point(label: str, probe_log: list, attempts: int) -> bool:
    """One timestamped+loadavg-stamped TPU probe entry; the tunnel wedges
    TRANSIENTLY, so the round probes at >=3 separated points (VERDICT r3 #2)."""
    import os

    entry = {
        "when": label,
        "unix_time": round(time.time(), 1),
        "loadavg": [round(x, 2) for x in os.getloadavg()],
    }
    reachable, errors = _tpu_probe(attempts=attempts)
    entry["reachable"] = reachable
    if errors:
        entry["errors"] = errors
    probe_log.append(entry)
    return reachable


_COMPACT_EXTRA_KEYS = (
    "device", "mfu", "batch_size", "remat", "seq_len", "final_loss",
    "attention", "masked_loss_fraction", "averaging_gbps_per_peer",
    "averaging_gbps_q8_per_peer", "swarm_sim",
)
# least-important-first drop order when the compact line must shrink to fit
_COMPACT_DROP_ORDER = (
    "tpu_probes", "swarm_sim", "masked_loss_fraction", "attention", "final_loss",
    "remat", "batch_size", "seq_len", "device", "averaging_gbps_q8_per_peer",
    "averaging_gbps_per_peer", "mfu",
)


def compact_result(result: dict, max_chars: int = 1500) -> str:
    """The final-stdout-line JSON: metric-first, guaranteed under ``max_chars``.

    The round driver records only the last ~2000 chars of output; round 4's
    artifact embedded the probe log inside the single JSON line and truncated
    away its own metric (VERDICT r4 weak #1). The headline fields therefore go
    FIRST and the line degrades by dropping optional extras, never the metric."""
    extra = result.get("extra") or {}
    compact = {
        "metric": result.get("metric"),
        "value": result.get("value"),
        "unit": result.get("unit"),
        "vs_baseline": result.get("vs_baseline"),
    }
    for flag in ("tpu_unavailable", "fallback"):
        if flag in result:
            compact[flag] = result[flag]
    compact_extra = {
        k: extra[k] for k in _COMPACT_EXTRA_KEYS if extra.get(k) is not None
    }
    probe_log = result.get("tpu_probe_log")
    if probe_log:
        compact_extra["tpu_probes"] = [
            {"when": p.get("when"), "reachable": p.get("reachable")} for p in probe_log
        ]
    compact["extra"] = compact_extra
    line = json.dumps(compact)
    for drop in _COMPACT_DROP_ORDER:
        if len(line) <= max_chars:
            break
        compact_extra.pop(drop, None)
        line = json.dumps(compact)
    if len(line) > max_chars:
        compact.pop("extra", None)
        line = json.dumps(compact)
    return line


def telemetry_section(averaging=None, serving=None) -> dict:
    """The telemetry snapshot embedded in every BENCH artifact (ISSUE 2): the
    bench process's own registry plus the averaging swarm's snapshot (shipped
    through the subprocess's JSON extra), so round artifacts carry a per-phase
    breakdown — five rounds of BENCH carried none (VERDICT r5).

    ISSUE 8: the averaging swarm's ledger + watchdog summary ride along
    (``attribution`` key) — rounds run, mean/p95 per-phase durations, straggler
    scores, stall count and max loop lag — so a perf regression's artifact says
    WHERE the regression lives (matchmaking? one slow peer? a blocked loop?),
    not just the headline number."""
    try:
        from hivemind_tpu.telemetry import build_peer_snapshot

        section: dict = {"bench_process": build_peer_snapshot()}
    except Exception as e:  # the artifact must survive a broken local install
        section = {"error": repr(e)[:200]}
    averaging_extra = (averaging or {}).get("extra") or {}
    swarm = averaging_extra.get("telemetry")
    if swarm:
        section["averaging_swarm"] = swarm
    attribution = averaging_extra.get("attribution")
    if attribution:
        section["attribution"] = attribution
    # ISSUE 9: the serving swarm's per-request attribution summary (per-expert
    # p50/p95, phase decomposition, batch occupancy, shed count) rides under
    # "serving" — a serving regression's artifact names the phase that moved
    serving_extra = (serving or {}).get("extra") or {}
    if serving_extra.get("serving"):
        section["serving"] = serving_extra["serving"]
    # ISSUE 19: the device-side story — this process's compile/memory/transfer
    # snapshot, plus the serving subprocess's steady-state compile guard (a
    # recompile storm in the decode loop is a silent tok/s regression)
    device: dict = {}
    try:
        from hivemind_tpu.telemetry.device import device_snapshot

        local = device_snapshot()
        if local:
            device["bench_process"] = local
    except Exception as e:
        device["error"] = repr(e)[:200]
    if serving_extra.get("device") is not None:
        device["serving"] = serving_extra["device"]
    if serving_extra.get("steady_state_compiles") is not None:
        device["serving_steady_state_compiles"] = serving_extra["steady_state_compiles"]
    if device:
        section["device"] = device
    return section


def lint_section() -> dict:
    """ISSUE 16: the hivemind-lint summary embedded in every BENCH artifact —
    per-rule violation/suppressed/allowlisted counts (no finding bodies), so
    each round records the static health of the exact tree it measured.
    Defensive: lint trouble must never take the benchmark down."""
    import os
    import sys

    try:
        tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        from lint.engine import run_suite

        summary = run_suite().to_json(include_findings=False)
        summary["total_stale_allowlist"] = sum(
            rule.get("stale_allowlist", 0) for rule in summary.get("rules", {}).values()
        )
        return summary
    except Exception as e:
        return {"error": repr(e)[:200]}


def emit(result: dict, out=None, err=None) -> None:
    """Full diagnostics (probe log, controls, errors) go to stderr; stdout's final
    line is the compact metric-first JSON the driver records."""
    import sys

    out = sys.stdout if out is None else out
    err = sys.stderr if err is None else err
    print(json.dumps(result), file=err, flush=True)
    print(compact_result(result), file=out, flush=True)


def main() -> None:
    diagnostics: list = []
    probe_log: list = []
    result = None
    control_start = _host_control()
    if _probe_point("round_start", probe_log, attempts=3):
        result = _try_measure(diagnostics)
    averaging = _averaging_gbps()
    averaging_q8 = _averaging_gbps_q8()
    serving = _llama_serving()
    swarm_sim = _swarm_sim()
    if result is None or result.get("tpu_unavailable"):
        # a tunnel wedged at round start may be free now (the averaging swarm just
        # bought several minutes): probe again mid-round
        if _probe_point("mid_round_post_averaging", probe_log, attempts=2):
            result = _try_measure(diagnostics) or result
    control_end = _host_control()
    if result is None or result.get("tpu_unavailable"):
        # final widened window before emitting (a few more minutes of separation)
        time.sleep(20.0)
        if _probe_point("pre_emit", probe_log, attempts=2):
            result = _try_measure(diagnostics) or result
    if result is None:
        # child hung or crashed: run the CPU fallback inline (CPU jax cannot hang)
        result = measure_main(force_cpu=True)

    result.setdefault("extra", {})
    result["extra"]["averaging_gbps_per_peer"] = (averaging or {}).get("value")
    # the quantized tier's fp32-equivalent rate + its success rate (the lossy
    # tier must not buy throughput with failed rounds)
    result["extra"]["averaging_gbps_q8_per_peer"] = (averaging_q8 or {}).get("value")
    q8_extra = (averaging_q8 or {}).get("extra") or {}
    result["extra"]["averaging_q8_success_rate"] = q8_extra.get("success_rate")
    result["extra"]["llama_serving_tok_s"] = (serving or {}).get("value")
    # ISSUE 12: the swarm simulator's scale numbers — peers simulated,
    # sim-seconds/wall-second, routing recall@beam, same-seed determinism
    swarm_extra = (swarm_sim or {}).get("extra") or {}
    result["extra"]["swarm_sim"] = {
        "peers": (swarm_sim or {}).get("value"),
        "sim_seconds_per_wall_second": swarm_extra.get("sim_seconds_per_wall_second"),
        "recall_at_beam": swarm_extra.get("recall_at_beam"),
        "deterministic": swarm_extra.get("deterministic"),
        "get_success_rate": swarm_extra.get("get_success_rate"),
        # virtual-time round-ledger summary (ISSUE 17): round totals and
        # straggler attribution aggregated from the sim's synthesized
        # allreduce spans — part of the determinism digest above
        "ledger": swarm_extra.get("ledger"),
        # the driver prints its JSON line before exiting nonzero on a breached
        # invariant — without this list a failed soak would read as clean data
        "failures": swarm_extra.get("failures"),
    } if swarm_sim else None
    # the swarm telemetry + attribution snapshots land ONCE, in
    # result["telemetry"] below — strip them from the copied extra so the
    # artifact does not carry them twice
    averaging_extra = (averaging or {}).get("extra")
    if isinstance(averaging_extra, dict):
        averaging_extra = {
            k: v for k, v in averaging_extra.items() if k not in ("telemetry", "attribution")
        }
    result["extra"]["averaging_extra"] = averaging_extra
    # attributability: the same-config controls bracket the averaging run, so a
    # co-tenancy swing shows up as a control swing right next to the number
    result["extra"]["host_control"] = {"at_start": control_start, "at_end": control_end}
    result["tpu_probe_log"] = probe_log
    result["telemetry"] = telemetry_section(averaging, serving)
    result["lint"] = lint_section()
    if diagnostics:
        result["tpu_measure_errors"] = diagnostics
    emit(result)


if __name__ == "__main__":
    import sys

    if "--_measure" in sys.argv:
        print(json.dumps(measure_main()))
    else:
        main()
