#!/usr/bin/env python3
"""Lint: every registered ``hivemind_*`` metric must be documented (ISSUE 9).

docs/observability.md is the operator's metric catalog, and it already drifted
once (the queue-depth gauge was documented under a wrong name). This lint keeps
the catalog honest by construction:

1. **AST scan** — every ``*.counter("hivemind_...")`` / ``.gauge(...)`` /
   ``.histogram(...)`` call in the tree whose first argument is a string
   literal starting with ``hivemind_`` registers a metric name. A non-literal
   first argument to one of those methods is a violation too (dynamic metric
   names cannot be cataloged).
2. **Catalog check** — each registered name must appear verbatim somewhere in
   docs/observability.md. Missing names fail the suite.
3. **Stale-entry sweep** — names that look like metrics in the doc's catalog
   tables (``| `hivemind_...` |`` rows) but are registered nowhere are
   reported as warnings so the catalog shrinks with the code.

Run directly (``python tools/check_metric_docs.py``) or via
``tests/test_metric_docs_lint.py`` (tier-1).
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE_ROOT = REPO_ROOT / "hivemind_tpu"
DOC_PATH = REPO_ROOT / "docs" / "observability.md"

_REGISTER_METHODS = {"counter", "gauge", "histogram"}
_DOC_TABLE_NAME = re.compile(r"^\|\s*`(hivemind_[a-z0-9_]+)`")

# documented names that are rendered, not registered (the exporter appends
# _total to counters / _bucket/_sum/_count to histograms at scrape time)
_RENDERED_SUFFIXES = ("_total", "_bucket", "_sum", "_count")


def registered_metrics(
    package_root: Path = PACKAGE_ROOT,
) -> Tuple[Dict[str, List[str]], List[str]]:
    """Returns ({metric_name: [file:line, ...]}, [dynamic-name violations])."""
    names: Dict[str, List[str]] = {}
    dynamic: List[str] = []
    for path in sorted(package_root.rglob("*.py")):
        relpath = str(path.relative_to(package_root.parent))
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTER_METHODS
                and node.args
            ):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                if first.value.startswith("hivemind_"):
                    names.setdefault(first.value, []).append(f"{relpath}:{node.lineno}")
            elif isinstance(first, ast.Constant):
                continue  # literal non-string: not a metric registration
            else:
                # .counter(variable) — could be re-declaring an existing family
                # (watchdog re-registers by passing <metric>.documentation); only
                # flag when the call LOOKS like a registry registration, i.e.
                # the receiver is named like a registry
                receiver = node.func.value
                receiver_name = getattr(receiver, "id", getattr(receiver, "attr", ""))
                if str(receiver_name).lower().endswith(("registry", "telemetry")) or (
                    str(receiver_name) == "REGISTRY"
                ):
                    dynamic.append(
                        f"{relpath}:{node.lineno} — dynamic metric name in "
                        f".{node.func.attr}(...): metric names must be string "
                        f"literals so the catalog lint can see them"
                    )
    return names, dynamic


def documented_names(doc_path: Path = DOC_PATH) -> Tuple[str, Set[str]]:
    """Returns (full doc text, names that appear as catalog-table rows)."""
    text = doc_path.read_text()
    table_names = {
        match.group(1)
        for line in text.splitlines()
        for match in [_DOC_TABLE_NAME.match(line.strip())]
        if match is not None
    }
    return text, table_names


def check(
    package_root: Path = PACKAGE_ROOT, doc_path: Path = DOC_PATH
) -> Tuple[List[str], List[str]]:
    """Returns (failures, warnings) as printable strings."""
    names, dynamic = registered_metrics(package_root)
    doc_text, table_names = documented_names(doc_path)
    failures = list(dynamic)
    for name, sites in sorted(names.items()):
        if name not in doc_text:
            failures.append(
                f"metric {name!r} (registered at {', '.join(sites[:3])}) is not in "
                f"docs/observability.md — add it to the catalog"
            )
    warnings = []
    registered = set(names)
    for name in sorted(table_names):
        candidates = {name} | {
            name[: -len(suffix)] for suffix in _RENDERED_SUFFIXES if name.endswith(suffix)
        }
        if not candidates & registered:
            warnings.append(
                f"docs/observability.md catalogs {name!r} but nothing registers it "
                f"(stale entry or typo'd name — the drift this lint exists to catch)"
            )
    return failures, warnings


def main() -> int:
    failures, warnings = check()
    for warning in warnings:
        print(f"warning: {warning}")
    if failures:
        print(f"{len(failures)} metric-catalog violation(s):")
        for failure in failures:
            print(f"  {failure}")
        return 1
    names, _dynamic = registered_metrics()
    print(f"ok: all {len(names)} registered hivemind_* metrics are documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
