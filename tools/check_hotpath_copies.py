#!/usr/bin/env python3
"""Lint: keep the averaging AND serving hot paths copy-free (ISSUE 6 satellite;
serving coverage added by ISSUE 10).

The throughput work in ISSUE 6 removed per-part byte concats and always-copy
``astype`` calls from the averaging tensor→wire pipeline; ISSUE 10 did the same
for the serving data path. This lint keeps them out of the hot-path files:

    p2p/mux.py, p2p/crypto_channel.py, averaging/partition.py, averaging/allreduce.py,
    moe/client/expert.py, moe/server/connection_handler.py, moe/server/task_pool.py

Rules:

1. ``bytes-concat`` — a ``+`` expression whose operand is recognizably bytes
   (a bytes literal, ``struct``'s ``.pack(...)``, ``.tobytes()``,
   ``.SerializeToString()``, ``.to_bytes()``, or ``bytes(...)``): on the frame
   path this doubles megabyte payloads. Use scatter-gather instead —
   ``send_frame(id, flags, *buffers)`` / ``SecureChannel.send(header, payload)``.
2. ``copy-astype`` — an ``.astype(...)`` call without an explicit ``copy=``
   keyword: ``astype`` copies even when the dtype already matches. Spell out
   ``astype(..., copy=False)`` (or ``copy=True`` where a copy is the point).

Findings are keyed ``(relative path, enclosing def, kind)`` — stable across
line-number churn. Reviewed occurrences (small control-plane frames, handshake
transcripts) are grandfathered in ``ALLOWLIST``; the wired-in test fails on
anything NEW and warns on stale entries so the list shrinks over time.

Run directly (``python tools/check_hotpath_copies.py``) or via
``tests/test_hotpath_copies_lint.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE_ROOT = REPO_ROOT / "hivemind_tpu"

HOT_FILES = (
    "p2p/mux.py",
    "p2p/crypto_channel.py",
    "averaging/partition.py",
    "averaging/allreduce.py",
    "averaging/residual.py",
    "compression/quantization.py",
    "moe/client/expert.py",
    "moe/server/connection_handler.py",
    "moe/server/task_pool.py",
)

Finding = Tuple[str, str, str]  # (relpath, enclosing function, kind)

# Reviewed occurrences. Do not add hot-loop sites here — route large payloads
# through the scatter-gather framing instead.
ALLOWLIST: Set[Finding] = {
    # handshake control plane: tiny transcript/hello/upgrade frames, never per-part
    ("p2p/crypto_channel.py", "_send_plain", "bytes-concat"),
    ("p2p/crypto_channel.py", "handshake._run", "bytes-concat"),
}

_BYTES_PRODUCING_METHODS = {"pack", "tobytes", "SerializeToString", "to_bytes"}


def _is_bytes_typed(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _BYTES_PRODUCING_METHODS:
            return True
        if isinstance(fn, ast.Name) and fn.id == "bytes":
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _is_bytes_typed(node.left) or _is_bytes_typed(node.right)
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: List[Tuple[Finding, int]] = []
        self._scope: List[str] = []

    # --- scope tracking -------------------------------------------------
    def _visit_scoped(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _visit_scoped

    def _qualname(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _record(self, kind: str, lineno: int) -> None:
        self.findings.append(((self.relpath, self._qualname(), kind), lineno))

    # --- rules ----------------------------------------------------------
    def visit_BinOp(self, node: ast.BinOp):
        if isinstance(node.op, ast.Add) and (
            _is_bytes_typed(node.left) or _is_bytes_typed(node.right)
        ):
            self._record("bytes-concat", node.lineno)
            # one finding per outermost concat chain: do not descend further
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "astype":
            if not any(keyword.arg == "copy" for keyword in node.keywords):
                self._record("copy-astype", node.lineno)
        self.generic_visit(node)


def collect_findings(package_root: Path = PACKAGE_ROOT) -> List[Tuple[Finding, int]]:
    findings: List[Tuple[Finding, int]] = []
    for relpath in HOT_FILES:
        path = package_root / relpath
        tree = ast.parse(path.read_text(), filename=str(path))
        visitor = _Visitor(relpath)
        visitor.visit(tree)
        findings.extend(visitor.findings)
    return findings


def check(package_root: Path = PACKAGE_ROOT) -> Tuple[List[str], List[str]]:
    """Returns (new_violations, stale_allowlist_entries) as printable strings."""
    found = collect_findings(package_root)
    found_keys = {key for key, _lineno in found}
    new = [
        f"{key[0]}:{lineno} [{key[2]}] in {key[1]} — "
        + ("pass buffers scatter-gather (send_frame/SecureChannel.send varargs)"
           if key[2] == "bytes-concat"
           else "spell out astype(..., copy=False) on the hot path")
        for key, lineno in sorted(found)
        if key not in ALLOWLIST
    ]
    stale = [f"{entry[0]} [{entry[2]}] in {entry[1]}" for entry in sorted(ALLOWLIST - found_keys)]
    return new, stale


def main() -> int:
    new, stale = check()
    for entry in stale:
        print(f"note: stale allowlist entry (cleaned up — remove it): {entry}")
    if new:
        print(f"{len(new)} new copy/concat site(s) in the averaging hot path:")
        for violation in new:
            print(f"  {violation}")
        return 1
    print("ok: no byte concats or implicit-copy astype calls in the hot path")
    return 0


if __name__ == "__main__":
    sys.exit(main())
