#!/usr/bin/env python3
"""Lint: no blocking calls inside ``async def`` on the swarm's event loop
(ISSUE 8 satellite).

The entire stack shares one asyncio loop (utils/loop.py): a single synchronous
call inside a coroutine stalls matchmaking, DHT RPCs and part streams for the
whole process — and to the rest of the swarm the peer looks like a network
straggler. The watchdog (telemetry/watchdog.py) catches such stalls at
runtime; this lint keeps new ones from being written at all. Scanned trees:

    p2p/, dht/, averaging/, moe/

Rules — flagged only when the INNERMOST enclosing function is ``async def``
(a nested sync ``def`` is the standard run-in-executor pattern and is fine):

1. ``time-sleep`` — ``time.sleep(...)`` (or a bare ``sleep`` imported from
   ``time``): use ``await asyncio.sleep(...)``.
2. ``blocking-io`` — ``open(...)`` or ``Path``-style ``.read_text()`` /
   ``.read_bytes()`` / ``.write_text()`` / ``.write_bytes()``: move file IO
   into ``run_in_executor`` (utils/asyncio_utils.py).
3. ``sync-socket`` — ``socket.socket(...)`` / ``socket.create_connection(...)``
   / ``socket.getaddrinfo(...)`` / ``socket.socketpair(...)``: use the loop's
   transport APIs (``loop.sock_*``, ``open_connection``) or an executor.

Findings are keyed ``(relative path, enclosing def, kind)`` — stable across
line-number churn. Pre-existing occurrences are grandfathered in ``ALLOWLIST``;
the wired-in test (tests/test_blocking_in_async_lint.py) fails on anything NEW
and warns on stale entries so the list shrinks over time.

Run directly (``python tools/check_blocking_in_async.py``) or via the test.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE_ROOT = REPO_ROOT / "hivemind_tpu"

SCANNED_TREES = ("p2p", "dht", "averaging", "moe")

Finding = Tuple[str, str, str]  # (relpath, enclosing function, kind)

# Pre-existing sites, reviewed and grandfathered (do not add new ones — fix the
# code instead). Currently EMPTY: the scanned trees are clean; keep them so.
ALLOWLIST: Set[Finding] = set()

_PATHLIKE_IO_METHODS = {"read_text", "read_bytes", "write_text", "write_bytes"}
_SOCKET_BLOCKING_FUNCS = {"socket", "create_connection", "getaddrinfo", "socketpair"}


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: List[Tuple[Finding, int]] = []
        self._scope: List[str] = []
        # parallel stack: is the function at this scope level async?
        self._func_kind: List[str] = []  # "async" | "sync" | "class"

    # --- scope tracking -------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._scope.append(node.name)
        self._func_kind.append("sync")
        self.generic_visit(node)
        self._func_kind.pop()
        self._scope.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._scope.append(node.name)
        self._func_kind.append("async")
        self.generic_visit(node)
        self._func_kind.pop()
        self._scope.pop()

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope.append(node.name)
        self._func_kind.append("class")
        self.generic_visit(node)
        self._func_kind.pop()
        self._scope.pop()

    def _in_async_function(self) -> bool:
        """True when the innermost enclosing FUNCTION is async (classes are
        transparent: a method defined in a class inside an async def counts by
        the method's own kind)."""
        for kind in reversed(self._func_kind):
            if kind == "class":
                continue
            return kind == "async"
        return False

    def _qualname(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _record(self, kind: str, lineno: int) -> None:
        self.findings.append(((self.relpath, self._qualname(), kind), lineno))

    # --- rules ----------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        if self._in_async_function():
            fn = node.func
            if isinstance(fn, ast.Attribute):
                owner = fn.value
                if isinstance(owner, ast.Name):
                    if owner.id == "time" and fn.attr == "sleep":
                        self._record("time-sleep", node.lineno)
                    elif owner.id == "socket" and fn.attr in _SOCKET_BLOCKING_FUNCS:
                        self._record("sync-socket", node.lineno)
                if fn.attr in _PATHLIKE_IO_METHODS:
                    self._record("blocking-io", node.lineno)
            elif isinstance(fn, ast.Name):
                if fn.id == "open":
                    self._record("blocking-io", node.lineno)
                elif fn.id == "sleep" and self._imported_time_sleep:
                    self._record("time-sleep", node.lineno)
        self.generic_visit(node)

    _imported_time_sleep = False

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "time" and any(alias.name == "sleep" for alias in node.names):
            self._imported_time_sleep = True
        self.generic_visit(node)


def collect_findings(package_root: Path = PACKAGE_ROOT) -> List[Tuple[Finding, int]]:
    findings: List[Tuple[Finding, int]] = []
    for tree_name in SCANNED_TREES:
        for path in sorted((package_root / tree_name).rglob("*.py")):
            relpath = str(path.relative_to(package_root))
            tree = ast.parse(path.read_text(), filename=str(path))
            visitor = _Visitor(relpath)
            visitor.visit(tree)
            findings.extend(visitor.findings)
    return findings


_ADVICE = {
    "time-sleep": "use `await asyncio.sleep(...)` — time.sleep blocks the whole swarm loop",
    "blocking-io": "move file IO off the loop (run_in_executor in utils/asyncio_utils.py)",
    "sync-socket": "use the loop's transports (open_connection / loop.sock_*) or an executor",
}


def check(package_root: Path = PACKAGE_ROOT) -> Tuple[List[str], List[str]]:
    """Returns (new_violations, stale_allowlist_entries) as printable strings."""
    found = collect_findings(package_root)
    found_keys = {key for key, _lineno in found}
    new = [
        f"{key[0]}:{lineno} [{key[2]}] in {key[1]} — {_ADVICE[key[2]]}"
        for key, lineno in sorted(found)
        if key not in ALLOWLIST
    ]
    stale = [f"{entry[0]} [{entry[2]}] in {entry[1]}" for entry in sorted(ALLOWLIST - found_keys)]
    return new, stale


def main() -> int:
    new, stale = check()
    for entry in stale:
        print(f"note: stale allowlist entry (cleaned up — remove it): {entry}")
    if new:
        print(f"{len(new)} blocking call(s) inside async def on the swarm loop:")
        for violation in new:
            print(f"  {violation}")
        return 1
    print("ok: no blocking calls inside async def under p2p/dht/averaging/moe")
    return 0


if __name__ == "__main__":
    sys.exit(main())
