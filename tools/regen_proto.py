"""Regenerate ``averaging_pb2.py`` without protoc (ISSUE 7 state-sync schema).

``proto/regen.sh`` needs a protoc binary, which the jax_graft image does not
ship. The generated modules are nothing but a serialized ``FileDescriptorProto``
handed to the protobuf builder, so the schema can be evolved with the runtime
library alone: parse the checked-in blob, append the new messages/fields
declared in ``ADDITIONS`` (idempotent — re-running is a no-op), re-serialize,
and rewrite the ``*_pb2.py`` in the exact protoc output shape (including the
``_serialized_start``/``_serialized_end`` offsets, located by substring search
the same way protoc computes them: each message's offsets point at its own
serialized ``DescriptorProto`` inside the file blob).

Keep ``averaging.proto`` in sync BY HAND — it stays the human-readable source
of truth and regains authority the moment a protoc is available.

Run from the repo root::

    python tools/regen_proto.py
"""

from __future__ import annotations

import sys
from pathlib import Path

from google.protobuf import descriptor_pb2

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

F = descriptor_pb2.FieldDescriptorProto

# (message, field name, number, label, type, type_name) appended iff absent
FIELD_ADDITIONS = [
    # resume support: the receiver names the tensors it already holds verified,
    # so a failover donor streams only what is missing
    ("DownloadRequest", "have_tensors", 1, F.LABEL_REPEATED, F.TYPE_UINT32, None),
    # striping probe: return just the manifest, then end the stream
    ("DownloadRequest", "manifest_only", 2, F.LABEL_OPTIONAL, F.TYPE_BOOL, None),
    ("DownloadData", "manifest", 3, F.LABEL_OPTIONAL, F.TYPE_MESSAGE, ".hivemind_tpu.StateManifest"),
    ("DownloadData", "tensor_index", 4, F.LABEL_OPTIONAL, F.TYPE_UINT32, None),
    # quantized delta leg (ISSUE 11): tensor_part carries the reduced average
    # of this part (quantized once with reducer-side error feedback) instead of
    # a per-sender delta; the sender subtracts its own input locally
    ("AveragingData", "absolute_part", 6, F.LABEL_OPTIONAL, F.TYPE_BOOL, None),
]

# (message name, [(field name, number, label, type, type_name), ...])
MESSAGE_ADDITIONS = [
    (
        "TensorManifest",
        [
            ("num_bytes", 1, F.LABEL_OPTIONAL, F.TYPE_UINT64, None),
            ("digest", 2, F.LABEL_OPTIONAL, F.TYPE_BYTES, None),
        ],
    ),
    (
        "StateManifest",
        [
            ("schema_hash", 1, F.LABEL_OPTIONAL, F.TYPE_STRING, None),
            ("epoch", 2, F.LABEL_OPTIONAL, F.TYPE_UINT64, None),
            ("state_unavailable", 3, F.LABEL_OPTIONAL, F.TYPE_BOOL, None),
            ("tensors", 4, F.LABEL_REPEATED, F.TYPE_MESSAGE, ".hivemind_tpu.TensorManifest"),
            ("metadata", 5, F.LABEL_OPTIONAL, F.TYPE_BYTES, None),
        ],
    ),
]


def _add_field(message, name, number, label, field_type, type_name) -> bool:
    if any(field.name == name for field in message.field):
        return False
    field = message.field.add()
    field.name = name
    field.number = number
    field.label = label
    field.type = field_type
    if type_name is not None:
        field.type_name = type_name
    return True


def evolve(file_proto: descriptor_pb2.FileDescriptorProto) -> int:
    changed = 0
    by_name = {message.name: message for message in file_proto.message_type}
    for message_name, fields in MESSAGE_ADDITIONS:
        if message_name not in by_name:
            message = file_proto.message_type.add()
            message.name = message_name
            by_name[message_name] = message
            changed += 1
        for name, number, label, field_type, type_name in fields:
            changed += _add_field(by_name[message_name], name, number, label, field_type, type_name)
    for message_name, name, number, label, field_type, type_name in FIELD_ADDITIONS:
        changed += _add_field(by_name[message_name], name, number, label, field_type, type_name)
    return changed


def render_pb2(file_proto: descriptor_pb2.FileDescriptorProto, module_name: str) -> str:
    blob = file_proto.SerializeToString()

    def offsets(proto_message) -> tuple:
        serialized = proto_message.SerializeToString()
        start = blob.find(serialized)
        assert start >= 0, f"could not locate {proto_message.name!r} in the file blob"
        return start, start + len(serialized)

    offset_lines = []
    for enum in file_proto.enum_type:
        start, end = offsets(enum)
        upper = enum.name.upper()
        offset_lines.append(f"  _{upper}._serialized_start={start}")
        offset_lines.append(f"  _{upper}._serialized_end={end}")
    for message in file_proto.message_type:
        start, end = offsets(message)
        upper = message.name.upper()
        offset_lines.append(f"  _{upper}._serialized_start={start}")
        offset_lines.append(f"  _{upper}._serialized_end={end}")
    offsets_block = "\n".join(offset_lines)

    return f'''# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# source: {file_proto.name}
# regenerated by tools/regen_proto.py (no protoc on this image)
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


from hivemind_tpu.proto import runtime_pb2 as hivemind__tpu_dot_proto_dot_runtime__pb2


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, {module_name!r}, globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
{offsets_block}
# @@protoc_insertion_point(module_scope)
'''


def main() -> None:
    from hivemind_tpu.proto import averaging_pb2

    file_proto = descriptor_pb2.FileDescriptorProto.FromString(
        averaging_pb2.DESCRIPTOR.serialized_pb
    )
    changed = evolve(file_proto)
    target = REPO_ROOT / "hivemind_tpu" / "proto" / "averaging_pb2.py"
    target.write_text(render_pb2(file_proto, "hivemind_tpu.proto.averaging_pb2"))
    print(f"{target}: {changed} schema addition(s) applied")


if __name__ == "__main__":
    main()
