#!/usr/bin/env python3
"""Lint: keep failure handling in the resilience layer (ISSUE 3 satellite).

Flags, everywhere under ``hivemind_tpu/`` EXCEPT ``resilience/``:

1. ``swallow`` — a bare ``except:`` / ``except Exception:`` / ``except
   BaseException:`` whose body is exactly ``pass``: silent failure handling.
   Use a logged warning + telemetry counter, or a narrower exception type.
2. ``retry-loop`` — a ``while``/``for`` loop that both sleeps via
   ``asyncio.sleep``/``time.sleep`` AND swallows broad exceptions to keep
   looping: a hand-rolled retry loop. Use
   :class:`hivemind_tpu.resilience.RetryPolicy` instead.

Findings are keyed ``(relative path, enclosing def, kind)`` — stable across
line-number churn. Pre-existing occurrences reviewed at introduction time are
grandfathered in ``ALLOWLIST``; the wired-in test fails on anything NEW, and
warns on stale allowlist entries so the list shrinks over time.

Run directly (``python tools/check_adhoc_retries.py``) or via
``tests/test_resilience.py::test_no_new_adhoc_failure_handling``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE_ROOT = REPO_ROOT / "hivemind_tpu"

Finding = Tuple[str, str, str]  # (relpath, enclosing function, kind)

# Grandfathered occurrences, reviewed when this lint was introduced. Do not add
# to this list — route new failure handling through hivemind_tpu/resilience/.
ALLOWLIST: Set[Finding] = {
    # best-effort teardown during create/shutdown/del: failures here must never
    # mask the original exception, and there is nothing useful to log mid-unwind
    ("p2p/p2p.py", "P2P.create", "swallow"),
    ("p2p/p2p.py", "P2P.shutdown", "swallow"),
    ("p2p/mux.py", "MuxConnection.close", "swallow"),
    ("p2p/crypto_channel.py", "SecureChannel.close", "swallow"),
    ("p2p/crypto_channel.py", "SecureChannel.wait_closed", "swallow"),
    ("dht/dht.py", "DHT.__del__", "swallow"),
    # prctl/platform probes where absence IS the answer
    ("p2p/native_transport.py", "_die_with_parent", "swallow"),
    ("moe/server/llama_loader.py", "device_hbm_bytes", "swallow"),
    # parser fallback chain (tries multiaddr forms in order)
    ("p2p/peer_id.py", "Multiaddr.parse", "swallow"),
    # periodic stats publishing: failure is cosmetic by design
    ("moe/server/runtime.py", "Runtime._maybe_report_stats", "swallow"),
}


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Name) and handler.type.id in ("Exception", "BaseException"):
        return True
    if isinstance(handler.type, ast.Tuple):
        return any(
            isinstance(element, ast.Name) and element.id in ("Exception", "BaseException")
            for element in handler.type.elts
        )
    return False


def _is_sleep_call(node: ast.AST) -> bool:
    call = node
    if isinstance(call, ast.Await):
        call = call.value
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "sleep"
        and isinstance(fn.value, ast.Name)
        and fn.value.id in ("asyncio", "time")
    )


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: List[Tuple[Finding, int]] = []
        self._scope: List[str] = []

    # --- scope tracking -------------------------------------------------
    def _visit_scoped(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = _visit_scoped

    def _qualname(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _record(self, kind: str, lineno: int) -> None:
        self.findings.append(((self.relpath, self._qualname(), kind), lineno))

    # --- rules ----------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if _broad_handler(node) and len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
            self._record("swallow", node.lineno)
        self.generic_visit(node)

    def _visit_loop(self, node):
        sleeps = any(_is_sleep_call(child) for child in ast.walk(node))
        swallows_to_loop = False
        for child in ast.walk(node):
            if not isinstance(child, ast.Try):
                continue
            for handler in child.handlers:
                if not _broad_handler(handler):
                    continue
                # "keep looping silently" shapes: pass / continue only — a handler
                # that logs and counts before continuing is the approved pattern
                if all(isinstance(stmt, (ast.Pass, ast.Continue)) for stmt in handler.body):
                    swallows_to_loop = True
        if sleeps and swallows_to_loop:
            self._record("retry-loop", node.lineno)
        self.generic_visit(node)

    visit_While = visit_For = visit_AsyncFor = _visit_loop


def collect_findings(package_root: Path = PACKAGE_ROOT) -> List[Tuple[Finding, int]]:
    findings: List[Tuple[Finding, int]] = []
    for path in sorted(package_root.rglob("*.py")):
        parts = path.relative_to(package_root).parts
        if "resilience" in parts or "__pycache__" in parts:
            continue
        relpath = "/".join(parts)
        tree = ast.parse(path.read_text(), filename=str(path))
        visitor = _Visitor(relpath)
        visitor.visit(tree)
        findings.extend(visitor.findings)
    return findings


def check(package_root: Path = PACKAGE_ROOT) -> Tuple[List[str], List[str]]:
    """Returns (new_violations, stale_allowlist_entries) as printable strings."""
    found = collect_findings(package_root)
    found_keys = {key for key, _lineno in found}
    new = [
        f"{key[0]}:{lineno} [{key[2]}] in {key[1]} — "
        + ("use RetryPolicy from hivemind_tpu.resilience" if key[2] == "retry-loop"
           else "log + count instead of silently passing")
        for key, lineno in sorted(found)
        if key not in ALLOWLIST
    ]
    stale = [f"{entry[0]} [{entry[2]}] in {entry[1]}" for entry in sorted(ALLOWLIST - found_keys)]
    return new, stale


def main() -> int:
    new, stale = check()
    for entry in stale:
        print(f"note: stale allowlist entry (cleaned up — remove it): {entry}")
    if new:
        print(f"{len(new)} new ad-hoc failure-handling site(s) outside hivemind_tpu/resilience/:")
        for violation in new:
            print(f"  {violation}")
        return 1
    print("ok: no new ad-hoc retry loops or silent except blocks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
