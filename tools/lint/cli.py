"""``hivemind-lint``: run the unified static-analysis suite (ISSUE 16).

Exit status: 0 when clean; 1 on any unsuppressed finding OR any stale
allowlist entry (an allowlist row whose finding no longer fires is debt that
must be deleted, not carried). ``--json`` emits the machine-readable summary
that bench.py embeds in BENCH artifacts.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from lint.engine import ALLOWLIST_DIR, LintContext, SuiteResult, run_suite
from lint.rules import ALL_RULES, get_rule


def _render_human(suite: SuiteResult) -> List[str]:
    lines: List[str] = []
    for result in suite.results:
        rule = result.rule
        status = "ok" if not (result.violations or result.stale_allowlist) else "FAIL"
        lines.append(
            f"[{status}] {rule.name}: {len(result.violations)} violation(s), "
            f"{len(result.suppressed)} suppressed, {len(result.allowlisted)} allowlisted "
            f"({result.duration_s * 1000:.0f} ms)"
        )
        for finding in result.violations:
            lines.append(f"    {finding.render()}")
        for stale in result.stale_allowlist:
            lines.append(
                f"    stale allowlist entry {stale!r} — no longer fires; delete it from "
                f"allowlists/{rule.name}.conf"
            )
        for warning in result.warnings:
            lines.append(f"    warning: {warning}")
    total_stale = sum(len(result.stale_allowlist) for result in suite.results)
    verdict = "clean" if suite.ok and not total_stale else "DIRTY"
    lines.append(
        f"hivemind-lint: {verdict} — {suite.total_violations} violation(s), "
        f"{total_stale} stale allowlist entr(y/ies) across {len(suite.results)} rule(s) "
        f"in {suite.duration_s:.2f} s"
    )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hivemind-lint",
        description="unified static-analysis suite for hivemind_tpu "
        "(asyncio races, task leaks, missing deadlines, wire drift, chaos coverage, "
        "plus the ported retry/blocking/hot-path/metric-docs checks)",
    )
    parser.add_argument("--json", action="store_true", help="emit a JSON summary instead of text")
    parser.add_argument(
        "--rule", action="append", metavar="NAME",
        help="run only this rule (repeatable); default: all",
    )
    parser.add_argument("--list-rules", action="store_true", help="list rules and exit")
    parser.add_argument(
        "--root", type=Path, default=None,
        help="repo root to lint (default: the repo this tool lives in)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_cls in ALL_RULES:
            print(f"{rule_cls.name:20s} {rule_cls.title}")
        return 0

    if args.rule:
        try:
            rules = [get_rule(name)() for name in args.rule]
        except KeyError as exc:
            print(f"hivemind-lint: {exc.args[0]}", file=sys.stderr)
            return 2
    else:
        rules = [rule_cls() for rule_cls in ALL_RULES]

    ctx = LintContext(repo_root=args.root) if args.root is not None else LintContext()
    suite = run_suite(rules=rules, ctx=ctx, allowlist_dir=ALLOWLIST_DIR)

    total_stale = sum(len(result.stale_allowlist) for result in suite.results)
    if args.json:
        payload = suite.to_json()
        payload["total_stale_allowlist"] = total_stale
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print("\n".join(_render_human(suite)))
    return 0 if suite.ok and not total_stale else 1


if __name__ == "__main__":
    raise SystemExit(main())
