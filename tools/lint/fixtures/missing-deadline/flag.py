"""MUST-flag fixture for ``missing-deadline``: the replication-fetch bug shape
— a network await with no deadline machinery anywhere in the function body. A
signature parameter alone deliberately does NOT count: an accepted-but-unused
``chunk_timeout`` is precisely the defect this rule exists to find."""


async def fetch(stub, request):
    return await stub.call_protobuf_handler("rpc_fetch", request)


async def fetch_replica_state(stub, request, chunk_timeout):
    async for part in stub.iterate_protobuf_handler("rpc_fetch_stream", request):
        yield part
