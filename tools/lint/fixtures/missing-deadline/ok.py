"""MUST-pass fixture for ``missing-deadline``: the deadline actually reaches
the network await — wait_for on the unary path, aiter_with_timeout (with the
parameter USED in the body) on the stream path."""

import asyncio


async def fetch_unary(stub, request):
    return await asyncio.wait_for(
        stub.call_protobuf_handler("rpc_fetch", request), timeout=10.0
    )


async def fetch_replica_state(stub, request, chunk_timeout, aiter_with_timeout):
    stream = stub.iterate_protobuf_handler("rpc_fetch_stream", request)
    async for part in aiter_with_timeout(stream, chunk_timeout):
        yield part
