# MUST-pass fixture for metric-docs: every registration is a string literal
# and every name has a catalog row.
DOCUMENTED = REGISTRY.counter("hivemind_fixture_documented_total", "in the catalog", ())
ALSO = REGISTRY.gauge("hivemind_fixture_depth", "also in the catalog")
