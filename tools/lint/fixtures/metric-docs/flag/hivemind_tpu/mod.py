# MUST-flag fixture for metric-docs: one metric the catalog never mentions
# (undocumented-metric) and one registered under a computed name the lint
# cannot tie to a catalog row (dynamic-metric-name).
DOCUMENTED = REGISTRY.counter("hivemind_fixture_documented_total", "in the catalog", ())
PHANTOM = REGISTRY.counter("hivemind_fixture_phantom_total", "absent from the catalog", ())
name = "hivemind_" + "computed"
DYNAMIC = REGISTRY.gauge(name, "uncatalogable")
