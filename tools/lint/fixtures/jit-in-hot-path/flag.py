"""MUST-flag fixture for ``jit-in-hot-path``: a fresh ``jax.jit`` inside a
hot-path function body recompiles per call and bypasses compile accounting."""

import jax
from jax import jit


def forward(params, x):
    step = jax.jit(lambda p, v: p @ v)  # fresh jit object EVERY call
    return step(params, x)


class Backend:
    def apply(self, params, grads):
        # stashing on self still skips hivemind_device_compiles_total
        self._apply = jit(lambda p, g: p - g)
        return self._apply(params, grads)
