"""MUST-pass fixture for ``jit-in-hot-path``: the sanctioned homes for
``jax.jit`` — module scope, ``__init__`` setup, cached factories — and the
preferred ``tracked_jit`` wrapper on the hot path itself."""

import functools

import jax

from hivemind_tpu.utils.profiling import tracked_jit

_STEP = jax.jit(lambda p, v: p @ v)  # module scope: compiled once at import


class Backend:
    def __init__(self):
        # one-time per-object setup (tracked_jit still preferred: it counts)
        self._apply = jax.jit(lambda p, g: p - g)

    def forward(self, params, x):
        return _STEP(params, x)


@functools.lru_cache(maxsize=None)
def make_step(static_shape):
    return jax.jit(lambda p, v: p @ v)  # one jit per static key, cached


def hot(params, x):
    # the hot-path idiom: compile-accounted jit with a stable site label
    return tracked_jit(lambda p, v: p @ v, site="fixture.hot")(params, x)
