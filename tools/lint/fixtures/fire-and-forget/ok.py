"""MUST-pass fixture for ``fire-and-forget``: the approved shapes — tracked
``spawn(coro, name=...)``, a stored-and-awaited handle, and a cancelled one."""

import asyncio


async def start(coro, other, spawn):
    spawn(coro, name="fixture.start")  # tracked: strong ref + logged + counted
    task = asyncio.create_task(other)
    await task


async def start_and_cancel(coro):
    task = asyncio.ensure_future(coro)
    task.cancel()
