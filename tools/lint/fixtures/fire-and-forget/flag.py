"""MUST-flag fixture for ``fire-and-forget``: dropped task handles — the relay
accept-loop / matchmaking key-refresh bug shape. asyncio keeps only a weak
reference; the task is collectable mid-flight and its exception rots until
interpreter shutdown."""

import asyncio


async def start(loop, coro):
    asyncio.create_task(coro)
    asyncio.ensure_future(coro)
    loop.create_task(coro)
