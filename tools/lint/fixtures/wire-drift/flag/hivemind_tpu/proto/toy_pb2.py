# Synthetic descriptor for the wire-drift fixture (toy.proto: ExpertRequest
# with uid = 1 string, metadata = 3 bytes). Never imported — the rule reads
# the AddSerializedFile blob straight off the AST.
DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(
    b'\n\ttoy.proto\x12\x03toy".\n\rExpertRequest\x12\x0b\n\x03uid\x18\x01 \x01(\t\x12\x10\n\x08metadata\x18\x03 \x01(\x0cb\x06proto3'
)
