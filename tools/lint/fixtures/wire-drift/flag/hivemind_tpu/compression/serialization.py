# MUST-flag fixture for wire-drift's hand-rolled-tag checks.

# tag-drift: ExpertRequest.uid is field 1 wire type 2 -> the tag byte must be
# b"\x0a"; b"\x12" is field 2's tag, the exact renumbering bug the rule exists
# to catch (frames the canonical parser rejects)
_REQUEST_UID_TAG = b"\x12"  # ExpertRequest.uid = 1

# tag-unverifiable: no `# Message.field = N` comment ties this constant to a
# proto field, so the lint cannot prove it right or wrong
_REQUEST_METADATA_TAG = b"\x1a"

# tag-drift: claims a field the checked-in descriptors never declare
_REQUEST_GHOST_TAG = b"\x22"  # ExpertRequest.ghost = 4
