# MUST-pass fixture for wire-drift: every hand-rolled tag carries its
# `# Message.field = N` annotation and the bytes match varint((N << 3) | wt).
_REQUEST_UID_TAG = b"\x0a"  # ExpertRequest.uid = 1
_REQUEST_METADATA_TAG = b"\x1a"  # ExpertRequest.metadata = 3
