# net.typo is soaked but the engine declares no such point (phantom).
DEFAULT_SCHEDULE = (
    ("dht.rpc_drop", 0.1),
    ("net.stall", 0.1),
    ("net.typo", 0.1),
)
