# An inject() literal naming a point the engine never declared (unknown).
CHAOS.inject("net.bogus")
