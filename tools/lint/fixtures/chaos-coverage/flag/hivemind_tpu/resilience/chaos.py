# MUST-flag fixture: net.ghost is declared but neither documented nor soaked.
INJECTION_POINTS = (
    "dht.rpc_drop",
    "net.stall",
    "net.ghost",
)
