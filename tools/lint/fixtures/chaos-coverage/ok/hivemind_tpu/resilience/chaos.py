# MUST-pass fixture: every declared point is documented AND soaked.
INJECTION_POINTS = (
    "dht.rpc_drop",
    "net.stall",
)
