DEFAULT_SCHEDULE = (
    ("dht.rpc_drop", 0.1),
    ("net.stall", 0.1),
)
