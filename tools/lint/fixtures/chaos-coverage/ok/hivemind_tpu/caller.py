CHAOS.inject("net.stall")
