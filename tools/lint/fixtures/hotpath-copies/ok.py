"""MUST-pass fixture for ``hotpath-copies``: scatter-gather framing and
explicit-copy astype."""


def frame(header, payload):
    return [header.pack(), payload]  # scatter-gather: writev sends both


def convert(array, dtype):
    return array.astype(dtype, copy=False)
