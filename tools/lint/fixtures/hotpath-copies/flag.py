"""MUST-flag fixture for ``hotpath-copies``: the two copy shapes that cost
~30% of averaging throughput before ISSUE 6/10 removed them."""


def frame(header, payload):
    return header.pack() + payload  # doubles every megabyte payload


def convert(array, dtype):
    return array.astype(dtype)  # copies even when dtype already matches
