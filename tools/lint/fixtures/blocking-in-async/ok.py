"""MUST-pass fixture for ``blocking-in-async``: the approved loop-friendly
counterparts, plus blocking IO inside a nested SYNC def (the standard
run-in-executor target shape)."""

import asyncio


def _read_blocking(path):
    with open(path) as f:  # sync def: an executor target, not on the loop
        return f.read()


async def polite(path, run_in_executor):
    await asyncio.sleep(0.1)
    data = await run_in_executor(_read_blocking, path)
    reader, writer = await asyncio.open_connection("host", 1)
    return data, reader, writer
