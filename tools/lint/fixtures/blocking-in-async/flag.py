"""MUST-flag fixture for ``blocking-in-async``: each call stalls the swarm's
shared event loop (the ISSUE 8 watchdog catches these at runtime; the lint
keeps them from being written)."""

import socket
import time


async def stalls_the_loop(path):
    time.sleep(0.1)
    data = open(path).read()
    conn = socket.create_connection(("host", 1))
    return data, conn
