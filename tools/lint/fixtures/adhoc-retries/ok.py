"""MUST-pass fixture for ``adhoc-retries``: narrow exception types and
log-and-count handlers are the approved shapes."""

import logging
import time

logger = logging.getLogger(__name__)


def risky():
    raise RuntimeError


def narrow_swallow():
    try:
        risky()
    except ValueError:
        pass  # narrow type: a deliberate, reviewable decision


def logged_loop():
    while True:
        try:
            return risky()
        except Exception as exc:
            logger.warning(f"retrying after {exc!r}")  # visible, countable
        time.sleep(1.0)
