"""MUST-flag fixture for ``adhoc-retries``: the pre-ISSUE-3 shapes that hid
real faults before the resilience layer existed."""

import time


def risky():
    raise RuntimeError


def swallow():
    try:
        risky()
    except Exception:
        pass


def retry_loop():
    while True:
        try:
            return risky()
        except Exception:
            pass
        time.sleep(1.0)
