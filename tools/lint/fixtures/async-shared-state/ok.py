"""MUST-pass fixture for ``async-shared-state``: a single post-await mutation
(atomic under the GIL), a lock-guarded update, and a plain rebind."""


class Matchmaker:
    async def join(self, peer, rpc):
        reply = await rpc(peer)
        self.followers[peer] = reply  # one mutation: nothing to interleave with
        return reply

    async def drain(self, queue):
        while True:
            item = await queue.get()
            async with self.lock:
                self.pending.append(item)  # lock-guarded: exempt

    async def refresh(self, rpc):
        self.snapshot = await rpc()  # plain rebind is atomic, never an event
