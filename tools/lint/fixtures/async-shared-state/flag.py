"""MUST-flag fixture for ``async-shared-state``: the matchmaking
``current_followers`` race shape — a ``self.*`` container mutated on both
sides of an RPC await (another coroutine interleaves in between), and a
counter bumped inside a loop that awaits."""


class Matchmaker:
    async def join(self, peer, rpc):
        self.followers[peer] = "pending"
        reply = await rpc(peer)
        self.followers[peer] = reply  # straddles the await: interleaving clobbers
        return reply

    async def drain(self, queue):
        while True:
            item = await queue.get()
            self.pending.append(item)  # mutation spans awaits across iterations
