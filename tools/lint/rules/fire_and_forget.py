"""Rule ``fire-and-forget``: no dropped ``create_task``/``ensure_future``.

New in ISSUE 16. A task whose handle is thrown away is garbage-collectable
mid-flight (asyncio keeps only a weak reference) and — worse — swallows its
exception until interpreter shutdown prints an opaque "Task exception was
never retrieved". The relay accept-loop and matchmaking key-refresh both
dropped task handles this way; a crashed accept loop looked like a silent
relay capacity loss.

Flagged shape (kind ``dropped-task``): an EXPRESSION STATEMENT whose value is
a ``create_task``/``ensure_future`` call — the handle is neither stored,
awaited, gathered, nor given a done-callback.

The approved pattern is :func:`hivemind_tpu.utils.asyncio_utils.spawn`,
which keeps a strong reference, names the task, and logs + counts failures
(``hivemind_background_task_errors_total{site}``).
"""

from __future__ import annotations

import ast
from typing import List

from lint.engine import AstRule, Finding, ParsedModule, ScopedVisitor

_SPAWNERS = {"create_task", "ensure_future"}


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "FireAndForgetRule", module: ParsedModule):
        super().__init__(module)
        self.rule = rule
        self.findings: List[Finding] = []

    def visit_Expr(self, node: ast.Expr):
        call = node.value
        if isinstance(call, ast.Call):
            fn = call.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
            if name in _SPAWNERS:
                self.findings.append(self.rule.finding(
                    self.module.relpath, node.lineno, self.qualname(), "dropped-task",
                    f"{name}(...) result discarded — the task is weakly referenced and its "
                    f"exception is swallowed; use utils.asyncio_utils.spawn(coro, name=...) "
                    f"or store the handle and await/cancel it",
                ))
        self.generic_visit(node)


class FireAndForgetRule(AstRule):
    name = "fire-and-forget"
    title = "every spawned task is stored, awaited, or tracked via spawn()"
    rationale = (
        "Dropped create_task handles let background loops die silently (relay accept "
        "loop, matchmaking key refresh): asyncio holds only a weak reference and the "
        "exception surfaces, if ever, as 'Task exception was never retrieved' at exit."
    )

    def check_module(self, module: ParsedModule) -> List[Finding]:
        visitor = _Visitor(self, module)
        visitor.visit(module.tree)
        return visitor.findings
