"""Rule ``blocking-in-async``: no blocking calls inside ``async def`` on the
swarm's shared event loop.

Ported from tools/check_blocking_in_async.py (ISSUE 8 satellite). A single
synchronous call inside a coroutine stalls matchmaking, DHT RPCs and part
streams for the whole process — to the rest of the swarm the peer looks like a
network straggler. Flagged only when the INNERMOST enclosing function is
``async def`` (a nested sync ``def`` is the standard run-in-executor pattern):

- ``time-sleep`` — ``time.sleep(...)``: use ``await asyncio.sleep(...)``.
- ``blocking-io`` — ``open(...)`` / ``.read_text()`` & friends: run_in_executor.
- ``sync-socket`` — ``socket.socket(...)`` etc.: use loop transports.
"""

from __future__ import annotations

import ast
from typing import List

from lint.engine import AstRule, Finding, ParsedModule, ScopedVisitor

_PATHLIKE_IO_METHODS = {"read_text", "read_bytes", "write_text", "write_bytes"}
_SOCKET_BLOCKING_FUNCS = {"socket", "create_connection", "getaddrinfo", "socketpair"}

_ADVICE = {
    "time-sleep": "use `await asyncio.sleep(...)` — time.sleep blocks the whole swarm loop",
    "blocking-io": "move file IO off the loop (run_in_executor in utils/asyncio_utils.py)",
    "sync-socket": "use the loop's transports (open_connection / loop.sock_*) or an executor",
}


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "BlockingInAsyncRule", module: ParsedModule):
        super().__init__(module)
        self.rule = rule
        self.findings: List[Finding] = []
        self._imported_time_sleep = False

    def _record(self, kind: str, lineno: int) -> None:
        self.findings.append(self.rule.finding(
            self.module.relpath, lineno, self.qualname(), kind, _ADVICE[kind]
        ))

    def visit_Call(self, node: ast.Call):
        if self.in_async_function():
            fn = node.func
            if isinstance(fn, ast.Attribute):
                owner = fn.value
                if isinstance(owner, ast.Name):
                    if owner.id == "time" and fn.attr == "sleep":
                        self._record("time-sleep", node.lineno)
                    elif owner.id == "socket" and fn.attr in _SOCKET_BLOCKING_FUNCS:
                        self._record("sync-socket", node.lineno)
                if fn.attr in _PATHLIKE_IO_METHODS:
                    self._record("blocking-io", node.lineno)
            elif isinstance(fn, ast.Name):
                if fn.id == "open":
                    self._record("blocking-io", node.lineno)
                elif fn.id == "sleep" and self._imported_time_sleep:
                    self._record("time-sleep", node.lineno)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "time" and any(alias.name == "sleep" for alias in node.names):
            self._imported_time_sleep = True
        self.generic_visit(node)


class BlockingInAsyncRule(AstRule):
    name = "blocking-in-async"
    title = "no blocking calls inside async def on the swarm loop"
    rationale = (
        "ISSUE 8: the event-loop watchdog caught runtime stalls from synchronous calls "
        "in coroutines (a stalled loop looks like a network straggler to peers); this "
        "keeps new ones from being written at all."
    )
    trees = ("p2p", "dht", "averaging", "moe")

    def check_module(self, module: ParsedModule) -> List[Finding]:
        visitor = _Visitor(self, module)
        visitor.visit(module.tree)
        return visitor.findings
