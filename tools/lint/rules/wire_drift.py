"""Rule ``wire-drift``: the checked-in wire schema matches its generators.

New in ISSUE 16. Two drift axes, both of which corrupt frames silently:

- ``regen-pending`` / ``regen-drift`` — ``tools/regen_proto.py`` evolves the
  FileDescriptorProto and re-renders each ``proto/*_pb2.py``; if evolving the
  checked-in blob would change it, or re-rendering does not reproduce the
  checked-in module byte-for-byte, someone hand-edited a ``_pb2`` or forgot to
  commit a regen. Peers then disagree about the schema revision they claim.
- ``tag-drift`` / ``tag-unverifiable`` — compression/serialization.py
  hand-rolls protobuf field tags (``_TENSOR_BUFFER_TAG = b"\\x0a"``) for the
  zero-copy fast path. Each constant carries a ``# Message.field = N`` comment;
  this rule recomputes ``varint((N << 3) | wire_type)`` from the real
  descriptor and fails on any mismatch — renumbering a proto field without
  updating the fast path would otherwise ship frames the slow path cannot
  parse.

Pure-descriptor work: extracts the ``AddSerializedFile(b"...")`` blob from the
``_pb2`` AST, so nothing heavyweight (jax, the package itself) is imported.
"""

from __future__ import annotations

import ast
import importlib.util
import re
from typing import Dict, List, Optional, Tuple

from lint.engine import Finding, LintContext, ParsedModule, Rule

_TAG_CONST = re.compile(r"^_[A-Z0-9_]*TAG$")
_TAG_COMMENT = re.compile(r"#\s*(\w+)\.(\w+)\s*=\s*(\d+)")

# FieldDescriptorProto.Type -> proto wire type
_WIRETYPE = {
    1: 1, 2: 5, 3: 0, 4: 0, 5: 0, 6: 1, 7: 5, 8: 0, 9: 2, 10: 3,
    11: 2, 12: 2, 13: 0, 14: 0, 15: 5, 16: 1, 17: 0, 18: 0,
}


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _serialized_blob(module: ParsedModule) -> Tuple[Optional[bytes], int]:
    for node in ast.walk(module.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "AddSerializedFile"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, bytes)
        ):
            return node.args[0].value, node.lineno
    return None, 0


def _load_regen_proto(ctx: LintContext):
    path = ctx.repo_root / "tools" / "regen_proto.py"
    if not path.is_file():
        return None
    spec = importlib.util.spec_from_file_location("_hivemind_lint_regen_proto", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


class WireDriftRule(Rule):
    name = "wire-drift"
    title = "checked-in _pb2 modules and hand-rolled field tags match the schema"
    rationale = (
        "the serialization fast path writes protobuf tags by hand for zero-copy "
        "framing; a field renumbered in the .proto without updating the constants "
        "ships frames the canonical parser rejects — and a hand-edited _pb2 makes "
        "peers disagree about the schema revision. Both drifts are invisible to "
        "unit tests that encode and decode with the same build."
    )

    def run(self, ctx: LintContext) -> Tuple[List[Finding], List[str]]:
        findings: List[Finding] = []
        warnings: List[str] = []
        try:
            from google.protobuf import descriptor_pb2
        except ImportError:
            return findings, ["wire-drift: google.protobuf unavailable — rule skipped"]

        # ---- collect every checked-in descriptor -------------------------------
        proto_modules: List[Tuple[ParsedModule, bytes, int]] = []
        for relpath, module in sorted(ctx.modules().items()):
            if not module.path.name.endswith("_pb2.py"):
                continue
            blob, lineno = _serialized_blob(module)
            if blob is None:
                warnings.append(f"wire-drift: no AddSerializedFile blob in {relpath} — skipped")
                continue
            proto_modules.append((module, blob, lineno))

        # ---- regen idempotence -------------------------------------------------
        regen = _load_regen_proto(ctx)
        if regen is None:
            if proto_modules:
                warnings.append("wire-drift: tools/regen_proto.py missing — idempotence check skipped")
        else:
            for module, blob, lineno in proto_modules:
                if module.path.stem != "averaging_pb2":
                    continue  # regen_proto regenerates only the averaging schema
                file_proto = descriptor_pb2.FileDescriptorProto.FromString(blob)
                changed = regen.evolve(file_proto)
                if changed:
                    findings.append(self.finding(
                        module.relpath, lineno, "<module>", "regen-pending",
                        f"regen_proto.evolve would change {changed} thing(s) — the "
                        f"checked-in descriptor lags the generator; rerun tools/regen_proto.py",
                    ))
                    continue
                stem = module.path.stem  # e.g. "averaging_pb2"
                module_name = f"{ctx.package_root.name}.proto.{stem}"
                rendered = regen.render_pb2(
                    descriptor_pb2.FileDescriptorProto.FromString(blob), module_name
                )
                if rendered != module.source:
                    findings.append(self.finding(
                        module.relpath, lineno, "<module>", "regen-drift",
                        f"re-rendering the descriptor does not reproduce {module.relpath} "
                        f"byte-for-byte — hand-edited _pb2 or stale regen; rerun tools/regen_proto.py",
                    ))

        # ---- hand-rolled tag constants ----------------------------------------
        serialization = ctx.module(ctx.package_relpath("compression/serialization.py"))
        if serialization is None:
            return findings, warnings

        fields: Dict[str, Dict[str, object]] = {}

        def collect(message) -> None:
            fields.setdefault(message.name, {})
            for field in message.field:
                fields[message.name][field.name] = field
            for nested in message.nested_type:
                collect(nested)

        for _, blob, _ in proto_modules:
            file_proto = descriptor_pb2.FileDescriptorProto.FromString(blob)
            for message in file_proto.message_type:
                collect(message)

        for node in serialization.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _TAG_CONST.match(node.targets[0].id)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, bytes)
            ):
                continue
            const_name = node.targets[0].id
            tag_bytes = node.value.value
            line = serialization.lines[node.lineno - 1]
            match = _TAG_COMMENT.search(line)
            if match is None:
                findings.append(self.finding(
                    serialization.relpath, node.lineno, "<module>", "tag-unverifiable",
                    f"{const_name} has no `# Message.field = N` comment — the lint "
                    f"cannot tie this wire tag to a proto field; annotate it",
                ))
                continue
            message_name, field_name, claimed_number = match.group(1), match.group(2), int(match.group(3))
            field = fields.get(message_name, {}).get(field_name)
            if field is None:
                findings.append(self.finding(
                    serialization.relpath, node.lineno, "<module>", "tag-drift",
                    f"{const_name} claims {message_name}.{field_name} but no such field "
                    f"exists in the checked-in descriptors",
                ))
                continue
            expected = _varint((field.number << 3) | _WIRETYPE[field.type])
            if field.number != claimed_number or tag_bytes != expected:
                findings.append(self.finding(
                    serialization.relpath, node.lineno, "<module>", "tag-drift",
                    f"{const_name} = {tag_bytes!r} but {message_name}.{field_name} is "
                    f"field {field.number} (wire type {_WIRETYPE[field.type]}) — "
                    f"expected {expected!r}; the fast path would ship unparseable frames",
                ))
        return findings, warnings
