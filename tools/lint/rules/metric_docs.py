"""Rule ``metric-docs``: every registered ``hivemind_*`` metric is documented.

Ported from tools/check_metric_docs.py (ISSUE 9). docs/observability.md is the
operator's metric catalog and it drifted once (a queue-depth gauge documented
under a wrong name):

- ``undocumented-metric`` — a ``.counter("hivemind_...")`` / ``.gauge`` /
  ``.histogram`` registration whose name never appears in the catalog.
- ``dynamic-metric-name`` — a registry registration whose first argument is
  not a string literal (uncatalogable).

Stale catalog rows (documented but registered nowhere) are warnings, so the
catalog shrinks with the code.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from lint.engine import Finding, LintContext, Rule

_REGISTER_METHODS = {"counter", "gauge", "histogram"}
_DOC_TABLE_NAME = re.compile(r"^\|\s*`(hivemind_[a-z0-9_]+)`")

# documented names that are rendered, not registered (the exporter appends
# _total to counters / _bucket/_sum/_count to histograms at scrape time)
_RENDERED_SUFFIXES = ("_total", "_bucket", "_sum", "_count")

DOC_PATH = "docs/observability.md"


class MetricDocsRule(Rule):
    name = "metric-docs"
    title = "every registered hivemind_* metric appears in docs/observability.md"
    rationale = (
        "ISSUE 9: the operator catalog documented a queue-depth gauge under a wrong "
        "name — a dashboard built from the doc silently read nothing."
    )

    def run(self, ctx: LintContext) -> Tuple[List[Finding], List[str]]:
        names: Dict[str, List[Tuple[str, int]]] = {}
        findings: List[Finding] = []
        for module in ctx.modules().values():
            for node in ast.walk(module.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTER_METHODS
                    and node.args
                ):
                    continue
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    if first.value.startswith("hivemind_"):
                        names.setdefault(first.value, []).append((module.relpath, node.lineno))
                elif isinstance(first, ast.Constant):
                    continue  # literal non-string: not a metric registration
                else:
                    # .counter(variable) — only flag when the receiver LOOKS like a
                    # registry (the watchdog re-registers via <metric>.documentation)
                    receiver = node.func.value
                    receiver_name = getattr(receiver, "id", getattr(receiver, "attr", ""))
                    if str(receiver_name).lower().endswith(("registry", "telemetry")) or (
                        str(receiver_name) == "REGISTRY"
                    ):
                        findings.append(self.finding(
                            module.relpath, node.lineno, "<module>", "dynamic-metric-name",
                            f"dynamic metric name in .{node.func.attr}(...): metric names "
                            f"must be string literals so the catalog lint can see them",
                        ))
        doc_text = ctx.read_text(DOC_PATH) or ""
        for metric_name, sites in sorted(names.items()):
            if metric_name not in doc_text:
                relpath, lineno = sites[0]
                findings.append(self.finding(
                    relpath, lineno, "<module>", "undocumented-metric",
                    f"metric {metric_name!r} is not in {DOC_PATH} — add it to the catalog",
                ))
        warnings: List[str] = []
        registered: Set[str] = set(names)
        table_names = {
            match.group(1)
            for line in doc_text.splitlines()
            for match in [_DOC_TABLE_NAME.match(line.strip())]
            if match is not None
        }
        for doc_name in sorted(table_names):
            candidates = {doc_name} | {
                doc_name[: -len(suffix)] for suffix in _RENDERED_SUFFIXES if doc_name.endswith(suffix)
            }
            if not candidates & registered:
                warnings.append(
                    f"{DOC_PATH} catalogs {doc_name!r} but nothing registers it "
                    f"(stale entry or typo'd name — the drift this rule exists to catch)"
                )
        return findings, warnings
