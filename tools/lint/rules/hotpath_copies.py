"""Rule ``hotpath-copies``: keep the averaging AND serving hot paths copy-free.

Ported from tools/check_hotpath_copies.py (ISSUE 6; serving coverage ISSUE 10).
Scans only the named hot-path files:

- ``bytes-concat`` — a ``+`` whose operand is recognizably bytes: on the frame
  path this doubles megabyte payloads; use scatter-gather framing.
- ``copy-astype`` — ``.astype(...)`` without an explicit ``copy=``: astype
  copies even when the dtype already matches.
"""

from __future__ import annotations

import ast
from typing import List

from lint.engine import AstRule, Finding, ParsedModule, ScopedVisitor

_BYTES_PRODUCING_METHODS = {"pack", "tobytes", "SerializeToString", "to_bytes"}


def _is_bytes_typed(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _BYTES_PRODUCING_METHODS:
            return True
        if isinstance(fn, ast.Name) and fn.id == "bytes":
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _is_bytes_typed(node.left) or _is_bytes_typed(node.right)
    return False


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "HotpathCopiesRule", module: ParsedModule):
        super().__init__(module)
        self.rule = rule
        self.findings: List[Finding] = []

    def visit_BinOp(self, node: ast.BinOp):
        if isinstance(node.op, ast.Add) and (
            _is_bytes_typed(node.left) or _is_bytes_typed(node.right)
        ):
            self.findings.append(self.rule.finding(
                self.module.relpath, node.lineno, self.qualname(), "bytes-concat",
                "pass buffers scatter-gather (send_frame/SecureChannel.send varargs)",
            ))
            # one finding per outermost concat chain: do not descend further
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "astype":
            if not any(keyword.arg == "copy" for keyword in node.keywords):
                self.findings.append(self.rule.finding(
                    self.module.relpath, node.lineno, self.qualname(), "copy-astype",
                    "spell out astype(..., copy=False) on the hot path",
                ))
        self.generic_visit(node)


class HotpathCopiesRule(AstRule):
    name = "hotpath-copies"
    title = "no byte concats or implicit-copy astype in hot-path files"
    rationale = (
        "ISSUE 6/10: per-part byte concats and always-copy astype calls cost ~30% of "
        "averaging throughput before they were removed; this keeps them out."
    )
    files = (
        "p2p/mux.py",
        "p2p/crypto_channel.py",
        "averaging/partition.py",
        "averaging/allreduce.py",
        "averaging/residual.py",
        "compression/quantization.py",
        "moe/client/expert.py",
        "moe/server/connection_handler.py",
        "moe/server/task_pool.py",
    )

    def check_module(self, module: ParsedModule) -> List[Finding]:
        visitor = _Visitor(self, module)
        visitor.visit(module.tree)
        return visitor.findings
