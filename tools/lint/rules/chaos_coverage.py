"""Rule ``chaos-coverage``: injection points, the resilience doc, and the soak
schedule agree — in both directions.

New in ISSUE 16. The chaos layer has three mirrors that historically drifted
independently: ``resilience/chaos.py`` declares ``INJECTION_POINTS``,
``docs/resilience.md`` catalogs them for operators, and
``hivemind_cli/run_chaos_soak.py`` exercises them in ``DEFAULT_SCHEDULE``. A
point added to the engine but never soaked is untested resilience theater; a
doc row for a point that no longer exists sends an operator hunting a ghost.

Kinds (point name embedded so allowlisting stays per-point):

- ``undocumented:<point>`` — declared but absent from docs/resilience.md.
- ``unexercised:<point>`` — declared but absent from DEFAULT_SCHEDULE.
- ``phantom:<point>``     — soaked but not declared (schedule typo).
- ``stale-doc:<token>``   — a backticked dotted token in the doc that LOOKS
  like an injection point (known first segment) but matches none.
- ``unknown:<literal>``   — a ``CHAOS.inject("...")`` call-site literal that
  is not a declared point (non-literal first args are skipped; the engine
  validates those at runtime).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from lint.engine import Finding, LintContext, Rule

DOC_PATH = "docs/resilience.md"
_DOC_TOKEN = re.compile(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`")


def _string_tuple(node: ast.AST) -> Optional[List[Tuple[str, int]]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for element in node.elts:
        if isinstance(element, ast.Constant) and isinstance(element.value, str):
            out.append((element.value, element.lineno))
    return out


class ChaosCoverageRule(Rule):
    name = "chaos-coverage"
    title = "INJECTION_POINTS ↔ docs/resilience.md ↔ DEFAULT_SCHEDULE stay in sync"
    rationale = (
        "a chaos point that exists but is never soaked is untested resilience "
        "theater, and a documented point that no longer exists sends operators "
        "hunting ghosts — the three mirrors drifted whenever a point was added "
        "to only one of them."
    )

    def run(self, ctx: LintContext) -> Tuple[List[Finding], List[str]]:
        findings: List[Finding] = []
        warnings: List[str] = []

        chaos_rel = ctx.package_relpath("resilience/chaos.py")
        chaos = ctx.module(chaos_rel)
        if chaos is None:
            return findings, ["chaos-coverage: resilience/chaos.py not found — rule skipped"]

        points: List[Tuple[str, int]] = []
        for node in chaos.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "INJECTION_POINTS"
            ):
                points = _string_tuple(node.value) or []
        if not points:
            return findings, ["chaos-coverage: INJECTION_POINTS not found in resilience/chaos.py"]
        declared = {point for point, _ in points}

        # ---- declared ↔ documented --------------------------------------------
        doc_text = ctx.read_text(DOC_PATH)
        if doc_text is None:
            warnings.append(f"chaos-coverage: {DOC_PATH} not found — doc checks skipped")
        else:
            for point, lineno in points:
                if point not in doc_text:
                    findings.append(self.finding(
                        chaos_rel, lineno, "<module>", f"undocumented:{point}",
                        f"injection point {point!r} is not cataloged in {DOC_PATH}",
                    ))
            prefixes = {point.split(".")[0] for point in declared}
            doc_lines = doc_text.splitlines()
            seen_tokens = set()
            for doc_lineno, line in enumerate(doc_lines, start=1):
                if not line.lstrip().startswith("|"):
                    continue  # prose may name spans/metrics; only CATALOG rows are the contract
                for match in _DOC_TOKEN.finditer(line):
                    token = match.group(1)
                    if token in seen_tokens or token.split(".")[0] not in prefixes:
                        continue
                    seen_tokens.add(token)
                    # a token may be a point PREFIX used in wildcard-ish prose
                    # ("state.download") — only exact-looking full points count
                    if token in declared or any(p.startswith(token + ".") for p in declared):
                        continue
                    findings.append(self.finding(
                        DOC_PATH, doc_lineno, "<doc>", f"stale-doc:{token}",
                        f"{DOC_PATH} names {token!r} like an injection point but the "
                        f"engine declares no such point — stale row or typo",
                    ))

        # ---- declared ↔ soaked ------------------------------------------------
        soak_rel = ctx.package_relpath("hivemind_cli/run_chaos_soak.py")
        soak = ctx.module(soak_rel)
        if soak is None:
            warnings.append("chaos-coverage: hivemind_cli/run_chaos_soak.py not found — soak checks skipped")
        else:
            schedule: List[Tuple[str, int]] = []
            schedule_lineno = 1
            for node in soak.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "DEFAULT_SCHEDULE"
                    and isinstance(node.value, (ast.Tuple, ast.List))
                ):
                    schedule_lineno = node.lineno
                    for entry in node.value.elts:
                        if (
                            isinstance(entry, (ast.Tuple, ast.List))
                            and entry.elts
                            and isinstance(entry.elts[0], ast.Constant)
                            and isinstance(entry.elts[0].value, str)
                        ):
                            schedule.append((entry.elts[0].value, entry.elts[0].lineno))
            if not schedule:
                warnings.append("chaos-coverage: DEFAULT_SCHEDULE not found in run_chaos_soak.py")
            soaked = {point for point, _ in schedule}
            for point, lineno in points:
                if schedule and point not in soaked:
                    findings.append(self.finding(
                        soak_rel, schedule_lineno, "<module>", f"unexercised:{point}",
                        f"injection point {point!r} is declared but DEFAULT_SCHEDULE never "
                        f"exercises it — the soak proves nothing about it",
                    ))
            for point, lineno in schedule:
                if point not in declared:
                    findings.append(self.finding(
                        soak_rel, lineno, "<module>", f"phantom:{point}",
                        f"DEFAULT_SCHEDULE exercises {point!r} but the engine declares no "
                        f"such point — the rule silently never fires",
                    ))

        # ---- call-site literals ------------------------------------------------
        for module in ctx.modules().values():
            for node in ast.walk(module.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "inject"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    literal = node.args[0].value
                    if literal not in declared:
                        findings.append(self.finding(
                            module.relpath, node.lineno, "<module>", f"unknown:{literal}",
                            f"CHAOS.inject({literal!r}) names an undeclared injection point",
                        ))
        return findings, warnings
