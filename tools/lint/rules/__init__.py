"""Rule registry: the full 10-rule hivemind-lint suite (ISSUE 16; jit-in-hot-path
added by ISSUE 19).

Four ported from the old standalone checkers (tools/check_*.py, now deleted),
six new analyzers. Order here is display order."""

from lint.rules.adhoc_retries import AdhocRetriesRule
from lint.rules.async_shared_state import AsyncSharedStateRule
from lint.rules.blocking_in_async import BlockingInAsyncRule
from lint.rules.chaos_coverage import ChaosCoverageRule
from lint.rules.fire_and_forget import FireAndForgetRule
from lint.rules.hotpath_copies import HotpathCopiesRule
from lint.rules.jit_in_hot_path import JitInHotPathRule
from lint.rules.metric_docs import MetricDocsRule
from lint.rules.missing_deadline import MissingDeadlineRule
from lint.rules.wire_drift import WireDriftRule

ALL_RULES = (
    AdhocRetriesRule,
    BlockingInAsyncRule,
    HotpathCopiesRule,
    JitInHotPathRule,
    MetricDocsRule,
    AsyncSharedStateRule,
    FireAndForgetRule,
    MissingDeadlineRule,
    WireDriftRule,
    ChaosCoverageRule,
)

_BY_NAME = {rule_cls.name: rule_cls for rule_cls in ALL_RULES}
assert len(_BY_NAME) == len(ALL_RULES), "duplicate rule names"


def get_rule(name: str):
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(f"unknown rule {name!r}; known: {sorted(_BY_NAME)}") from None
