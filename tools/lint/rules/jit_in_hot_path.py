"""Rule ``jit-in-hot-path``: no untracked ``jax.jit`` inside hot-path bodies.

A ``jax.jit(...)`` call that executes inside a function body under the
``moe``/``averaging``/``optim`` trees builds a FRESH jitted callable — and a
fresh compile cache — every time that function runs. Two failure modes, both
seen in this repo's history (ISSUE 19):

- a per-call jit recompiles on every invocation: the 79-241 µs optimizer step
  becomes a multi-second step, silently;
- even a jit that is stashed on ``self`` bypasses compile accounting, so
  ``hivemind_device_compiles_total`` and the recompile-storm detector never
  see it.

The sanctioned homes for ``jax.jit``:

- module scope (compiled once at import);
- ``__init__`` (one-time per-object setup — though ``tracked_jit`` is still
  preferred so the compile is counted);
- an ``lru_cache``/``cache``-decorated factory (one jit per static key);
- :func:`hivemind_tpu.utils.profiling.tracked_jit`, which wraps ``jax.jit``
  with per-site compile accounting and is what hot paths should use.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from lint.engine import AstRule, Finding, ParsedModule, ScopedVisitor

_CACHE_DECORATORS = {"lru_cache", "cache", "cached_property"}


def _decorator_name(node: ast.AST) -> Optional[str]:
    """Terminal name of a decorator: ``functools.lru_cache(maxsize=1)`` ->
    ``lru_cache``."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _jit_aliases(tree: ast.Module) -> Set[str]:
    """Bare names that are jax's jit in this module (``from jax import jit``)."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in ("jax", "jax.experimental.pjit"):
            for alias in node.names:
                if alias.name in ("jit", "pjit"):
                    aliases.add(alias.asname or alias.name)
    return aliases


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "JitInHotPathRule", module: ParsedModule):
        super().__init__(module)
        self.rule = rule
        self.findings: List[Finding] = []
        self.aliases = _jit_aliases(module.tree)
        self._func_nodes: List[ast.AST] = []

    # track the actual function nodes (ScopedVisitor only keeps names) so the
    # exemptions can read the innermost function's name and decorators
    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._func_nodes.append(node)
        super().visit_FunctionDef(node)
        self._func_nodes.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._func_nodes.append(node)
        super().visit_AsyncFunctionDef(node)
        self._func_nodes.pop()

    def _exempt_scope(self) -> bool:
        if not self._func_nodes:
            return True  # module/class scope: compiled once at import
        innermost = self._func_nodes[-1]
        if innermost.name == "__init__":
            return True  # one-time per-object setup
        return any(
            _decorator_name(decorator) in _CACHE_DECORATORS
            for decorator in innermost.decorator_list
        )

    def _is_jit(self, fn: ast.AST) -> bool:
        if isinstance(fn, ast.Attribute) and fn.attr in ("jit", "pjit"):
            # dotted chain rooted at `jax`: jax.jit, jax.experimental.pjit.pjit
            root = fn.value
            while isinstance(root, ast.Attribute):
                root = root.value
            return isinstance(root, ast.Name) and root.id == "jax"
        return isinstance(fn, ast.Name) and fn.id in self.aliases

    def visit_Call(self, node: ast.Call):
        if self._is_jit(node.func) and not self._exempt_scope():
            self.findings.append(self.rule.finding(
                self.module.relpath, node.lineno, self.qualname(), "inline-jit",
                "jax.jit inside a hot-path function body recompiles per call and "
                "bypasses compile accounting — use utils.profiling.tracked_jit"
                "(site=...), or hoist to module/__init__ scope / an lru_cache "
                "factory",
            ))
        self.generic_visit(node)


class JitInHotPathRule(AstRule):
    name = "jit-in-hot-path"
    title = "no untracked jax.jit inside moe/averaging/optim function bodies"
    rationale = (
        "ISSUE 19: an inline jax.jit rebuilds its compile cache every call — a "
        "silent 1000x step-time regression — and even a stashed one is invisible "
        "to hivemind_device_compiles_total and the recompile-storm detector."
    )
    trees = ("moe", "averaging", "optim")

    def check_module(self, module: ParsedModule) -> List[Finding]:
        visitor = _Visitor(self, module)
        visitor.visit(module.tree)
        return visitor.findings
