"""Rule ``adhoc-retries``: keep failure handling in the resilience layer.

Ported from tools/check_adhoc_retries.py (ISSUE 3 satellite). Flags, everywhere
under the package EXCEPT ``resilience/``:

- ``swallow`` — a bare/broad ``except`` whose body is exactly ``pass``: silent
  failure handling. Log + count, or narrow the exception type.
- ``retry-loop`` — a loop that both sleeps and swallows broad exceptions to
  keep looping: a hand-rolled retry. Use
  :class:`hivemind_tpu.resilience.RetryPolicy`.
"""

from __future__ import annotations

import ast
from typing import List

from lint.engine import AstRule, Finding, ParsedModule, ScopedVisitor


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Name) and handler.type.id in ("Exception", "BaseException"):
        return True
    if isinstance(handler.type, ast.Tuple):
        return any(
            isinstance(element, ast.Name) and element.id in ("Exception", "BaseException")
            for element in handler.type.elts
        )
    return False


def _is_sleep_call(node: ast.AST) -> bool:
    call = node.value if isinstance(node, ast.Await) else node
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "sleep"
        and isinstance(fn.value, ast.Name)
        and fn.value.id in ("asyncio", "time")
    )


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "AdhocRetriesRule", module: ParsedModule):
        super().__init__(module)
        self.rule = rule
        self.findings: List[Finding] = []

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if _broad_handler(node) and len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
            self.findings.append(self.rule.finding(
                self.module.relpath, node.lineno, self.qualname(), "swallow",
                "broad `except: pass` — log + count instead of silently passing",
            ))
        self.generic_visit(node)

    def _visit_loop(self, node):
        sleeps = any(_is_sleep_call(child) for child in ast.walk(node))
        swallows_to_loop = False
        for child in ast.walk(node):
            if not isinstance(child, ast.Try):
                continue
            for handler in child.handlers:
                if not _broad_handler(handler):
                    continue
                # "keep looping silently" shapes: pass / continue only — a handler
                # that logs and counts before continuing is the approved pattern
                if all(isinstance(stmt, (ast.Pass, ast.Continue)) for stmt in handler.body):
                    swallows_to_loop = True
        if sleeps and swallows_to_loop:
            self.findings.append(self.rule.finding(
                self.module.relpath, node.lineno, self.qualname(), "retry-loop",
                "hand-rolled retry loop — use RetryPolicy from hivemind_tpu.resilience",
            ))
        self.generic_visit(node)

    visit_While = visit_For = visit_AsyncFor = _visit_loop


class AdhocRetriesRule(AstRule):
    name = "adhoc-retries"
    title = "failure handling stays in the resilience layer"
    rationale = (
        "ISSUE 3: scattered bare `except: pass` and hand-rolled sleep-and-retry loops hid "
        "real faults before the RetryPolicy/breaker layer existed; this keeps them out."
    )
    exclude_trees = ("resilience",)

    def check_module(self, module: ParsedModule) -> List[Finding]:
        visitor = _Visitor(self, module)
        visitor.visit(module.tree)
        return visitor.findings
