"""Rule ``async-shared-state``: read-modify-write on ``self.*`` must not span
an ``await``.

New in ISSUE 16. An ``await`` is a scheduling point: any other coroutine may
run and see — or clobber — shared state mid-update. The matchmaking
``current_followers``/``assembled`` races (ISSUE 3 era) were exactly this
shape: a ``self.<dict>`` mutated before an RPC await and again after it, with
a second coroutine interleaving in between.

Per ``async def``, we collect mutation events of ``self.<attr>`` containers
and counters:

- ``self.attr += ...`` / ``self.attr -= ...`` (counter read-modify-write),
- ``self.attr[k] = ...`` / ``del self.attr[k]`` / ``self.attr[k] += ...``,
- mutator method calls: ``self.attr.append/add/update/pop/...``.

An attribute is flagged (kind ``interleaved:<attr>``) when its mutations
straddle at least one await point, or sit inside a loop that also awaits
(the mutation spans awaits across iterations). Mutations inside a
``with``/``async with`` whose context manager looks like a lock
(``*lock*``/``*mutex*``/``*cond*``/``*sem*`` in the expression) are exempt,
as is anything on a line or block annotated ``# lint: single-writer``
(engine-level alias for ``# lint: allow(async-shared-state)``).

Plain rebinds (``self.attr = x``) are NOT events: a single assignment is
atomic under the GIL and flagging every post-await rebind drowns the signal.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from lint.engine import AstRule, Finding, ParsedModule

_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "put_nowait",
}
_LOCKLIKE = re.compile(r"lock|mutex|cond|sem", re.IGNORECASE)


def _self_attr(node: ast.AST) -> str:
    """'attr' when node is ``self.attr``, else ''."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _looks_like_lock(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        name = ""
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and _LOCKLIKE.search(name):
            return True
    return False


class _FunctionScan:
    """Order-sensitive walk of ONE async def body (nested defs skipped).

    Tracks the await counter, lock-guard depth, and whether we are inside a
    loop whose body awaits; records per-attribute mutation events."""

    def __init__(self) -> None:
        self.awaits_seen = 0
        self._lock_depth = 0
        self._awaiting_loop_depth = 0
        # attr -> list of (awaits_seen_at_mutation, inside_awaiting_loop, lineno)
        self.events: Dict[str, List[Tuple[int, bool, int]]] = {}

    def _record(self, attr: str, lineno: int) -> None:
        if self._lock_depth > 0 or not attr:
            return
        self.events.setdefault(attr, []).append(
            (self.awaits_seen, self._awaiting_loop_depth > 0, lineno)
        )

    def _contains_await(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.AsyncFunctionDef, ast.FunctionDef, ast.Lambda)) and sub is not node:
                continue
            if isinstance(sub, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
        return False

    def scan(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested function: its own coroutine frame, not this one
        if isinstance(node, ast.Await):
            self.awaits_seen += 1
            self._scan_children(node)
            return
        if isinstance(node, ast.AugAssign):
            # self.attr += 1  /  self.attr[k] += 1
            target = node.target
            self._record(_self_attr(target), node.lineno)
            if isinstance(target, ast.Subscript):
                self._record(_self_attr(target.value), node.lineno)
            self._scan_children(node)
            return
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    self._record(_self_attr(target.value), node.lineno)
            self._scan_children(node)
            return
        if isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    self._record(_self_attr(target.value), node.lineno)
            self._scan_children(node)
            return
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                self._record(_self_attr(fn.value), node.lineno)
            self._scan_children(node)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locked = any(_looks_like_lock(item.context_expr) for item in node.items)
            for item in node.items:
                self.scan(item.context_expr)
            if isinstance(node, ast.AsyncWith):
                self.awaits_seen += 1  # __aenter__ is an await point
            if locked:
                self._lock_depth += 1
            for stmt in node.body:
                self.scan(stmt)
            if locked:
                self._lock_depth -= 1
            return
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            awaiting_loop = isinstance(node, ast.AsyncFor) or self._contains_await(node)
            if isinstance(node, ast.For):
                self.scan(node.iter)
            elif isinstance(node, ast.AsyncFor):
                self.scan(node.iter)
                self.awaits_seen += 1  # each __anext__ is an await point
            else:
                self.scan(node.test)
            if awaiting_loop:
                self._awaiting_loop_depth += 1
            for stmt in node.body:
                self.scan(stmt)
            if awaiting_loop:
                self._awaiting_loop_depth -= 1
                self.awaits_seen += 1  # loop body awaited at least once notionally
            for stmt in node.orelse:
                self.scan(stmt)
            return
        self._scan_children(node)

    def _scan_children(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self.scan(child)


class AsyncSharedStateRule(AstRule):
    name = "async-shared-state"
    title = "self.* container/counter mutations must not straddle an await"
    rationale = (
        "The matchmaking group-assembly races: self.<dict> mutated before an RPC await "
        "and again after it let a second coroutine interleave and corrupt the group "
        "roster. Any read-modify-write spanning a scheduling point is this bug."
    )
    trees = ("p2p", "dht", "averaging", "moe", "optim", "sim")

    def check_module(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []
        scope: List[str] = []

        def walk(node: ast.AST) -> None:
            if isinstance(node, ast.ClassDef):
                scope.append(node.name)
                for child in node.body:
                    walk(child)
                scope.pop()
                return
            if isinstance(node, ast.AsyncFunctionDef):
                scope.append(node.name)
                scan = _FunctionScan()
                for stmt in node.body:
                    scan.scan(stmt)
                qualname = ".".join(scope)
                for attr, events in sorted(scan.events.items()):
                    counts = [awaits for awaits, _, _ in events]
                    looped = any(in_loop for _, in_loop, _ in events)
                    if looped or min(counts) < max(counts):
                        lineno = min(line for _, _, line in events)
                        findings.append(self.finding(
                            module.relpath, lineno, qualname, f"interleaved:{attr}",
                            f"self.{attr} is mutated across an await point in {qualname} — "
                            f"another coroutine can interleave mid-update; hold an "
                            f"asyncio.Lock or mark the line `# lint: single-writer`",
                        ))
                # nested defs are skipped by the scan (own coroutine frame) but
                # still deserve their own analysis
                def nested(sub: ast.AST) -> None:
                    for child in ast.iter_child_nodes(sub):
                        if isinstance(child, (ast.AsyncFunctionDef, ast.FunctionDef, ast.ClassDef)):
                            walk(child)
                        else:
                            nested(child)

                for stmt in node.body:
                    if isinstance(stmt, (ast.AsyncFunctionDef, ast.FunctionDef, ast.ClassDef)):
                        walk(stmt)
                    else:
                        nested(stmt)
                scope.pop()
                return
            if isinstance(node, ast.FunctionDef):
                scope.append(node.name)
                for child in node.body:
                    walk(child)
                scope.pop()
                return
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(module.tree)
        return findings
