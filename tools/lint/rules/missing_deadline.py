"""Rule ``missing-deadline``: network-layer awaits must be reachable from a
deadline.

New in ISSUE 16. An RPC await with no timeout anywhere in scope hangs forever
when the remote peer stalls instead of dying — the replication state fetch did
exactly this: it ACCEPTED a ``chunk_timeout`` parameter and then never applied
it, so a stalled donor wedged the fetch coroutine permanently.

Flagged shape (kind ``no-deadline``): a call to a network primitive
(``call_protobuf_handler`` / ``iterate_protobuf_handler``) inside a function
whose body shows NO deadline machinery at all. "Deadline machinery" is any of:

- a ``timeout=``/``deadline=``-style keyword on some call in the body,
- a load of a name or attribute matching ``*timeout*``/``*deadline*``,
- a call to ``asyncio.wait_for`` / ``aiter_with_timeout``.

Deliberately coarse: one timeout mention anywhere in the body clears the whole
function. That keeps false positives near zero while still catching the real
bug class — a *signature* parameter alone does NOT count (an accepted-but-
unused ``chunk_timeout`` is precisely the defect this rule exists to find).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Sequence, Tuple

from lint.engine import AstRule, Finding, ParsedModule

_NETWORK_CALLS = {"call_protobuf_handler", "iterate_protobuf_handler"}
_DEADLINE_NAME = re.compile(r"timeout|deadline", re.IGNORECASE)
_DEADLINE_FUNCS = {"wait_for", "aiter_with_timeout"}

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _walk_own_body(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """Every node in these statements EXCLUDING nested def/class subtrees
    (they get their own deadline scope and their own findings)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _DEFS):
            continue  # yielded so the caller records it, but never entered
        for child in ast.iter_child_nodes(node):
            stack.append(child)


class MissingDeadlineRule(AstRule):
    name = "missing-deadline"
    title = "network RPC awaits are reachable from a timeout"
    rationale = (
        "replication.fetch_replica_state accepted chunk_timeout and never used it — a "
        "stalled donor wedged the fetch forever. Peers fail by stalling, not only by "
        "dying; every network await needs a deadline in scope."
    )

    def check_module(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []

        def check_function(func: ast.AST, qualname: str) -> None:
            nested: List[Tuple[ast.AST, str]] = []
            network_calls: List[ast.Call] = []
            has_deadline = False
            for node in _walk_own_body(func.body):
                if isinstance(node, _DEFS):
                    nested.append((node, f"{qualname}.{node.name}"))
                    continue
                if isinstance(node, ast.Name) and _DEADLINE_NAME.search(node.id):
                    has_deadline = True
                elif isinstance(node, ast.Attribute) and _DEADLINE_NAME.search(node.attr):
                    has_deadline = True
                elif isinstance(node, ast.keyword) and node.arg and _DEADLINE_NAME.search(node.arg):
                    has_deadline = True
                elif isinstance(node, ast.Call):
                    name = _call_name(node)
                    if name in _DEADLINE_FUNCS:
                        has_deadline = True
                    elif name in _NETWORK_CALLS:
                        network_calls.append(node)
            if not has_deadline:
                for call in network_calls:
                    findings.append(self.finding(
                        module.relpath, call.lineno, qualname, "no-deadline",
                        f"{_call_name(call)}(...) with no timeout anywhere in "
                        f"{qualname} — wrap in asyncio.wait_for / pass a timeout so a "
                        f"stalled peer cannot wedge this coroutine",
                    ))
            for sub, sub_qualname in nested:
                descend(sub, sub_qualname)

        def descend(node: ast.AST, qualname: str) -> None:
            if isinstance(node, ast.ClassDef):
                for child in node.body:
                    if isinstance(child, _DEFS):
                        descend(child, f"{qualname}.{child.name}")
            else:
                check_function(node, qualname)

        for top in module.tree.body:
            if isinstance(top, _DEFS):
                descend(top, top.name)
        return findings
