"""hivemind-lint: the unified static-analysis suite (ISSUE 16).

One AST-walk engine (`lint.engine`), ten rules (`lint.rules`), one console
entry point (`hivemind-lint`, `lint.cli`) and one tier-1 pytest entry
(tests/test_lint_suite.py). Rules share:

- a single parse of every package module (`LintContext`),
- in-source suppression: ``# lint: allow(<rule>[, <rule>...])`` on the flagged
  line, or on a ``def``/``class`` line to cover the whole block
  (``# lint: single-writer`` is an alias for ``allow(async-shared-state)``),
- per-rule allowlist files under ``tools/lint/allowlists/<rule>.conf`` where
  every entry must carry a one-line justification,
- ``--json`` output consumed by bench.py so lint debt lands in BENCH artifacts.

See docs/static_analysis.md for the rule catalog and policy.
"""

from lint.engine import Finding, LintContext, RuleResult, SuiteResult, run_suite
from lint.rules import ALL_RULES, get_rule

__all__ = [
    "ALL_RULES",
    "Finding",
    "LintContext",
    "RuleResult",
    "SuiteResult",
    "get_rule",
    "run_suite",
]
