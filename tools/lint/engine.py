"""The shared AST-walk engine behind every hivemind-lint rule (ISSUE 16).

Replaces the four bespoke walkers that used to live in tools/check_*.py: one
parse per module, one suppression syntax, one allowlist format, one runner.

Key objects:

- :class:`LintContext` — parses every ``*.py`` under the package root exactly
  once and hands rules :class:`ParsedModule` objects (tree + source + the
  in-source suppressions already extracted).
- :class:`Rule` / :class:`AstRule` — a rule declares its scope (subtrees or an
  explicit file list) and returns raw :class:`Finding` objects; the runner
  applies suppressions and allowlists centrally, so no rule reimplements them.
- :func:`run_suite` — runs rules, partitions findings into violations /
  suppressed / allowlisted, reports stale allowlist entries, and times each
  rule (the whole 10-rule suite must stay under the tier-1 budget).

Findings are keyed ``(repo-relative path, enclosing qualname, kind)`` — stable
across line-number churn, same convention the old checkers used.
"""

from __future__ import annotations

import ast
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
ALLOWLIST_DIR = Path(__file__).resolve().parent / "allowlists"

# `# lint: allow(rule-a, rule-b)` — suppress on this line (or this whole block
# when the comment sits on a def/class line). `# lint: single-writer` is the
# async-shared-state annotation from the rule's docstring: "this attribute has
# exactly one writing coroutine by design".
_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")
_SINGLE_WRITER_RE = re.compile(r"#\s*lint:\s*single-writer\b")


@dataclass(frozen=True)
class Finding:
    rule: str
    relpath: str  # repo-relative path, e.g. "hivemind_tpu/p2p/relay.py"
    lineno: int
    qualname: str  # enclosing function/class dotted path, or "<module>"
    kind: str
    message: str

    @property
    def key(self) -> str:
        """Line-number-free identity used by allowlist files."""
        return f"{self.relpath}:{self.qualname}:{self.kind}"

    def render(self) -> str:
        return f"{self.relpath}:{self.lineno} [{self.rule}/{self.kind}] in {self.qualname} — {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {
            "rule": self.rule, "path": self.relpath, "line": self.lineno,
            "qualname": self.qualname, "kind": self.kind, "message": self.message,
        }


class ParsedModule:
    """One parsed source file: tree, lines, and extracted suppressions."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        self.lines = source.splitlines()
        self._line_allow: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(self.lines, start=1):
            allowed: Set[str] = set()
            match = _ALLOW_RE.search(line)
            if match:
                allowed |= {part.strip() for part in match.group(1).split(",") if part.strip()}
            if _SINGLE_WRITER_RE.search(line):
                allowed.add("async-shared-state")
            if allowed:
                self._line_allow[lineno] = allowed
        # a suppression on a def/class line covers that whole block
        self._block_allow: List[Tuple[int, int, Set[str]]] = []
        if self._line_allow:
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    rules = self._line_allow.get(node.lineno)
                    if rules:
                        self._block_allow.append((node.lineno, node.end_lineno or node.lineno, rules))

    def is_suppressed(self, rule: str, lineno: int) -> bool:
        line_rules = self._line_allow.get(lineno)
        if line_rules and rule in line_rules:
            return True
        for start, end, rules in self._block_allow:
            if start <= lineno <= end and rule in rules:
                return True
        return False

    def suppression_count(self, rule: str) -> int:
        """How many in-source suppressions name this rule (tracked as lint debt)."""
        total = sum(1 for rules in self._line_allow.values() if rule in rules)
        return total


class LintContext:
    """Parses the package once; every rule reads from the same cache."""

    def __init__(self, repo_root: Path = REPO_ROOT, package_root: Optional[Path] = None):
        self.repo_root = Path(repo_root)
        self.package_root = Path(package_root) if package_root is not None else self.repo_root / "hivemind_tpu"
        self._modules: Optional[Dict[str, ParsedModule]] = None

    def _relpath(self, path: Path) -> str:
        try:
            return str(path.relative_to(self.repo_root))
        except ValueError:
            return str(path)

    def modules(self) -> Dict[str, ParsedModule]:
        """Every package module, keyed by repo-relative path."""
        if self._modules is None:
            self._modules = {}
            for path in sorted(self.package_root.rglob("*.py")):
                if "__pycache__" in path.parts:
                    continue
                relpath = self._relpath(path)
                self._modules[relpath] = ParsedModule(path, relpath, path.read_text())
        return self._modules

    def module(self, relpath: str) -> Optional[ParsedModule]:
        return self.modules().get(relpath)

    def package_relpath(self, package_file: str) -> str:
        """Repo-relative path of a package-relative file ("p2p/mux.py")."""
        return self._relpath(self.package_root / package_file)

    def select_modules(
        self,
        trees: Optional[Sequence[str]] = None,
        files: Optional[Sequence[str]] = None,
        exclude_trees: Sequence[str] = (),
    ) -> List[ParsedModule]:
        """Rule scoping: explicit package-relative files, or package subtrees
        (``None`` = the whole package), minus excluded subtrees."""
        if files is not None:
            out = []
            for package_file in files:
                module = self.module(self.package_relpath(package_file))
                if module is not None:
                    out.append(module)
            return out
        selected = []
        for module in self.modules().values():
            parts = module.path.relative_to(self.package_root).parts
            if parts and parts[0] in exclude_trees:
                continue
            if trees is not None and (not parts or parts[0] not in trees):
                continue
            selected.append(module)
        return selected

    def read_text(self, repo_relative: str) -> Optional[str]:
        path = self.repo_root / repo_relative
        if not path.is_file():
            return None
        return path.read_text()


class Rule:
    """Base: a named analyzer. ``run`` returns RAW findings; suppression and
    allowlisting are the runner's job."""

    name: str = ""
    title: str = ""
    rationale: str = ""  # the historical bug class this rule exists to prevent

    def run(self, ctx: LintContext) -> List[Finding]:
        raise NotImplementedError

    def finding(self, relpath: str, lineno: int, qualname: str, kind: str, message: str) -> Finding:
        return Finding(self.name, relpath, lineno, qualname, kind, message)


class AstRule(Rule):
    """A rule that walks module ASTs. Scope via ``trees`` (package subtrees),
    ``files`` (explicit package-relative paths) or neither (whole package)."""

    trees: Optional[Tuple[str, ...]] = None
    files: Optional[Tuple[str, ...]] = None
    exclude_trees: Tuple[str, ...] = ()

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in ctx.select_modules(self.trees, self.files, self.exclude_trees):
            findings.extend(self.check_module(module))
        return findings

    def check_module(self, module: ParsedModule) -> List[Finding]:
        raise NotImplementedError


class ScopedVisitor(ast.NodeVisitor):
    """Shared qualname/async-scope tracking (what every old checker re-rolled)."""

    def __init__(self, module: ParsedModule):
        self.module = module
        self._scope: List[str] = []
        self._func_kind: List[str] = []  # "async" | "sync" | "class"

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._enter(node.name, "sync")
        self.generic_visit(node)
        self._exit()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._enter(node.name, "async")
        self.generic_visit(node)
        self._exit()

    def visit_ClassDef(self, node: ast.ClassDef):
        self._enter(node.name, "class")
        self.generic_visit(node)
        self._exit()

    def _enter(self, name: str, kind: str) -> None:
        self._scope.append(name)
        self._func_kind.append(kind)

    def _exit(self) -> None:
        self._scope.pop()
        self._func_kind.pop()

    def in_async_function(self) -> bool:
        """True when the innermost enclosing FUNCTION is async (classes are
        transparent — a method counts by its own kind)."""
        for kind in reversed(self._func_kind):
            if kind == "class":
                continue
            return kind == "async"
        return False

    def qualname(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"


# --------------------------------------------------------------------- allowlists


@dataclass
class AllowlistEntry:
    key: str  # relpath:qualname:kind
    justification: str


def load_allowlist(rule_name: str, allowlist_dir: Path = ALLOWLIST_DIR) -> Dict[str, AllowlistEntry]:
    """``tools/lint/allowlists/<rule>.conf``: one entry per line,
    ``<path>:<qualname>:<kind>  <justification>``. A justification is REQUIRED —
    zero silent grandfathering (ISSUE 16 satellite)."""
    path = allowlist_dir / f"{rule_name}.conf"
    entries: Dict[str, AllowlistEntry] = {}
    if not path.is_file():
        return entries
    for raw_line in path.read_text().splitlines():
        line = raw_line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, justification = line.partition("  ")
        justification = justification.strip()
        if not justification:
            raise ValueError(
                f"{path.name}: allowlist entry {key!r} has no justification — every "
                f"grandfathered finding must say why (two spaces separate key from reason)"
            )
        entries[key.strip()] = AllowlistEntry(key.strip(), justification)
    return entries


# --------------------------------------------------------------------- runner


@dataclass
class RuleResult:
    rule: Rule
    violations: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    allowlisted: List[Finding] = field(default_factory=list)
    stale_allowlist: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)
    duration_s: float = 0.0

    def to_json(self, include_findings: bool = True) -> Dict[str, object]:
        out: Dict[str, object] = {
            "violations": len(self.violations),
            "suppressed": len(self.suppressed),
            "allowlisted": len(self.allowlisted),
            "stale_allowlist": len(self.stale_allowlist),
            "warnings": len(self.warnings),
            "duration_s": round(self.duration_s, 4),
        }
        if include_findings:
            out["findings"] = [finding.to_json() for finding in self.violations]
        return out


@dataclass
class SuiteResult:
    results: List[RuleResult]
    duration_s: float

    @property
    def total_violations(self) -> int:
        return sum(len(result.violations) for result in self.results)

    @property
    def ok(self) -> bool:
        return self.total_violations == 0

    def to_json(self, include_findings: bool = True) -> Dict[str, object]:
        return {
            "ok": self.ok,
            "total_violations": self.total_violations,
            "total_suppressed": sum(len(r.suppressed) for r in self.results),
            "total_allowlisted": sum(len(r.allowlisted) for r in self.results),
            "duration_s": round(self.duration_s, 3),
            "rules": {
                result.rule.name: result.to_json(include_findings) for result in self.results
            },
        }


def run_rule(rule: Rule, ctx: LintContext, allowlist_dir: Path = ALLOWLIST_DIR) -> RuleResult:
    started = time.perf_counter()
    raw = rule.run(ctx)
    allowlist = load_allowlist(rule.name, allowlist_dir)
    result = RuleResult(rule=rule)
    if isinstance(raw, tuple):  # project rules may return (findings, warnings)
        raw, result.warnings = raw[0], list(raw[1])
    seen_keys: Set[str] = set()
    for finding in raw:
        seen_keys.add(finding.key)
        module = ctx.modules().get(finding.relpath)
        if module is not None and module.is_suppressed(rule.name, finding.lineno):
            result.suppressed.append(finding)
        elif finding.key in allowlist:
            result.allowlisted.append(finding)
        else:
            result.violations.append(finding)
    result.stale_allowlist = sorted(set(allowlist) - seen_keys)
    result.duration_s = time.perf_counter() - started
    return result


def run_suite(
    rules: Optional[Iterable[Rule]] = None,
    ctx: Optional[LintContext] = None,
    allowlist_dir: Path = ALLOWLIST_DIR,
) -> SuiteResult:
    from lint.rules import ALL_RULES

    if rules is None:
        rules = [rule_cls() for rule_cls in ALL_RULES]
    ctx = ctx if ctx is not None else LintContext()
    started = time.perf_counter()
    results = [run_rule(rule, ctx, allowlist_dir) for rule in rules]
    return SuiteResult(results=results, duration_s=time.perf_counter() - started)
