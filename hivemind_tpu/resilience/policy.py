"""Composable retry and deadline policies — the one place failure-handling
*shape* is decided (ISSUE 3 tentpole).

Before this module, every layer hand-rolled its own loops: p2p retried dials,
the DHT retried via its blacklist, matchmaking slept ad-hoc jittered intervals,
and the MoE client kept three separate ``for attempt in range(...)`` loops.
Each had its own backoff curve and its own bugs. A :class:`RetryPolicy` is a
small immutable value describing *when to retry and how long to wait*; call
sites either run a callable through :meth:`RetryPolicy.execute` /
:meth:`RetryPolicy.execute_sync` or pull :meth:`RetryPolicy.delay` into an
existing loop they cannot invert.

:class:`Deadline` replaces stacked independent ``asyncio.wait_for`` timeouts
with ONE remaining-time budget that shrinks as it propagates through nested
awaits — three sequential 5 s waits under a 10 s budget can no longer add up
to 15 s of worst-case latency.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional, Tuple, Type, TypeVar

from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import current_span as _current_span

T = TypeVar("T")

_RETRIES = _TELEMETRY.counter(
    "hivemind_resilience_retries_total", "retries performed by named RetryPolicy sites", ("site",)
)


class DeadlineExceeded(asyncio.TimeoutError):
    """The remaining-time budget ran out. Subclasses ``asyncio.TimeoutError`` so
    every existing ``except asyncio.TimeoutError`` failure path handles it."""


class Deadline:
    """A monotonic remaining-time budget. ``Deadline(None)`` is unlimited.

    The object is cheap and immutable; pass it DOWN through nested calls so
    that each layer waits at most what the whole operation has left.
    """

    __slots__ = ("_expires_at",)

    def __init__(self, seconds: Optional[float] = None, *, _expires_at: Optional[float] = None):
        if _expires_at is not None:
            self._expires_at = _expires_at
        else:
            self._expires_at = None if seconds is None else time.monotonic() + seconds

    @classmethod
    def after(cls, seconds: Optional[float]) -> "Deadline":
        return cls(seconds)

    @classmethod
    def unlimited(cls) -> "Deadline":
        return cls(None)

    @property
    def expired(self) -> bool:
        return self._expires_at is not None and time.monotonic() >= self._expires_at

    def remaining(self) -> Optional[float]:
        """Seconds left, clamped at 0.0; None means unlimited."""
        if self._expires_at is None:
            return None
        return max(0.0, self._expires_at - time.monotonic())

    def remaining_or(self, cap: float) -> float:
        """Seconds left capped at ``cap`` (the per-step timeout a call would have
        used standalone): nested waits use ``min(step_timeout, whole_budget)``."""
        remaining = self.remaining()
        return cap if remaining is None else min(cap, remaining)

    def require(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(f"deadline expired before {what}")

    async def wait_for(self, awaitable: Awaitable[T], cap: Optional[float] = None) -> T:
        """``asyncio.wait_for`` bounded by this budget (and optionally ``cap``).
        Raises :class:`DeadlineExceeded` if the budget is already spent."""
        remaining = self.remaining()
        if remaining is None:
            timeout = cap
        else:
            if remaining <= 0.0:
                # the awaitable may be a coroutine that was never scheduled: close
                # it instead of leaking a "never awaited" warning
                if asyncio.iscoroutine(awaitable):
                    awaitable.close()
                raise DeadlineExceeded("deadline expired before wait")
            timeout = remaining if cap is None else min(cap, remaining)
        try:
            return await asyncio.wait_for(awaitable, timeout=timeout)
        except asyncio.TimeoutError:
            if self.expired:
                raise DeadlineExceeded("deadline expired during wait") from None
            raise

    def __repr__(self) -> str:
        remaining = self.remaining()
        return f"Deadline(remaining={'inf' if remaining is None else f'{remaining:.3f}s'})"


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter, attempt caps, and retryable-exception
    predicates.

    :param max_attempts: total attempts including the first; None = unlimited
        (bound it with a ``deadline`` instead)
    :param base_delay: backoff before the first retry
    :param backoff: multiplier per subsequent retry (1.0 = constant interval)
    :param max_delay: ceiling on any single sleep
    :param jitter: ``"full"`` — sleep U(0, d) (best for thundering herds);
        ``"equal"`` — sleep d/2 + U(0, d/2); ``"none"`` — sleep exactly d
    :param retry_on: exception types worth retrying (``CancelledError`` never is)
    :param retry_if: extra predicate over the exception; both must pass
    :param name: when set, each retry increments
        ``hivemind_resilience_retries_total{site=name}``
    """

    max_attempts: Optional[int] = 3
    base_delay: float = 0.1
    backoff: float = 2.0
    max_delay: float = 10.0
    jitter: str = "full"
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    retry_if: Optional[Callable[[BaseException], bool]] = None
    name: Optional[str] = None

    def is_retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, asyncio.CancelledError):
            return False
        if not isinstance(exc, self.retry_on):
            return False
        return self.retry_if is None or bool(self.retry_if(exc))

    def delay(self, retry_index: int, rng: Optional[random.Random] = None) -> float:
        """The sleep before retry number ``retry_index`` (0-based)."""
        raw = min(self.base_delay * (self.backoff ** retry_index), self.max_delay)
        rand = (rng.random() if rng is not None else random.random())
        if self.jitter == "full":
            return raw * rand
        if self.jitter == "equal":
            return raw / 2.0 + raw / 2.0 * rand
        return raw

    def _account_retry(self, retry_index: int = 0, exc: Optional[BaseException] = None) -> None:
        if self.name is not None:
            _RETRIES.inc(site=self.name)
        span = _current_span()
        if span is not None:  # the retried operation's span shows each attempt
            span.add_event(
                "retry",
                site=self.name or "anonymous",
                attempt=retry_index + 1,
                error=type(exc).__name__ if exc is not None else "",
            )

    async def execute(
        self,
        fn: Callable[[], Awaitable[T]],
        *,
        deadline: Optional[Deadline] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        rng: Optional[random.Random] = None,
    ) -> T:
        """Run ``fn`` (a zero-arg async callable), retrying per this policy.
        ``on_retry(retry_index, exc)`` runs before each backoff sleep (the hook
        for re-resolution / cache invalidation between attempts)."""
        retry_index = 0
        while True:
            try:
                return await fn()
            except BaseException as e:
                if not self.is_retryable(e):
                    raise
                if self.max_attempts is not None and retry_index + 1 >= self.max_attempts:
                    raise
                if deadline is not None and deadline.expired:
                    raise
                self._account_retry(retry_index, e)
                if on_retry is not None:
                    on_retry(retry_index, e)
                sleep = self.delay(retry_index, rng)
                if deadline is not None:
                    sleep = deadline.remaining_or(sleep)
                await asyncio.sleep(sleep)
                retry_index += 1

    def execute_sync(
        self,
        fn: Callable[[], T],
        *,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> T:
        """Blocking-world twin of :meth:`execute` (the MoE client's pure_callback
        bodies run on executor threads, not the event loop)."""
        retry_index = 0
        while True:
            try:
                return fn()
            except BaseException as e:
                if not self.is_retryable(e):
                    raise
                if self.max_attempts is not None and retry_index + 1 >= self.max_attempts:
                    raise
                self._account_retry(retry_index, e)
                if on_retry is not None:
                    on_retry(retry_index, e)
                sleep(self.delay(retry_index, rng))
                retry_index += 1
