"""First-class deterministic fault injection (ISSUE 3 tentpole).

Fault injection used to exist only as test-local subclasses
(``FaultyAllReduceRunner`` / ``FaultyAverager``); this module makes it a
subsystem: production code carries **named injection points** that are free
when disabled (one attribute check) and, when armed, consult a seeded schedule
— no wall-clock randomness, so a failing chaos run replays exactly.

Named injection points (the call sites pass ``scope=<local peer id or expert
uid>`` so multi-peer-in-one-process tests can fault exactly one peer):

==========================  ====================================================
point                       where it fires
==========================  ====================================================
``p2p.unary.send``          client side, before a unary request leaves
``p2p.unary.recv``          client side, after a unary response arrives
``p2p.stream.send``         client side, before each streamed request message
``p2p.stream.recv``         client side, after each streamed response message
``dht.rpc_ping``            before an outbound DHT ping
``dht.rpc_store``           before an outbound DHT store
``dht.rpc_find``            before an outbound DHT find
``allreduce.setup``         before constructing a round's AllReduceRunner
``allreduce.load``          sender side, per tensor part streamed to a reducer
``allreduce.reduce``        reducer side, per delta returned to a sender
``moe.forward``             per expert forward RPC (scope = expert uid)
``moe.backward``            per expert backward RPC (scope = expert uid)
``state.download.send``     donor side, per state-sync message (scope = donor id)
``state.download.recv``     receiver side, per state-sync message (scope = donor id)
==========================  ====================================================

Actions: ``drop`` (raises :class:`ChaosDrop`, a ``ConnectionError`` — looks
like the network ate it), ``delay`` (sleeps ``delay`` seconds), ``abort``
(raises :class:`ChaosAbort`, a ``RuntimeError`` — looks like a peer crash or
software fault), ``corrupt_payload`` (deterministically flips bytes in the
payload when the point carries one), ``throttle`` (sleeps
``len(payload) / rate`` — a simulated bandwidth-limited WAN link; no-op at
points that carry no payload).

Activation: programmatically (``CHAOS.add_rule(...)`` / ``CHAOS.configure``)
or via ``HIVEMIND_CHAOS`` at import, e.g.::

    HIVEMIND_CHAOS="seed=7;dht.rpc_find:drop:prob=0.2;allreduce.load:delay:delay=0.5:prob=0.1"

Grammar: ``spec = segment (";" segment)*``; a segment is either ``seed=<int>``
or ``<point>:<action>[:key=value]...`` with keys ``prob`` (default 1.0),
``delay`` (seconds, default 0.1), ``rate`` (throttle bandwidth in bytes/s,
default 125e6 ≈ 1 Gbps), ``after`` (skip the first N matching calls),
``times`` (max injections), ``scope`` (substring matched against the call
site's scope). A point may end in ``*`` for prefix matching (``p2p.*``).

Directional link scoping (ISSUE 12): a rule whose scope starts with ``link:``
matches only call sites that identify a directed link, ``scope=link:<src>-><dst>``
— the in-process swarm simulator (hivemind_tpu/sim) tags every simulated RPC
this way. Each side is a peer id pattern with ``*`` wildcards
(``fnmatch``-style), so ``link:*->QmBob*`` throttles everything flowing INTO
one peer while ``link:QmAli*->QmBob*`` faults exactly one direction of one
link. Non-link rules keep substring semantics; because a link scope string
contains both endpoint ids, a plain ``scope=<peer_b58>`` rule matches both
directions of that peer's simulated links — the existing 14-point catalog
composes with per-link schedules unchanged.
"""

from __future__ import annotations

import asyncio
import os
import random
import zlib
from fnmatch import fnmatchcase
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import current_span as _current_span
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CHAOS_INJECTIONS = _TELEMETRY.counter(
    "hivemind_chaos_injections_total", "faults injected by the chaos engine", ("point", "action")
)

INJECTION_POINTS = (
    "p2p.unary.send", "p2p.unary.recv", "p2p.stream.send", "p2p.stream.recv",
    "dht.rpc_ping", "dht.rpc_store", "dht.rpc_find",
    "allreduce.setup", "allreduce.load", "allreduce.reduce",
    "moe.forward", "moe.backward",
    "state.download.send", "state.download.recv",
)

ACTIONS = ("drop", "delay", "abort", "corrupt_payload", "throttle")


class ChaosError(Exception):
    """Base for engine-raised faults (never raised unless chaos is armed)."""


class ChaosDrop(ChaosError, ConnectionError):
    """Injected message loss: call sites see an ordinary ConnectionError."""


class ChaosAbort(ChaosError, RuntimeError):
    """Injected crash/software fault: an unexpected RuntimeError."""


@dataclass
class ChaosRule:
    point: str
    action: str
    prob: float = 1.0
    delay: float = 0.1
    rate: float = 125e6  # throttle bandwidth, bytes/s (default ≈ 1 Gbps)
    after: int = 0
    times: Optional[int] = None
    scope: Optional[str] = None
    rng: random.Random = field(default_factory=random.Random, repr=False)
    calls: int = 0
    hits: int = 0

    def matches(self, point: str, scope: Optional[str]) -> bool:
        if self.point.endswith("*"):
            if not point.startswith(self.point[:-1]):
                return False
        elif point != self.point:
            return False
        if self.scope is not None:
            if scope is None:
                return False
            if self.scope.startswith("link:"):
                return _match_link_scope(self.scope, scope)
            if self.scope not in scope:
                return False
        return True

    def decide(self) -> bool:
        """One deterministic injection decision. Counters make ``after``/``times``
        schedules reproducible; the rule-local rng makes ``prob`` reproducible."""
        index = self.calls
        self.calls += 1
        if index < self.after:
            return False
        if self.times is not None and self.hits >= self.times:
            return False
        if self.prob < 1.0 and self.rng.random() >= self.prob:
            return False
        self.hits += 1
        return True


def _match_link_scope(rule_scope: str, call_scope: str) -> bool:
    """``link:<src_pat>-><dst_pat>`` vs a call site's ``link:<src>-><dst>``.
    Patterns use ``*`` wildcards per side; a call site that carries no link
    identity never matches a link-scoped rule."""
    if not call_scope.startswith("link:"):
        return False
    src_pat, arrow, dst_pat = rule_scope[len("link:"):].partition("->")
    src, call_arrow, dst = call_scope[len("link:"):].partition("->")
    if not arrow or not call_arrow:
        return False
    return fnmatchcase(src, src_pat) and fnmatchcase(dst, dst_pat)


def _rule_seed(seed: int, index: int, point: str, action: str) -> int:
    return zlib.crc32(f"{seed}|{index}|{point}|{action}".encode())


class ChaosEngine:
    """The process-wide fault injector. ``enabled`` is False with no rules, so
    instrumented call sites cost one attribute read in production."""

    def __init__(self, seed: int = 0):
        self._rules: List[ChaosRule] = []
        self._seed = seed
        self.enabled = False

    # ------------------------------------------------------------------ config

    def add_rule(
        self,
        point: str,
        action: str,
        *,
        prob: float = 1.0,
        delay: float = 0.1,
        rate: float = 125e6,
        after: int = 0,
        times: Optional[int] = None,
        scope: Optional[str] = None,
    ) -> ChaosRule:
        assert action in ACTIONS, f"unknown chaos action {action!r} (choose from {ACTIONS})"
        if not point.endswith("*") and point not in INJECTION_POINTS:
            logger.warning(f"chaos rule targets unknown injection point {point!r}")
        rule = ChaosRule(
            point=point, action=action, prob=prob, delay=delay, rate=rate, after=after,
            times=times, scope=scope,
            rng=random.Random(_rule_seed(self._seed, len(self._rules), point, action)),
        )
        self._rules.append(rule)
        self.enabled = True
        return rule

    def configure(self, spec: str, seed: Optional[int] = None) -> None:
        """Parse the ``HIVEMIND_CHAOS`` grammar (see module docstring) into rules.
        Clears existing rules first."""
        self.clear()
        segments = [segment.strip() for segment in spec.split(";") if segment.strip()]
        # the seed segment applies to every rule regardless of position
        for segment in segments:
            if segment.startswith("seed="):
                seed = int(segment[len("seed="):])
        if seed is not None:
            self._seed = seed
        for segment in segments:
            if segment.startswith("seed="):
                continue
            raw = segment.split(":")
            if len(raw) < 2:
                raise ValueError(f"bad chaos segment {segment!r}: need <point>:<action>")
            point, action = raw[0], raw[1]
            # a value may itself contain ":" (scope=link:<src>-><dst>): a part
            # with no "=" re-joins the key=value field it was split off from
            fields: List[str] = []
            for part in raw[2:]:
                if "=" in part or not fields:
                    fields.append(part)
                else:
                    fields[-1] = f"{fields[-1]}:{part}"
            kwargs: Dict[str, object] = {}
            for kv in fields:
                key, _, value = kv.partition("=")
                if key in ("prob", "delay", "rate"):
                    kwargs[key] = float(value)
                elif key in ("after", "times"):
                    kwargs[key] = int(value)
                elif key == "scope":
                    kwargs[key] = value
                else:
                    raise ValueError(f"unknown chaos rule key {key!r} in {segment!r}")
            self.add_rule(point, action, **kwargs)

    def configure_from_env(self, environ=os.environ) -> None:
        spec = environ.get("HIVEMIND_CHAOS")
        if spec:
            self.configure(spec)
            logger.warning(f"HIVEMIND_CHAOS armed: {len(self._rules)} fault rule(s) active")

    def remove_rule(self, rule: ChaosRule) -> None:
        """Retire one rule (e.g. a scenario-scoped fault) leaving the rest armed."""
        self._rules.remove(rule)
        self.enabled = bool(self._rules)

    def clear(self) -> None:
        self._rules = []
        self.enabled = False

    def reseed(self, seed: int) -> None:
        """Set the seed for ALL rules — existing ones get fresh rngs and reset
        counters, so reseed-then-replay is deterministic regardless of whether
        rules were added before or after the call."""
        self._seed = seed
        for index, rule in enumerate(self._rules):
            rule.rng = random.Random(_rule_seed(seed, index, rule.point, rule.action))
            rule.calls = rule.hits = 0

    @property
    def rules(self) -> Tuple[ChaosRule, ...]:
        return tuple(self._rules)

    def stats(self) -> Dict[str, int]:
        """Injections performed so far, keyed ``point:action``."""
        out: Dict[str, int] = {}
        for rule in self._rules:
            key = f"{rule.point}:{rule.action}"
            out[key] = out.get(key, 0) + rule.hits
        return out

    # ------------------------------------------------------------------ injection

    async def inject(self, point: str, payload=None, scope: Optional[str] = None):
        """Consult the schedule at one injection point. Returns the (possibly
        corrupted) payload; may sleep; may raise ChaosDrop / ChaosAbort."""
        for rule in self._rules:
            if not rule.matches(point, scope) or not rule.decide():
                continue
            _CHAOS_INJECTIONS.inc(point=point, action=rule.action)
            # the injected fault becomes visible IN the trace at the exact
            # operation it hit: the active span carries a chaos.<action> event
            span = _current_span()
            if span is not None:
                span.add_event(f"chaos.{rule.action}", point=point)
            if rule.action == "drop":
                raise ChaosDrop(f"chaos: dropped at {point}")
            if rule.action == "abort":
                raise ChaosAbort(f"chaos: aborted at {point}")
            if rule.action == "delay":
                await asyncio.sleep(rule.delay)
            elif rule.action == "throttle":
                # simulated bandwidth-limited link: pay the payload's wire time.
                # Rules on the same link serialize naturally (the call site
                # awaits inline), distinct links throttle independently.
                try:
                    size = len(payload) if payload is not None else 0
                except TypeError:
                    size = 0
                if size and rule.rate > 0:
                    await asyncio.sleep(size / rule.rate)
            elif rule.action == "corrupt_payload":
                payload = self._corrupt(payload, rule.rng)
        return payload

    @staticmethod
    def _corrupt(payload, rng: random.Random):
        # memoryview included: the mux delivers zero-copy views of wire frames
        if isinstance(payload, (bytes, bytearray, memoryview)) and len(payload):
            corrupted = bytearray(payload)
            for _ in range(max(1, len(corrupted) // 256)):
                corrupted[rng.randrange(len(corrupted))] ^= 0xFF
            return bytes(corrupted)
        return payload  # point carries no byte payload: corruption is a no-op


CHAOS = ChaosEngine()
CHAOS.configure_from_env()
