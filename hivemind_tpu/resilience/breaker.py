"""Per-peer circuit breakers: ONE health-score implementation behind every ban
list in the stack (ISSUE 3 tentpole).

Before this module three modules kept independent ad-hoc ban state: the DHT
node's ``Blacklist`` (timed exponential backoff), the MoE client's dead-expert
masking (no memory at all — every batch re-probed every dead expert), and each
all-reduce round's ``banned_senders`` set (permanent within the round). All
three are now :class:`BreakerBoard` instances with different parameters:

========================  ===================  =====================================
consumer                  board name           parameters
========================  ===================  =====================================
DHT node blacklist        ``dht_blacklist``    threshold 1, timed backoff, dht clock
MoE expert blacklist      ``moe_expert``       threshold 2, 30 s recovery, backoff 2x
all-reduce sender bans    ``allreduce_senders``threshold 1, infinite recovery
========================  ===================  =====================================

State machine (classic closed -> open -> half-open):

- **closed**: requests flow; ``failure_threshold`` consecutive failures trip it.
- **open**: requests are refused (``key in board`` is True) until
  ``recovery_time`` elapses; the window doubles (``backoff_rate``) per re-trip,
  capped at ``max_recovery_time``.
- **half-open**: the window elapsed. :meth:`BreakerBoard.allow` admits up to
  ``half_open_max_probes`` concurrent probe requests; a probe success closes the
  breaker (full reset), a probe failure re-opens it with a longer window.
  ``in`` / :meth:`BreakerBoard.is_banned` are PURE reads (half-open reads as
  not-banned) so checking cannot consume probe slots.

Telemetry (registered in the PR-2 registry, docs/observability.md):
``hivemind_breaker_trips_total{board}``, ``hivemind_breaker_tripped{board}``
(tripped = open or awaiting a probe), and
``hivemind_breaker_probe_outcomes_total{board,outcome}``.
"""

from __future__ import annotations

import enum
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Dict, Hashable, Optional

from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import current_span as _current_span

_BREAKER_TRIPS = _TELEMETRY.counter(
    "hivemind_breaker_trips_total", "circuit-breaker trips (-> open)", ("board",)
)
_BREAKER_TRIPPED = _TELEMETRY.gauge(
    "hivemind_breaker_tripped", "breakers currently open or awaiting a probe", ("board",)
)
_BREAKER_PROBES = _TELEMETRY.counter(
    "hivemind_breaker_probe_outcomes_total", "half-open probe outcomes", ("board", "outcome")
)


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class BreakerOpenError(RuntimeError):
    """A request was refused because the target's breaker is open."""


class CircuitBreaker:
    """One protected target. Not thread-safe on its own — the owning
    :class:`BreakerBoard` serializes access."""

    __slots__ = (
        "failure_threshold", "recovery_time", "backoff_rate", "max_recovery_time",
        "half_open_max_probes", "_clock", "_consecutive_failures", "_open_until",
        "_current_recovery", "_probes_admitted", "_last_probe_at", "trip_count",
    )

    def __init__(
        self,
        *,
        failure_threshold: int = 1,
        recovery_time: float = 5.0,
        backoff_rate: float = 2.0,
        max_recovery_time: float = float("inf"),
        half_open_max_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.recovery_time = recovery_time
        self.backoff_rate = backoff_rate
        self.max_recovery_time = max_recovery_time
        self.half_open_max_probes = half_open_max_probes
        self._clock = clock
        self._consecutive_failures = 0
        self._open_until: Optional[float] = None
        self._current_recovery = recovery_time
        self._probes_admitted = 0
        self._last_probe_at: Optional[float] = None
        self.trip_count = 0

    @property
    def state(self) -> BreakerState:
        if self._open_until is None:
            return BreakerState.CLOSED
        if self._clock() < self._open_until:
            return BreakerState.OPEN
        return BreakerState.HALF_OPEN

    @property
    def tripped(self) -> bool:
        """Open or half-open: tripped at some point and not yet closed again."""
        return self._open_until is not None

    def is_banned(self) -> bool:
        """Pure read: True only while hard-open (no side effects, so callers may
        check as often as they like)."""
        return self.state is BreakerState.OPEN

    def allow(self) -> bool:
        """Probe-limited admission: True when a request may proceed. In
        half-open this consumes one of ``half_open_max_probes`` slots. A probe
        that never reports back (cancelled task, crashed caller) must not wedge
        the breaker: once ``recovery_time`` passes since the last admission with
        no verdict, the slots re-open."""
        state = self.state
        if state is BreakerState.CLOSED:
            return True
        if state is BreakerState.OPEN:
            return False
        now = self._clock()
        if (
            self._probes_admitted >= self.half_open_max_probes
            and self._last_probe_at is not None
            and self.recovery_time != float("inf")
            and now - self._last_probe_at >= self.recovery_time
        ):
            self._probes_admitted = 0
        if self._probes_admitted < self.half_open_max_probes:
            self._probes_admitted += 1
            self._last_probe_at = now
            return True
        return False

    def record_failure(self) -> tuple:
        """Returns (tripped_now: bool, probe_outcome: Optional[str])."""
        if self.recovery_time <= 0:
            return False, None  # breaking disabled (Blacklist base_time=0 parity)
        state = self.state
        if state is BreakerState.OPEN:
            return False, None  # in-flight stragglers failing adds no new evidence
        if state is BreakerState.HALF_OPEN:
            self._trip()
            return True, "failure"
        self._consecutive_failures += 1
        if self._consecutive_failures >= self.failure_threshold:
            self._trip()
            return True, None
        return False, None

    def record_success(self) -> Optional[str]:
        """Returns the probe outcome ("success") when this closed a half-open
        breaker, else None."""
        was_half_open = self.state is BreakerState.HALF_OPEN
        self._consecutive_failures = 0
        self._open_until = None
        self._current_recovery = self.recovery_time
        self._probes_admitted = 0
        # a success forgives history entirely (DHT Blacklist parity): the next
        # trip escalates from the base window and ban_counter reads 0
        self.trip_count = 0
        return "success" if was_half_open else None

    def _trip(self) -> None:
        self.trip_count += 1
        self._consecutive_failures = 0
        self._probes_admitted = 0
        self._open_until = self._clock() + self._current_recovery
        self._current_recovery = min(self._current_recovery * self.backoff_rate, self.max_recovery_time)


_ALL_BOARDS: "weakref.WeakSet[BreakerBoard]" = weakref.WeakSet()


class BreakerBoard:
    """A keyed family of :class:`CircuitBreaker` with shared parameters and one
    telemetry identity. Thread-safe. ``key in board`` means *banned right now*
    (pure read); :meth:`allow` is the mutating probe-admission check."""

    def __init__(
        self,
        name: str,
        *,
        maxsize: int = 10_000,
        failure_threshold: int = 1,
        recovery_time: float = 5.0,
        backoff_rate: float = 2.0,
        max_recovery_time: float = float("inf"),
        half_open_max_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.maxsize = maxsize
        self._kwargs = dict(
            failure_threshold=failure_threshold,
            recovery_time=recovery_time,
            backoff_rate=backoff_rate,
            max_recovery_time=max_recovery_time,
            half_open_max_probes=half_open_max_probes,
            clock=clock,
        )
        self._breakers: "OrderedDict[Hashable, CircuitBreaker]" = OrderedDict()
        self._lock = threading.Lock()
        self._tripped_keys: set = set()
        _ALL_BOARDS.add(self)

    # ------------------------------------------------------------------ internals

    def _get(self, key: Hashable, create: bool) -> Optional[CircuitBreaker]:
        breaker = self._breakers.get(key)
        if breaker is None and create:
            breaker = self._breakers[key] = CircuitBreaker(**self._kwargs)
            if len(self._breakers) > self.maxsize:
                self._evict()
        elif breaker is not None:
            self._breakers.move_to_end(key)
        return breaker

    def _evict(self) -> None:
        """Drop oldest non-tripped entries past the cap (tripped ones carry the
        very state the board exists for)."""
        for stale_key in list(self._breakers):
            if len(self._breakers) <= self.maxsize:
                return
            if not self._breakers[stale_key].tripped:
                del self._breakers[stale_key]
        while len(self._breakers) > self.maxsize:  # pathological: everyone tripped
            dropped_key, _ = self._breakers.popitem(last=False)
            self._note_recovered(dropped_key)

    def _note_tripped(self, key: Hashable) -> None:
        if key not in self._tripped_keys:
            self._tripped_keys.add(key)
            _BREAKER_TRIPPED.set(len(self._tripped_keys), board=self.name)

    def _note_recovered(self, key: Hashable) -> None:
        if key in self._tripped_keys:
            self._tripped_keys.discard(key)
            _BREAKER_TRIPPED.set(len(self._tripped_keys), board=self.name)

    # ------------------------------------------------------------------ API

    def register_failure(self, key: Hashable) -> None:
        with self._lock:
            breaker = self._get(key, create=True)
            tripped_now, probe_outcome = breaker.record_failure()
            if tripped_now:
                _BREAKER_TRIPS.inc(board=self.name)
                self._note_tripped(key)
            if probe_outcome is not None:
                _BREAKER_PROBES.inc(board=self.name, outcome=probe_outcome)
        if tripped_now or probe_outcome is not None:
            # trips and failed probes are trace-worthy: the operation that
            # tripped the breaker carries the event on its active span
            span = _current_span()
            if span is not None:
                if tripped_now:
                    span.add_event("breaker.trip", board=self.name, key=str(key))
                if probe_outcome is not None:
                    span.add_event("breaker.probe", board=self.name, key=str(key), outcome=probe_outcome)

    def register_success(self, key: Hashable) -> None:
        with self._lock:
            breaker = self._get(key, create=False)
            if breaker is None:
                return
            probe_outcome = breaker.record_success()
            if probe_outcome is not None:
                _BREAKER_PROBES.inc(board=self.name, outcome=probe_outcome)
            self._note_recovered(key)
        if probe_outcome is not None:
            span = _current_span()
            if span is not None:
                span.add_event("breaker.probe", board=self.name, key=str(key), outcome=probe_outcome)

    def allow(self, key: Hashable) -> bool:
        """Probe-admission check (mutating in half-open): call ONCE per request."""
        with self._lock:
            breaker = self._get(key, create=False)
            return True if breaker is None else breaker.allow()

    def is_banned(self, key: Hashable) -> bool:
        with self._lock:
            breaker = self._breakers.get(key)
            return breaker is not None and breaker.is_banned()

    def __contains__(self, key: Hashable) -> bool:
        return self.is_banned(key)

    def state(self, key: Hashable) -> BreakerState:
        with self._lock:
            breaker = self._breakers.get(key)
            return BreakerState.CLOSED if breaker is None else breaker.state

    def trip_count(self, key: Hashable) -> int:
        with self._lock:
            breaker = self._breakers.get(key)
            return 0 if breaker is None else breaker.trip_count

    @property
    def ban_counter(self) -> Dict[Hashable, int]:
        """Legacy DHT ``Blacklist.ban_counter`` view: key -> times tripped."""
        with self._lock:
            return {key: b.trip_count for key, b in self._breakers.items() if b.trip_count}

    def tripped_keys(self) -> list:
        """Keys currently open or awaiting a probe (the soak's recovery check)."""
        with self._lock:
            return [key for key, b in self._breakers.items() if b.tripped]

    def all_closed(self) -> bool:
        return not self.tripped_keys()

    def reconfigure(self, **overrides) -> None:
        """Change breaker parameters (e.g. shrink recovery_time for a soak) and
        clear — existing breakers carry old parameters, so they are dropped."""
        unknown = set(overrides) - set(self._kwargs)
        assert not unknown, f"unknown breaker parameters: {unknown}"
        self._kwargs.update(overrides)
        self.clear()

    def clear(self) -> None:
        with self._lock:
            self._breakers.clear()
            self._tripped_keys.clear()
            _BREAKER_TRIPPED.set(0, board=self.name)

    def __len__(self) -> int:
        with self._lock:
            return len(self._breakers)

    def __repr__(self) -> str:
        return f"BreakerBoard({self.name!r}, {len(self)} keys, {len(self.tripped_keys())} tripped)"


def reset_all_boards() -> None:
    """Clear every live board (test isolation: boards are often module-level)."""
    for board in list(_ALL_BOARDS):
        board.clear()


def all_board_states() -> Dict[str, Dict[str, object]]:
    """Compact health view of every live board — what the DHT-published peer
    snapshot carries so the swarm monitor can show WHICH peers are degraded,
    not just their counters. Only boards with any tripped key appear."""
    out: Dict[str, Dict[str, object]] = {}
    for board in list(_ALL_BOARDS):
        tripped = [str(key) for key in board.tripped_keys()]
        if tripped:
            out[board.name] = {"tripped": sorted(tripped)[:16], "num_tripped": len(tripped)}
    return out
