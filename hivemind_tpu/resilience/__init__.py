"""Swarm resilience layer (ISSUE 3): unified retry/backoff/deadline policies,
cross-layer circuit breakers, and a deterministic chaos/fault-injection engine.
See docs/resilience.md for the catalog and per-layer failure-propagation table."""

from hivemind_tpu.resilience.breaker import (
    BreakerBoard,
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
    all_board_states,
    reset_all_boards,
)
from hivemind_tpu.resilience.chaos import (
    ACTIONS,
    CHAOS,
    ChaosAbort,
    ChaosDrop,
    ChaosEngine,
    ChaosError,
    INJECTION_POINTS,
)
from hivemind_tpu.resilience.policy import Deadline, DeadlineExceeded, RetryPolicy

__all__ = [
    "ACTIONS",
    "BreakerBoard",
    "BreakerOpenError",
    "BreakerState",
    "CHAOS",
    "ChaosAbort",
    "ChaosDrop",
    "ChaosEngine",
    "ChaosError",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "INJECTION_POINTS",
    "RetryPolicy",
    "all_board_states",
    "reset_all_boards",
]
