"""Structured logging for hivemind_tpu (capability parity with reference hivemind/utils/logging.py).

Env vars: ``HIVEMIND_TPU_LOGLEVEL`` sets the default level, ``HIVEMIND_TPU_COLORS``
forces colors on/off.
"""

import logging
import os
import sys
import threading

_LOCK = threading.Lock()
_INITIALIZED = False

_RESET = "\033[0m"
_COLORS = {
    logging.DEBUG: "\033[36m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}


def _use_colors() -> bool:
    env = os.getenv("HIVEMIND_TPU_COLORS")
    if env is not None:
        return env.lower() in ("1", "true", "yes", "always")
    return sys.stderr.isatty()


class _Formatter(logging.Formatter):
    def __init__(self, colors: bool):
        super().__init__(fmt="%(asctime)s.%(msecs)03d [%(levelname)s] [%(name)s:%(lineno)d] %(message)s",
                         datefmt="%b %d %H:%M:%S")
        self._colors = colors

    def format(self, record: logging.LogRecord) -> str:
        text = super().format(record)
        if self._colors:
            color = _COLORS.get(record.levelno, "")
            if color:
                return f"{color}{text}{_RESET}"
        return text


def _initialize() -> None:
    global _INITIALIZED
    with _LOCK:
        if _INITIALIZED:
            return
        root = logging.getLogger("hivemind_tpu")
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_Formatter(_use_colors()))
        root.addHandler(handler)
        root.propagate = False
        level = os.getenv("HIVEMIND_TPU_LOGLEVEL", "INFO").upper()
        root.setLevel(getattr(logging, level, logging.INFO))
        _INITIALIZED = True


def get_logger(name: str = "hivemind_tpu") -> logging.Logger:
    _initialize()
    if not name.startswith("hivemind_tpu"):
        name = f"hivemind_tpu.{name}"
    return logging.getLogger(name)


def set_loglevel(level: str) -> None:
    _initialize()
    logging.getLogger("hivemind_tpu").setLevel(level.upper())
