"""A minimal drop-in for the subset of the ``cryptography`` package this codebase
uses, backed by the system's libcrypto (OpenSSL >= 1.1.1) over ctypes.

Some deployment images ship no ``cryptography`` wheel (no Rust toolchain, hermetic
python), but every one of them has OpenSSL's libcrypto — the native relay daemon
already dlopens it for the very same primitives (native/relay_daemon.cpp,
relay_crypto::load). The import sites gate on ``cryptography`` first and fall back
here, so behavior is identical wherever the real package exists.

Covered surface (exactly what utils/crypto.py, p2p/crypto_channel.py and
p2p/relay.py touch):

- ``exceptions.InvalidSignature`` / ``exceptions.InvalidTag``
- ``ed25519.Ed25519PrivateKey`` / ``Ed25519PublicKey`` (raw bytes, sign/verify)
- ``x25519.X25519PrivateKey`` / ``X25519PublicKey`` (raw bytes, exchange)
- ``ChaCha20Poly1305`` AEAD (RFC 7539: ciphertext || 16-byte tag)
- ``HKDF`` (SHA-256; pure hmac/hashlib — no libcrypto needed)
- ``rsa`` 2048 keygen + PSS-SHA256 sign/verify, DER (PKCS8 / SubjectPublicKeyInfo)
- the ``hashes`` / ``serialization`` / ``padding`` marker namespaces those calls
  pass around (Encoding.Raw etc. are accepted and validated loosely)

Everything is one-shot EVP with a per-call context, so the shim is thread-safe the
same way the real package is.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import hashlib
import hmac as _hmac
from typing import Optional


class InvalidSignature(Exception):
    pass


class InvalidTag(Exception):
    pass


class _Exceptions:
    InvalidSignature = InvalidSignature
    InvalidTag = InvalidTag


exceptions = _Exceptions()

# ------------------------------------------------------------------ libcrypto


def _load_libcrypto() -> ctypes.CDLL:
    candidates = []
    found = ctypes.util.find_library("crypto")
    if found:
        candidates.append(found)
    candidates += ["libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so", "libcrypto.dylib"]
    last_error: Optional[Exception] = None
    for name in candidates:
        try:
            lib = ctypes.CDLL(name)
            lib.EVP_PKEY_new_raw_private_key  # >= 1.1.1 required (Ed25519 raw keys)
            return lib
        except (OSError, AttributeError) as e:
            last_error = e
    raise ImportError(
        f"neither the 'cryptography' package nor a usable libcrypto (OpenSSL >= 1.1.1) "
        f"is available: {last_error!r}"
    )


_lib = _load_libcrypto()

_lib.EVP_PKEY_new_raw_private_key.restype = ctypes.c_void_p
_lib.EVP_PKEY_new_raw_private_key.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
_lib.EVP_PKEY_new_raw_public_key.restype = ctypes.c_void_p
_lib.EVP_PKEY_new_raw_public_key.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
_lib.EVP_PKEY_get_raw_private_key.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t)]
_lib.EVP_PKEY_get_raw_public_key.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t)]
_lib.EVP_PKEY_free.argtypes = [ctypes.c_void_p]
_lib.EVP_PKEY_CTX_new_id.restype = ctypes.c_void_p
_lib.EVP_PKEY_CTX_new_id.argtypes = [ctypes.c_int, ctypes.c_void_p]
_lib.EVP_PKEY_CTX_new.restype = ctypes.c_void_p
_lib.EVP_PKEY_CTX_new.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
_lib.EVP_PKEY_CTX_free.argtypes = [ctypes.c_void_p]
_lib.EVP_PKEY_keygen_init.argtypes = [ctypes.c_void_p]
_lib.EVP_PKEY_keygen.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
_lib.EVP_PKEY_CTX_ctrl_str.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
_lib.EVP_PKEY_derive_init.argtypes = [ctypes.c_void_p]
_lib.EVP_PKEY_derive_set_peer.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
_lib.EVP_PKEY_derive.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t)]
_lib.EVP_MD_CTX_new.restype = ctypes.c_void_p
_lib.EVP_MD_CTX_free.argtypes = [ctypes.c_void_p]
_lib.EVP_DigestSignInit.argtypes = [
    ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
]
_lib.EVP_DigestVerifyInit.argtypes = [
    ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
]
_lib.EVP_DigestSign.argtypes = [
    ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t,
]
_lib.EVP_DigestVerify.argtypes = [
    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
]
_lib.EVP_sha256.restype = ctypes.c_void_p
_lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
_lib.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
_lib.EVP_chacha20_poly1305.restype = ctypes.c_void_p
_lib.EVP_CipherInit_ex.argtypes = [
    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
]
# the output parameter is void* (not char*) so multi-part sealing can write each
# piece at an offset into one ciphertext buffer via addressof()+offset
_lib.EVP_CipherUpdate.argtypes = [
    ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int,
]
_lib.EVP_CipherFinal_ex.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int)]
_lib.EVP_CIPHER_CTX_ctrl.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
_lib.EVP_PKEY2PKCS8.restype = ctypes.c_void_p
_lib.EVP_PKEY2PKCS8.argtypes = [ctypes.c_void_p]
_lib.PKCS8_PRIV_KEY_INFO_free.argtypes = [ctypes.c_void_p]
_lib.i2d_PKCS8_PRIV_KEY_INFO.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte))]
_lib.d2i_PKCS8_PRIV_KEY_INFO.restype = ctypes.c_void_p
_lib.d2i_PKCS8_PRIV_KEY_INFO.argtypes = [
    ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)), ctypes.c_long,
]
_lib.EVP_PKCS82PKEY.restype = ctypes.c_void_p
_lib.EVP_PKCS82PKEY.argtypes = [ctypes.c_void_p]
_lib.i2d_PUBKEY.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte))]
_lib.d2i_PUBKEY.restype = ctypes.c_void_p
_lib.d2i_PUBKEY.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)), ctypes.c_long]
# OPENSSL_free is a macro over CRYPTO_free(ptr, file, line)
_lib.CRYPTO_free.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
_lib.CRYPTO_free.restype = None


def _openssl_free(ptr) -> None:
    _lib.CRYPTO_free(ptr, b"_libcrypto.py", 0)

_EVP_PKEY_X25519 = 1034  # NID_X25519
_EVP_PKEY_ED25519 = 1087  # NID_ED25519
_EVP_PKEY_RSA = 6
_EVP_CTRL_AEAD_SET_IVLEN = 0x9
_EVP_CTRL_AEAD_GET_TAG = 0x10
_EVP_CTRL_AEAD_SET_TAG = 0x11


def _check(ok: int, what: str) -> None:
    if ok != 1:
        raise ValueError(f"libcrypto: {what} failed")


class _PKey:
    """Owns one EVP_PKEY*."""

    def __init__(self, handle: int):
        if not handle:
            raise ValueError("libcrypto returned a NULL EVP_PKEY")
        self._handle = handle

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle:
            _lib.EVP_PKEY_free(handle)


def _keygen(key_type: int, setup=None) -> _PKey:
    ctx = _lib.EVP_PKEY_CTX_new_id(key_type, None)
    if not ctx:
        raise ValueError(f"libcrypto: no keygen context for type {key_type}")
    try:
        _check(_lib.EVP_PKEY_keygen_init(ctx), "keygen_init")
        if setup is not None:
            setup(ctx)
        out = ctypes.c_void_p()
        _check(_lib.EVP_PKEY_keygen(ctx, ctypes.byref(out)), "keygen")
        return _PKey(out.value)
    finally:
        _lib.EVP_PKEY_CTX_free(ctx)


def _raw_private(pkey: _PKey, length: int = 32) -> bytes:
    buf = ctypes.create_string_buffer(length)
    size = ctypes.c_size_t(length)
    _check(_lib.EVP_PKEY_get_raw_private_key(pkey._handle, buf, ctypes.byref(size)), "get_raw_private_key")
    return buf.raw[: size.value]


def _raw_public(pkey: _PKey, length: int = 32) -> bytes:
    buf = ctypes.create_string_buffer(length)
    size = ctypes.c_size_t(length)
    _check(_lib.EVP_PKEY_get_raw_public_key(pkey._handle, buf, ctypes.byref(size)), "get_raw_public_key")
    return buf.raw[: size.value]


# ------------------------------------------------------------------ marker namespaces


class _SHA256Marker:
    digest_size = 32


class _Hashes:
    SHA256 = _SHA256Marker


hashes = _Hashes()


class _Encoding:
    Raw = "Raw"
    DER = "DER"


class _PrivateFormat:
    Raw = "Raw"
    PKCS8 = "PKCS8"


class _PublicFormat:
    Raw = "Raw"
    SubjectPublicKeyInfo = "SubjectPublicKeyInfo"


class _NoEncryption:
    pass


class _Serialization:
    Encoding = _Encoding
    PrivateFormat = _PrivateFormat
    PublicFormat = _PublicFormat
    NoEncryption = _NoEncryption

    @staticmethod
    def load_der_private_key(data: bytes, password=None):
        assert password is None, "encrypted keys are not supported by the libcrypto shim"
        return RSAPrivateKey._from_der(data)

    @staticmethod
    def load_der_public_key(data: bytes):
        return RSAPublicKey._from_der(data)


serialization = _Serialization()


# ------------------------------------------------------------------ Ed25519


class Ed25519PrivateKey:
    def __init__(self, pkey: _PKey):
        self._pkey = pkey

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(_keygen(_EVP_PKEY_ED25519))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        handle = _lib.EVP_PKEY_new_raw_private_key(_EVP_PKEY_ED25519, None, bytes(data), len(data))
        return cls(_PKey(handle))

    def sign(self, data: bytes) -> bytes:
        mdctx = _lib.EVP_MD_CTX_new()
        try:
            _check(_lib.EVP_DigestSignInit(mdctx, None, None, None, self._pkey._handle), "DigestSignInit")
            sig = ctypes.create_string_buffer(64)
            siglen = ctypes.c_size_t(64)
            _check(_lib.EVP_DigestSign(mdctx, sig, ctypes.byref(siglen), bytes(data), len(data)), "DigestSign")
            return sig.raw[: siglen.value]
        finally:
            _lib.EVP_MD_CTX_free(mdctx)

    def public_key(self) -> "Ed25519PublicKey":
        return Ed25519PublicKey.from_public_bytes(_raw_public(self._pkey))

    def private_bytes(self, encoding=None, format=None, encryption_algorithm=None) -> bytes:
        return _raw_private(self._pkey)

    def private_bytes_raw(self) -> bytes:
        return _raw_private(self._pkey)


class Ed25519PublicKey:
    def __init__(self, pkey: _PKey):
        self._pkey = pkey

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        handle = _lib.EVP_PKEY_new_raw_public_key(_EVP_PKEY_ED25519, None, bytes(data), len(data))
        return cls(_PKey(handle))

    def verify(self, signature: bytes, data: bytes) -> None:
        mdctx = _lib.EVP_MD_CTX_new()
        try:
            _check(_lib.EVP_DigestVerifyInit(mdctx, None, None, None, self._pkey._handle), "DigestVerifyInit")
            ok = _lib.EVP_DigestVerify(mdctx, bytes(signature), len(signature), bytes(data), len(data))
        finally:
            _lib.EVP_MD_CTX_free(mdctx)
        if ok != 1:
            raise InvalidSignature("Ed25519 signature mismatch")

    def public_bytes(self, encoding=None, format=None) -> bytes:
        return _raw_public(self._pkey)

    def public_bytes_raw(self) -> bytes:
        return _raw_public(self._pkey)


class _Ed25519Module:
    Ed25519PrivateKey = Ed25519PrivateKey
    Ed25519PublicKey = Ed25519PublicKey


ed25519 = _Ed25519Module()


# ------------------------------------------------------------------ X25519


class X25519PublicKey:
    def __init__(self, pkey: _PKey):
        self._pkey = pkey

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        handle = _lib.EVP_PKEY_new_raw_public_key(_EVP_PKEY_X25519, None, bytes(data), len(data))
        return cls(_PKey(handle))

    def public_bytes(self, encoding=None, format=None) -> bytes:
        return _raw_public(self._pkey)

    def public_bytes_raw(self) -> bytes:
        return _raw_public(self._pkey)


class X25519PrivateKey:
    def __init__(self, pkey: _PKey):
        self._pkey = pkey

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(_keygen(_EVP_PKEY_X25519))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        handle = _lib.EVP_PKEY_new_raw_private_key(_EVP_PKEY_X25519, None, bytes(data), len(data))
        return cls(_PKey(handle))

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey.from_public_bytes(_raw_public(self._pkey))

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        ctx = _lib.EVP_PKEY_CTX_new(self._pkey._handle, None)
        if not ctx:
            raise ValueError("libcrypto: no derive context")
        try:
            _check(_lib.EVP_PKEY_derive_init(ctx), "derive_init")
            _check(_lib.EVP_PKEY_derive_set_peer(ctx, peer_public_key._pkey._handle), "derive_set_peer")
            out = ctypes.create_string_buffer(32)
            outlen = ctypes.c_size_t(32)
            _check(_lib.EVP_PKEY_derive(ctx, out, ctypes.byref(outlen)), "derive")
            return out.raw[: outlen.value]
        finally:
            _lib.EVP_PKEY_CTX_free(ctx)


class _X25519Module:
    X25519PrivateKey = X25519PrivateKey
    X25519PublicKey = X25519PublicKey


x25519 = _X25519Module()


# ------------------------------------------------------------------ ChaCha20-Poly1305


class ChaCha20Poly1305:
    _TAG_LEN = 16

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _run(self, encrypt: bool, nonce: bytes, data, aad: Optional[bytes], tag: Optional[bytes]):
        """``data`` is one bytes-like object or a sequence of them; multi-part input
        is streamed through EVP_CipherUpdate piecewise (scatter-gather: no plaintext
        join — the only allocation is the contiguous ciphertext output)."""
        parts = [data] if isinstance(data, (bytes, bytearray, memoryview)) else list(data)
        total_in = sum(len(part) for part in parts)
        ctx = _lib.EVP_CIPHER_CTX_new()
        if not ctx:
            raise ValueError("libcrypto: no cipher context")
        try:
            enc = 1 if encrypt else 0
            _check(_lib.EVP_CipherInit_ex(ctx, _lib.EVP_chacha20_poly1305(), None, None, None, enc), "CipherInit")
            _check(
                _lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_IVLEN, len(nonce), None), "set_ivlen"
            )
            _check(_lib.EVP_CipherInit_ex(ctx, None, None, self._key, bytes(nonce), enc), "CipherInit(key)")
            outlen = ctypes.c_int(0)
            if aad:
                _check(_lib.EVP_CipherUpdate(ctx, None, ctypes.byref(outlen), bytes(aad), len(aad)), "aad")
            out = ctypes.create_string_buffer(total_in if total_in else 1)
            total = 0
            out_address = ctypes.addressof(out)
            for part in parts:
                if not len(part):
                    continue
                _check(
                    _lib.EVP_CipherUpdate(
                        ctx, out_address + total, ctypes.byref(outlen), bytes(part), len(part)
                    ),
                    "update",
                )
                total += outlen.value
            if not encrypt:
                tag_buf = ctypes.create_string_buffer(bytes(tag), self._TAG_LEN)
                _check(_lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_TAG, self._TAG_LEN, tag_buf), "set_tag")
            final = ctypes.create_string_buffer(16)
            ok = _lib.EVP_CipherFinal_ex(ctx, final, ctypes.byref(outlen))
            if ok != 1:
                raise InvalidTag("AEAD authentication failed")
            result = out.raw[:total]
            if encrypt:
                tag_out = ctypes.create_string_buffer(self._TAG_LEN)
                _check(_lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_GET_TAG, self._TAG_LEN, tag_out), "get_tag")
                return result + tag_out.raw
            return result
        finally:
            _lib.EVP_CIPHER_CTX_free(ctx)

    def encrypt(self, nonce: bytes, data: bytes, associated_data: Optional[bytes]) -> bytes:
        return self._run(True, nonce, data, associated_data, None)

    def encrypt_parts(self, nonce: bytes, parts, associated_data: Optional[bytes]) -> bytes:
        """Seal a frame whose plaintext is the concatenation of ``parts`` without
        joining them first (SecureChannel's scatter-gather send path)."""
        return self._run(True, nonce, parts, associated_data, None)

    def decrypt(self, nonce: bytes, data: bytes, associated_data: Optional[bytes]) -> bytes:
        if len(data) < self._TAG_LEN:
            raise InvalidTag("ciphertext shorter than the AEAD tag")
        return self._run(False, nonce, data[: -self._TAG_LEN], associated_data, data[-self._TAG_LEN :])


# ------------------------------------------------------------------ HKDF (RFC 5869, SHA-256)


class HKDF:
    def __init__(self, algorithm=None, length: int = 32, salt: Optional[bytes] = None, info: Optional[bytes] = None):
        self._length = length
        self._salt = salt or b"\x00" * 32
        self._info = info or b""
        self._used = False

    def derive(self, key_material: bytes) -> bytes:
        assert not self._used, "HKDF instances are single-use"
        self._used = True
        prk = _hmac.new(self._salt, bytes(key_material), hashlib.sha256).digest()
        okm, block = b"", b""
        counter = 1
        while len(okm) < self._length:
            block = _hmac.new(prk, block + self._info + bytes([counter]), hashlib.sha256).digest()
            okm += block
            counter += 1
        return okm[: self._length]


# ------------------------------------------------------------------ RSA (PSS-SHA256, DER)


class _PSSMarker:
    MAX_LENGTH = "max"

    def __init__(self, mgf=None, salt_length=None):
        pass


class _MGF1Marker:
    def __init__(self, algorithm=None):
        pass


class _Padding:
    PSS = _PSSMarker
    MGF1 = _MGF1Marker


padding = _Padding()


def _rsa_pss_ctrl(pctx_value: int, sign: bool) -> None:
    pctx = ctypes.c_void_p(pctx_value)
    _check(_lib.EVP_PKEY_CTX_ctrl_str(pctx, b"rsa_padding_mode", b"pss"), "rsa_padding_mode")
    _check(
        _lib.EVP_PKEY_CTX_ctrl_str(pctx, b"rsa_pss_saltlen", b"max" if sign else b"auto"),
        "rsa_pss_saltlen",
    )


class RSAPrivateKey:
    def __init__(self, pkey: _PKey):
        self._pkey = pkey

    @classmethod
    def _from_der(cls, data: bytes) -> "RSAPrivateKey":
        raw = (ctypes.c_ubyte * len(data)).from_buffer_copy(data)
        pp = ctypes.cast(raw, ctypes.POINTER(ctypes.c_ubyte))
        p8 = _lib.d2i_PKCS8_PRIV_KEY_INFO(None, ctypes.byref(pp), len(data))
        if not p8:
            raise ValueError("could not parse PKCS8 private key DER")
        try:
            handle = _lib.EVP_PKCS82PKEY(p8)
        finally:
            _lib.PKCS8_PRIV_KEY_INFO_free(p8)
        return cls(_PKey(handle))

    def sign(self, data: bytes, pss_padding=None, algorithm=None) -> bytes:
        mdctx = _lib.EVP_MD_CTX_new()
        try:
            pctx = ctypes.c_void_p()
            _check(
                _lib.EVP_DigestSignInit(mdctx, ctypes.byref(pctx), _lib.EVP_sha256(), None, self._pkey._handle),
                "DigestSignInit(RSA)",
            )
            _rsa_pss_ctrl(pctx.value, sign=True)
            siglen = ctypes.c_size_t(0)
            _check(_lib.EVP_DigestSign(mdctx, None, ctypes.byref(siglen), bytes(data), len(data)), "size")
            sig = ctypes.create_string_buffer(siglen.value)
            _check(_lib.EVP_DigestSign(mdctx, sig, ctypes.byref(siglen), bytes(data), len(data)), "DigestSign(RSA)")
            return sig.raw[: siglen.value]
        finally:
            _lib.EVP_MD_CTX_free(mdctx)

    def public_key(self) -> "RSAPublicKey":
        der = self.public_key_der()
        return RSAPublicKey._from_der(der)

    def public_key_der(self) -> bytes:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        length = _lib.i2d_PUBKEY(self._pkey._handle, ctypes.byref(out))
        if length <= 0:
            raise ValueError("i2d_PUBKEY failed")
        try:
            return bytes(bytearray(out[:length]))
        finally:
            _openssl_free(out)

    def private_bytes(self, encoding=None, format=None, encryption_algorithm=None) -> bytes:
        p8 = _lib.EVP_PKEY2PKCS8(self._pkey._handle)
        if not p8:
            raise ValueError("EVP_PKEY2PKCS8 failed")
        try:
            out = ctypes.POINTER(ctypes.c_ubyte)()
            length = _lib.i2d_PKCS8_PRIV_KEY_INFO(p8, ctypes.byref(out))
            if length <= 0:
                raise ValueError("i2d_PKCS8_PRIV_KEY_INFO failed")
            try:
                return bytes(bytearray(out[:length]))
            finally:
                _openssl_free(out)
        finally:
            _lib.PKCS8_PRIV_KEY_INFO_free(p8)


class RSAPublicKey:
    def __init__(self, pkey: _PKey):
        self._pkey = pkey

    @classmethod
    def _from_der(cls, data: bytes) -> "RSAPublicKey":
        raw = (ctypes.c_ubyte * len(data)).from_buffer_copy(data)
        pp = ctypes.cast(raw, ctypes.POINTER(ctypes.c_ubyte))
        handle = _lib.d2i_PUBKEY(None, ctypes.byref(pp), len(data))
        if not handle:
            raise ValueError("could not parse SubjectPublicKeyInfo DER")
        return cls(_PKey(handle))

    def verify(self, signature: bytes, data: bytes, pss_padding=None, algorithm=None) -> None:
        mdctx = _lib.EVP_MD_CTX_new()
        try:
            pctx = ctypes.c_void_p()
            _check(
                _lib.EVP_DigestVerifyInit(mdctx, ctypes.byref(pctx), _lib.EVP_sha256(), None, self._pkey._handle),
                "DigestVerifyInit(RSA)",
            )
            _rsa_pss_ctrl(pctx.value, sign=False)
            ok = _lib.EVP_DigestVerify(mdctx, bytes(signature), len(signature), bytes(data), len(data))
        finally:
            _lib.EVP_MD_CTX_free(mdctx)
        if ok != 1:
            raise InvalidSignature("RSA-PSS signature mismatch")

    def public_bytes(self, encoding=None, format=None) -> bytes:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        length = _lib.i2d_PUBKEY(self._pkey._handle, ctypes.byref(out))
        if length <= 0:
            raise ValueError("i2d_PUBKEY failed")
        try:
            return bytes(bytearray(out[:length]))
        finally:
            _openssl_free(out)


def _rsa_generate_private_key(public_exponent: int = 65537, key_size: int = 2048) -> RSAPrivateKey:
    def _setup(ctx):
        _check(
            _lib.EVP_PKEY_CTX_ctrl_str(ctypes.c_void_p(ctx), b"rsa_keygen_bits", str(key_size).encode()),
            "rsa_keygen_bits",
        )

    return RSAPrivateKey(_keygen(_EVP_PKEY_RSA, _setup))


class _RSAModule:
    RSAPrivateKey = RSAPrivateKey
    RSAPublicKey = RSAPublicKey
    generate_private_key = staticmethod(_rsa_generate_private_key)


rsa = _RSAModule()
