"""The process-wide event-loop runtime.

The reference runs every service (DHT, averager, connection handlers) as a forked
daemon process with its own uvloop, bridged by pipes + a shared-memory ``MPFuture``
(reference hivemind/utils/mpfuture.py:65-328, dht/dht.py:89-139). That topology exists
to dodge the GIL and CUDA-fork hazards. On TPU the process model is the opposite: one
process owns the accelerator, and forking after jax initialization is unsafe. So the
runtime here is a single shared asyncio event loop on a background thread; components
schedule coroutines onto it and sync callers get ``concurrent.futures.Future`` handles
(the MPFuture equivalent without crossing a process boundary).
"""

from __future__ import annotations

import asyncio
import atexit
import concurrent.futures
import threading
from typing import Any, Awaitable, Coroutine, Optional, TypeVar

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

T = TypeVar("T")


class EventLoopShutdownError(RuntimeError):
    """Raised when scheduling onto a loop runner that has shut down."""


class LoopRunner:
    """An asyncio event loop running on a dedicated daemon thread.

    ``run_coroutine(coro)`` returns a concurrent Future (sync handle);
    ``run_coroutine(coro, return_future=True)`` returns it without waiting.
    """

    def __init__(self, name: str = "hmtpu-loop"):
        self._name = name
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._closed = False
        self._start_lock = threading.Lock()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        self._ensure_started()
        assert self._loop is not None
        return self._loop

    def _ensure_started(self) -> None:
        if self._started.is_set():
            return
        with self._start_lock:
            if self._started.is_set():
                return
            if self._closed:
                raise EventLoopShutdownError(f"{self._name} is shut down")

            def _run():
                loop = asyncio.new_event_loop()
                asyncio.set_event_loop(loop)
                self._loop = loop
                self._started.set()
                try:
                    loop.run_forever()
                finally:
                    try:
                        pending = asyncio.all_tasks(loop)
                        for task in pending:
                            task.cancel()
                        if pending:
                            loop.run_until_complete(
                                asyncio.gather(*pending, return_exceptions=True)
                            )
                    finally:
                        loop.close()

            self._thread = threading.Thread(target=_run, name=self._name, daemon=True)
            self._thread.start()
            self._started.wait()

    def run_coroutine(
        self, coro: Coroutine[Any, Any, T], return_future: bool = False
    ) -> Any:
        """Schedule a coroutine onto the loop. Returns the result (blocking) or a
        concurrent.futures.Future if return_future=True."""
        self._ensure_started()
        if self._closed:
            raise EventLoopShutdownError(f"{self._name} is shut down")
        future = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return future if return_future else future.result()

    def call_soon(self, callback, *args) -> None:
        self._ensure_started()
        self.loop.call_soon_threadsafe(callback, *args)

    @property
    def is_running(self) -> bool:
        return self._started.is_set() and not self._closed

    def in_loop(self) -> bool:
        """True if the caller is already on this runner's loop thread."""
        return threading.current_thread() is self._thread

    def shutdown(self, timeout: float = 5.0) -> None:
        if self._closed or not self._started.is_set():
            self._closed = True
            return
        self._closed = True
        assert self._loop is not None
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout)


_global_runner: Optional[LoopRunner] = None
_global_lock = threading.Lock()


def get_loop_runner() -> LoopRunner:
    """The process-wide shared loop runner (created lazily)."""
    global _global_runner
    with _global_lock:
        if _global_runner is None or not _global_runner.is_running:
            _global_runner = LoopRunner()
        return _global_runner


def reset_loop_runner() -> None:
    """Tear down the global runner (test isolation)."""
    global _global_runner
    with _global_lock:
        if _global_runner is not None:
            _global_runner.shutdown()
            _global_runner = None


@atexit.register
def _shutdown_at_exit():
    global _global_runner
    if _global_runner is not None:
        _global_runner.shutdown(timeout=1.0)
        _global_runner = None


def as_concurrent_future(awaitable: Awaitable[T], runner: Optional[LoopRunner] = None) -> concurrent.futures.Future:
    """Bridge an awaitable to a thread-safe concurrent future on the shared loop."""
    runner = runner or get_loop_runner()

    async def _wrap():
        return await awaitable

    return runner.run_coroutine(_wrap(), return_future=True)
