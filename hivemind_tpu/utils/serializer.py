"""MessagePack serialization with an extension-type registry — the wire format for DHT
values and control metadata (capability parity: reference hivemind/utils/serializer.py:25-73).
"""

from __future__ import annotations

import threading
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Type, TypeVar

import msgpack

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

T = TypeVar("T")

_TUPLE_EXT_CODE = 0x40
_EXT_SERIALIZABLE_BASE = 0x50


class SerializerBase(ABC):
    @staticmethod
    @abstractmethod
    def dumps(obj: Any) -> bytes: ...

    @staticmethod
    @abstractmethod
    def loads(buf: bytes) -> Any: ...


class MSGPackSerializer(SerializerBase):
    """msgpack with two extension families: tuples (code 0x40) and user classes
    registered via ``ext_serializable`` (codes ≥ 0x50). Registered classes must
    provide ``packb() -> bytes`` and ``unpackb(cls, data) -> instance``."""

    _ext_types: Dict[int, Type] = {}
    _lock = threading.Lock()

    @classmethod
    def ext_serializable(cls, type_code: int) -> Callable[[Type[T]], Type[T]]:
        assert isinstance(type_code, int) and 0 <= type_code <= 127

        def wrap(wrapped_type: Type[T]) -> Type[T]:
            with cls._lock:
                existing = cls._ext_types.get(type_code)
                if existing is not None and existing.__name__ != wrapped_type.__name__:
                    raise ValueError(f"msgpack ext code {type_code} already taken by {existing}")
                assert callable(getattr(wrapped_type, "packb", None)) and callable(
                    getattr(wrapped_type, "unpackb", None)
                ), f"{wrapped_type} must define packb() and classmethod unpackb(data)"
                cls._ext_types[type_code] = wrapped_type
            return wrapped_type

        return wrap

    @classmethod
    def _encode_ext_types(cls, obj):
        # exact type first, then most-derived isinstance match, so a subclass
        # registered under its own code is not shadowed by its base class
        for code, ext_type in cls._ext_types.items():
            if type(obj) is ext_type:
                return msgpack.ExtType(code, obj.packb())
        best = None
        for code, ext_type in cls._ext_types.items():
            if isinstance(obj, ext_type):
                if best is None or issubclass(ext_type, best[1]):
                    best = (code, ext_type)
        if best is not None:
            return msgpack.ExtType(best[0], obj.packb())
        if isinstance(obj, tuple):
            data = msgpack.packb(list(obj), strict_types=True, use_bin_type=True,
                                 default=cls._encode_ext_types)
            return msgpack.ExtType(_TUPLE_EXT_CODE, data)
        raise TypeError(f"cannot serialize {obj!r} ({type(obj)})")

    @classmethod
    def _decode_ext_types(cls, code: int, data: bytes):
        if code == _TUPLE_EXT_CODE:
            return tuple(
                msgpack.unpackb(data, ext_hook=cls._decode_ext_types, raw=False, strict_map_key=False)
            )
        if code in cls._ext_types:
            return cls._ext_types[code].unpackb(data)
        logger.warning(f"unknown msgpack ext code {code}, returning raw bytes")
        return data

    @classmethod
    def dumps(cls, obj: Any) -> bytes:
        return msgpack.packb(obj, use_bin_type=True, strict_types=True,
                             default=cls._encode_ext_types)

    @classmethod
    def loads(cls, buf: bytes) -> Any:
        return msgpack.unpackb(buf, ext_hook=cls._decode_ext_types, raw=False,
                               strict_map_key=False)
