"""Asyncio helpers (capability parity: reference hivemind/utils/asyncio.py). uvloop is
not available in this environment; the stock loop is used (switch_to_uvloop kept as a
no-op shim so call sites stay portable).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
from concurrent.futures import ThreadPoolExecutor
from contextlib import asynccontextmanager
from typing import AsyncIterable, AsyncIterator, Awaitable, Callable, Optional, Tuple, TypeVar, Union

T = TypeVar("T")


def switch_to_uvloop() -> asyncio.AbstractEventLoop:
    """Create a fresh event loop for the current thread (uvloop unavailable on this image)."""
    try:
        import uvloop  # type: ignore

        asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
    except ImportError:
        pass
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    return loop


async def anext_safe(aiter: AsyncIterator[T]) -> Union[T, object]:
    """Like anext() but returns the sentinel instead of raising StopAsyncIteration."""
    try:
        return await aiter.__anext__()
    except StopAsyncIteration:
        return _SENTINEL


_SENTINEL = object()


async def as_aiter(*items: T) -> AsyncIterator[T]:
    for item in items:
        yield item


async def azip(*iterables: AsyncIterable) -> AsyncIterator[Tuple]:
    iterators = [it.__aiter__() for it in iterables]
    while True:
        results = await asyncio.gather(*(anext_safe(it) for it in iterators))
        if any(r is _SENTINEL for r in results):
            return
        yield tuple(results)


async def achain(*iterables: AsyncIterable[T]) -> AsyncIterator[T]:
    for it in iterables:
        async for item in it:
            yield item


async def aenumerate(iterable: AsyncIterable[T], start: int = 0) -> AsyncIterator[Tuple[int, T]]:
    index = start
    async for item in iterable:
        yield index, item
        index += 1


async def aiter_with_timeout(iterable: AsyncIterable[T], timeout: Optional[float]) -> AsyncIterator[T]:
    """Iterate over an async iterable, raising asyncio.TimeoutError if any single item
    takes longer than ``timeout`` seconds."""
    iterator = iterable.__aiter__()
    while True:
        item = await asyncio.wait_for(anext_safe(iterator), timeout=timeout)
        if item is _SENTINEL:
            return
        yield item


async def amap_in_executor(
    fn: Callable[..., T],
    *iterables: AsyncIterable,
    max_prefetch: int = 1,
    executor: Optional[ThreadPoolExecutor] = None,
) -> AsyncIterator[T]:
    """Apply a blocking fn to items of async iterable(s) in a thread executor with
    bounded prefetch — used to overlap compression with networking
    (reference asyncio.py:104-143)."""
    assert max_prefetch > 0
    loop = asyncio.get_event_loop()
    queue: asyncio.Queue = asyncio.Queue(max_prefetch)

    async def _producer():
        try:
            async for args in azip(*iterables):
                await queue.put(loop.run_in_executor(executor, fn, *args))
            await queue.put(None)
        except asyncio.CancelledError:
            # consumer is gone; never block on a full queue in cleanup
            try:
                queue.put_nowait(None)
            except asyncio.QueueFull:
                pass
            raise

    producer = asyncio.create_task(_producer())
    try:
        while True:
            future = await queue.get()
            if future is None:
                break
            yield await future
        await producer
    finally:
        if not producer.done():
            producer.cancel()


# strong refs: asyncio keeps only a weak reference to running tasks, so a spawned
# task with no other referent is garbage-collectable MID-FLIGHT
_background_tasks: set = set()
_background_error_counter = None


def _count_background_error(site: str) -> None:
    global _background_error_counter
    if _background_error_counter is None:
        # lazy: telemetry's package init pulls in monitor/exporter, which must not
        # become an import-time dependency of the utils layer
        from hivemind_tpu.telemetry.registry import REGISTRY

        _background_error_counter = REGISTRY.counter(
            "hivemind_background_task_errors_total",
            "exceptions raised by fire-and-forget background tasks, by spawn site",
            ("site",),
        )
    _background_error_counter.inc(site=site)


def _on_background_done(name: str, task: asyncio.Task) -> None:
    _background_tasks.discard(task)
    if task.cancelled():
        return
    exc = task.exception()  # marks the exception retrieved either way
    if exc is None:
        return
    from hivemind_tpu.utils.logging import get_logger

    get_logger(__name__).warning(f"background task {name!r} failed: {exc!r}")
    try:
        _count_background_error(name)
    except Exception:  # lint: allow(adhoc-retries) — counting must never mask the original failure
        pass


def spawn(coro: Awaitable, *, name: str) -> asyncio.Task:
    """Tracked fire-and-forget: the approved alternative to a bare
    ``asyncio.create_task(...)`` whose handle is dropped (flagged by the
    ``fire-and-forget`` lint rule).

    Keeps a strong reference until the task finishes, names the task, and on
    failure logs + increments ``hivemind_background_task_errors_total{site}``
    instead of letting the exception rot until interpreter shutdown. The
    returned task may still be stored/awaited/cancelled by the caller —
    retrieving the exception here does not stop a later ``await task`` from
    re-raising it."""
    task = asyncio.ensure_future(coro)
    try:
        task.set_name(name)
    except AttributeError:
        pass  # lint: allow(adhoc-retries) — futures (vs tasks) have no set_name; name only aids debugging
    _background_tasks.add(task)
    task.add_done_callback(lambda t, _name=name: _on_background_done(_name, t))
    return task


async def cancel_and_wait(task: asyncio.Task) -> bool:
    """Cancel a task and wait until the cancellation lands. Returns True if it was
    cancelled (vs finished/failed first)."""
    task.cancel()
    try:
        await task
        return False
    except asyncio.CancelledError:
        return True
    except BaseException:
        return False


async def await_cancelled(awaitable: Awaitable) -> bool:
    try:
        await awaitable
        return False
    except asyncio.CancelledError:
        return True
    except BaseException:
        return False


_blocking_executor = ThreadPoolExecutor(
    max_workers=int(os.getenv("HIVEMIND_TPU_BLOCKING_THREADS", "32")),
    thread_name_prefix="hmtpu-blocking",
)


async def run_in_executor(fn: Callable[..., T], *args) -> T:
    """Run a blocking function in the shared background thread pool."""
    return await asyncio.get_event_loop().run_in_executor(_blocking_executor, fn, *args)


# lock acquisition can block indefinitely, so it must never share a bounded pool with
# productive work (reference asyncio.py:166-198 uses an unbounded executor for this)
_lock_executor = ThreadPoolExecutor(max_workers=2**16, thread_name_prefix="hmtpu-lock")


@asynccontextmanager
async def enter_asynchronously(lock):
    """Acquire a synchronous threading.Lock without blocking the event loop."""
    await asyncio.get_event_loop().run_in_executor(_lock_executor, lock.acquire)
    try:
        yield lock
    finally:
        lock.release()


async def attach_event_on_finished(iterable: AsyncIterable[T], event: asyncio.Event) -> AsyncIterator[T]:
    """Yield from iterable; set the event when iteration ends for any reason."""
    try:
        async for item in iterable:
            yield item
    finally:
        event.set()


def complete_future_threadsafe(future: Union[asyncio.Future, concurrent.futures.Future], result=None, exception=None):
    """Set a result/exception on a future from any thread."""
    if isinstance(future, concurrent.futures.Future):
        if exception is not None:
            future.set_exception(exception)
        else:
            future.set_result(result)
        return
    loop = future.get_loop()

    def _set():
        if future.done():
            return
        if exception is not None:
            future.set_exception(exception)
        else:
            future.set_result(result)

    loop.call_soon_threadsafe(_set)
