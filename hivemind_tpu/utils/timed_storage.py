"""A dictionary with value expiration times — the storage primitive beneath the DHT,
caches, blacklists and leader queues (capability parity: reference
hivemind/utils/timed_storage.py:50-143).
"""

from __future__ import annotations

import heapq
import time
from contextlib import contextmanager
from typing import Generic, Iterator, NamedTuple, Optional, Tuple, TypeVar

KeyType = TypeVar("KeyType")
ValueType = TypeVar("ValueType")

DHTExpiration = float
MAX_DHT_TIME_DISCREPANCY_SECONDS = 3.0  # max tolerated clock skew between peers

# swappable swarm-time source: None = wall clock. The swarm simulator
# (hivemind_tpu/sim) installs its virtual clock here so every expiration,
# declaration window and blacklist backoff in the process tracks simulated
# time; production never touches it. A module-global (not monkeypatching
# get_dht_time itself) because callers across the tree bound the function
# object at import time.
_dht_time_source = None


def set_dht_time_source(source) -> None:
    """Install a ``() -> float`` swarm-time source, or None to restore wall time."""
    global _dht_time_source
    _dht_time_source = source


def get_dht_time() -> DHTExpiration:
    """Global swarm time. Approximated as local UNIX time; peers tolerate up to
    MAX_DHT_TIME_DISCREPANCY_SECONDS of skew (reference timed_storage.py:13-14)."""
    if _dht_time_source is not None:
        return _dht_time_source()
    return time.time()


class ValueWithExpiration(NamedTuple):
    # generic-NamedTuple multiple inheritance requires py3.11; on 3.10 the class
    # stays a plain NamedTuple and subscription (ValueWithExpiration[T]) is a no-op
    value: "ValueType"  # type: ignore[valid-type]
    expiration_time: DHTExpiration

    __class_getitem__ = classmethod(lambda cls, _item: cls)  # type: ignore[assignment]

    def __eq__(self, other):
        if isinstance(other, ValueWithExpiration):
            return self.value == other.value and self.expiration_time == other.expiration_time
        if isinstance(other, tuple):
            return tuple(self) == other
        return False

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash((self.value, self.expiration_time))


class _HeapEntry(NamedTuple):
    expiration_time: DHTExpiration
    key: "KeyType"  # type: ignore[valid-type]

    __class_getitem__ = classmethod(lambda cls, _item: cls)  # type: ignore[assignment]


class TimedStorage(Generic[KeyType, ValueType]):
    """A dict that evicts expired values lazily and the soonest-to-expire value when
    over ``maxsize``. ``freeze()`` suspends eviction for consistent multi-step reads."""

    frozen = False  # class-level default; instances toggle via freeze()

    def __init__(self, maxsize: Optional[int] = None):
        self.maxsize = maxsize
        self._data: dict[KeyType, ValueWithExpiration[ValueType]] = {}
        self._expiration_heap: list[_HeapEntry[KeyType]] = []

    def _remove_outdated(self) -> None:
        if self.frozen:
            return
        now = get_dht_time()
        while self._expiration_heap:
            entry = self._expiration_heap[0]
            current = self._data.get(entry.key)
            if current is not None and current.expiration_time == entry.expiration_time:
                # live heap entry: evict only if expired or oversize
                if entry.expiration_time > now and not (
                    self.maxsize is not None and len(self._data) > self.maxsize
                ):
                    break
                del self._data[entry.key]
            heapq.heappop(self._expiration_heap)

    def store(self, key: KeyType, value: ValueType, expiration_time: DHTExpiration) -> bool:
        """Store (key, value) until expiration_time, unless a fresher value exists.
        Returns True if stored."""
        if expiration_time < get_dht_time() and not self.frozen:
            return False
        previous = self._data.get(key)
        if previous is not None and previous.expiration_time > expiration_time:
            return False
        self._data[key] = ValueWithExpiration(value, expiration_time)
        heapq.heappush(self._expiration_heap, _HeapEntry(expiration_time, key))
        self._remove_outdated()
        return True

    def get(self, key: KeyType) -> Optional[ValueWithExpiration[ValueType]]:
        self._remove_outdated()
        return self._data.get(key)

    def items(self) -> Iterator[Tuple[KeyType, ValueWithExpiration[ValueType]]]:
        self._remove_outdated()
        return iter(self._data.items())

    def top(self) -> Optional[Tuple[KeyType, ValueWithExpiration[ValueType]]]:
        """The entry with the soonest expiration, or None."""
        self._remove_outdated()
        while self._expiration_heap:
            entry = self._expiration_heap[0]
            current = self._data.get(entry.key)
            if current is not None and current.expiration_time == entry.expiration_time:
                return entry.key, current
            heapq.heappop(self._expiration_heap)
        return None

    def __contains__(self, key: KeyType) -> bool:
        self._remove_outdated()
        return key in self._data

    def __len__(self) -> int:
        self._remove_outdated()
        return len(self._data)

    def __delitem__(self, key: KeyType) -> None:
        self._remove_outdated()
        del self._data[key]
        # stale heap entries are pruned lazily

    def __bool__(self) -> bool:
        return len(self) > 0

    @contextmanager
    def freeze(self):
        """Within this context, no values are evicted (consistent reads across awaits)."""
        previous, self.frozen = self.frozen, True
        try:
            yield self
        finally:
            self.frozen = previous

    def __repr__(self) -> str:
        return f"{type(self).__name__}({len(self._data)} items, maxsize={self.maxsize})"
