"""Debiased exponential moving average of throughput (samples/sec), with a pause
context for excluding idle time (capability parity: reference
hivemind/utils/performance_ema.py:7-70)."""

from __future__ import annotations

import time
from contextlib import contextmanager
from threading import Lock


class PerformanceEMA:
    def __init__(self, alpha: float = 0.1, paused: bool = False):
        self.alpha = alpha
        self.samples_per_second = 0.0
        self._ema_seconds_per_sample = 0.0
        self._num_updates = 0
        self._last_update = time.perf_counter()
        self.paused = paused
        self._lock = Lock()

    def update(self, task_size: float, interval: float | None = None) -> float:
        """Register that ``task_size`` units were processed; returns updated rate."""
        assert task_size > 0
        with self._lock:
            now = time.perf_counter()
            if interval is None:
                assert not self.paused, "provide interval explicitly while paused"
                interval = max(now - self._last_update, 1e-9)
            self._last_update = now
            seconds_per_sample = interval / task_size
            self._ema_seconds_per_sample = (
                self.alpha * seconds_per_sample + (1 - self.alpha) * self._ema_seconds_per_sample
            )
            self._num_updates += 1
            bias_correction = 1 - (1 - self.alpha) ** self._num_updates
            self.samples_per_second = bias_correction / max(self._ema_seconds_per_sample, 1e-20)
            return self.samples_per_second

    def reset_timer(self) -> None:
        self._last_update = time.perf_counter()

    @contextmanager
    def pause(self):
        """Exclude the time inside this context from throughput estimation."""
        was_paused, self.paused = self.paused, True
        try:
            yield
        finally:
            self.paused = was_paused
            self.reset_timer()

    @contextmanager
    def update_threadsafe(self, task_size: float):
        """Measure the duration of the context body and update with it."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.update(task_size, interval=max(time.perf_counter() - start, 1e-9))

    def __repr__(self):
        return f"PerformanceEMA({self.samples_per_second:.3g} samples/s, {self._num_updates} updates)"
