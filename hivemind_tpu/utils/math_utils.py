"""Numeric helpers for low-rank gradient compression (capability parity: reference
hivemind/utils/math.py — orthogonalize_, get_flatten_greedy_dims)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


def orthogonalize(matrix: np.ndarray, eps: float = 1e-8) -> np.ndarray:
    """Column-wise Gram-Schmidt (in place); the PowerSGD P-phase orthogonalization."""
    for col in range(matrix.shape[1]):
        column = matrix[:, col]
        norm = np.linalg.norm(column)
        column /= max(norm, eps)
        if col + 1 < matrix.shape[1]:
            rest = matrix[:, col + 1 :]
            rest -= np.outer(column, column @ rest)
    return matrix


def get_flatten_greedy_dims(shape: Tuple[int, ...], max_ndim: int = 2) -> Tuple[int, int]:
    """Flatten an nd shape into 2D [m, n] keeping m as close to n as possible —
    maximizes the energy a rank-r factorization can capture."""
    numel = int(np.prod(shape))
    if numel == 0:
        return (0, 1)
    best = (numel, 1)
    m = 1
    for dim in shape:
        m *= dim
        n = numel // m
        if abs(m - n) < abs(best[0] - best[1]):
            best = (m, n)
    return best
