"""Profiling hooks over the jax/XLA profiler (fills the reference's tracing role:
hivemind/utils/performance_ema.py + the torch profiler hooks scattered through its
runtime; here the device timeline comes from XLA's own profiler, which captures
HBM traffic, fusion boundaries, and per-op device time — strictly more than the
reference's host-side timers).

- :func:`trace_span` — annotate a host-side region so it shows up on the XLA trace
  timeline (viewable in TensorBoard / Perfetto).
- :func:`profile_to` — capture a full device+host trace for a ``with`` block.
- :func:`device_memory_stats` — live HBM usage of a device (bytes in use / limit),
  the "am I about to OOM" probe for schedulers and monitors.
- :func:`tracked_jit` — ``jax.jit`` plus compile accounting: every cache miss is
  reported to the device-telemetry compile tracker with a site label and the
  triggering abstract signature (ISSUE 19).
- :class:`StepProfiler` — rolling tokens/s + achieved-FLOP/s estimator for training
  loops (PerformanceEMA under the hood), the number the training monitor reports.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Dict, Optional

from hivemind_tpu.utils.performance_ema import PerformanceEMA

# jax is imported lazily inside each hook: utils/__init__.py re-exports this module,
# and lightweight processes (DHT-only peers, CLIs) must not pay for — or claim — an
# accelerator backend just by importing hivemind_tpu.


@contextlib.contextmanager
def trace_span(name: str, **attributes):
    """Label a host-side region on BOTH timelines under one name: the XLA
    profiler trace (device view — HBM traffic, fusions, per-op device time) and
    the swarm telemetry tracer (host view — the flight recorder, ``/trace``
    Perfetto export, cross-peer parenting). One call site, two synchronized
    views; the shared name is what lets you line them up in Perfetto."""
    import jax

    from hivemind_tpu.telemetry.tracing import trace as _telemetry_trace

    with _telemetry_trace(name, **attributes):
        with jax.profiler.TraceAnnotation(name):
            yield


@contextlib.contextmanager
def profile_to(logdir: str):
    """Capture a device+host trace into ``logdir`` for the duration of the block
    (open with TensorBoard's profile plugin or Perfetto)."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def device_memory_stats(device=None) -> Dict[str, Any]:
    """Live memory statistics for one device; empty dict when the backend does not
    expose them (CPU)."""
    import jax

    device = device if device is not None else jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}


def _abstract_signature(args, kwargs, limit: int = 16) -> Optional[str]:
    """Compact shape/dtype signature of a call's array leaves — computed only
    when a compile was actually observed, so the cost never hits a cache hit."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        parts = [
            f"{getattr(leaf, 'dtype', '?')}{list(getattr(leaf, 'shape', ()))}"
            for leaf in leaves[:limit]
            if hasattr(leaf, "shape")
        ]
        return ",".join(parts)[:200] or None
    except Exception:
        return None


def tracked_jit(fn=None, *, site: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` with compile accounting (ISSUE 19).

    Wraps the jitted callable so every cache miss — detected via a
    ``_cache_size()`` delta around the call — is reported to
    :data:`~hivemind_tpu.telemetry.device.COMPILE_TRACKER` under ``site``
    (default: the function's qualname), with the call's wall duration (trace +
    lower + compile + first run) and abstract signature. Cache hits pay one
    cache-size probe and one clock read, cheap enough for per-token decode
    paths; this is the sanctioned alternative the ``jit-in-hot-path`` lint rule
    points at for memoized-factory jits that legitimately live inside methods.

    Usable as ``tracked_jit(fn, site=..., donate_argnums=...)`` or as a bare
    decorator. The underlying jitted function stays reachable via
    ``wrapper.jitted`` (``lower()``/cache inspection)."""

    def wrap(fn):
        import jax

        from hivemind_tpu.telemetry.device import COMPILE_TRACKER

        label = site or getattr(fn, "__qualname__", None) or getattr(fn, "__name__", "jit")
        jitted = jax.jit(fn, **jit_kwargs)
        cache_size = getattr(jitted, "_cache_size", None)
        if cache_size is None:  # exotic jaxlib: still jit, just without tracking
            return jitted

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            before = cache_size()
            started = time.perf_counter()
            out = jitted(*args, **kwargs)
            if cache_size() > before:
                COMPILE_TRACKER.record_compile(
                    label,
                    duration_s=time.perf_counter() - started,
                    signature=_abstract_signature(args, kwargs),
                )
            return out

        wrapper.jitted = jitted
        wrapper.site = label
        return wrapper

    return wrap if fn is None else wrap(fn)


class StepProfiler:
    """Rolling throughput for a training loop.

    >>> prof = StepProfiler(flops_per_token=flops)
    >>> for batch in data:
    ...     loss = train_step(batch)
    ...     prof.step(tokens=batch_tokens)
    >>> prof.tokens_per_second, prof.achieved_flops
    """

    def __init__(self, flops_per_token: Optional[float] = None, alpha: float = 0.1):
        self.flops_per_token = flops_per_token
        self.ema = PerformanceEMA(alpha=alpha)
        self.total_tokens = 0
        self._started = time.perf_counter()

    def step(self, tokens: int) -> None:
        self.total_tokens += tokens
        self.ema.update(tokens)

    @property
    def tokens_per_second(self) -> float:
        return self.ema.samples_per_second

    @property
    def achieved_flops(self) -> Optional[float]:
        if self.flops_per_token is None:
            return None
        return self.tokens_per_second * self.flops_per_token

    def mfu(self, peak_flops: float) -> Optional[float]:
        achieved = self.achieved_flops
        return None if achieved is None else achieved / peak_flops

    def summary(self) -> Dict[str, Any]:
        return {
            "tokens_per_second": round(self.tokens_per_second, 1),
            "total_tokens": self.total_tokens,
            "elapsed_s": round(time.perf_counter() - self._started, 3),
            "achieved_tflops": None
            if self.achieved_flops is None
            else round(self.achieved_flops / 1e12, 3),
        }


class JsonlMetricsSink:
    """Append metric records as JSON lines — the offline wandb-style sink shared
    by the flagship recipe's trainer and monitor. Non-finite floats serialize as
    null so every line stays strict JSON (jq/pandas-parsable)."""

    def __init__(self, path: Optional[str]):
        self._file = open(path, "a") if path else None

    def log(self, record: Dict[str, Any]) -> None:
        if self._file is None:
            return
        import json
        import math

        clean = {
            key: (None if isinstance(value, float) and not math.isfinite(value) else value)
            for key, value in record.items()
        }
        self._file.write(json.dumps(clean, allow_nan=False) + "\n")
        self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
