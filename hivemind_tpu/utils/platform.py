"""Shared --platform plumbing for CLI entrypoints and examples: hosts whose default
accelerator plugin is unavailable (or wedged) can force e.g. the CPU backend. Must
run before the first device use; ``jax.config`` is used rather than the JAX_PLATFORMS
env var because site configuration may override the env at interpreter startup."""

from __future__ import annotations

import argparse


def add_platform_arg(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. cpu) — useful on hosts whose default "
             "accelerator plugin is unavailable",
    )


def apply_platform(args: argparse.Namespace) -> None:
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
