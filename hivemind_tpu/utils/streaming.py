"""Split serialized tensors into stream-sized chunks and combine them back
(capability parity: reference hivemind/utils/streaming.py:14-46), plus the
scatter-gather wire-message container shared by the p2p layer and the
serving-path protobuf splicers (compression/serialization.py)."""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple, TypeVar, Union

STREAMING_CHUNK_SIZE_BYTES = 2**16

Buffer = Union[bytes, bytearray, memoryview]


class WireParts:
    """One wire message as a list of buffers whose concatenation IS the
    serialized protobuf — the serving-path analog of the averaging path's
    scatter-gather framing (ISSUE 6): a multi-MB tensor buffer rides to the
    AEAD as its own buffer instead of being copied into one materialized
    ``SerializeToString`` blob. The p2p send paths (``MuxStream.send``,
    ``call_protobuf_handler``, the stream feeders) accept this wherever they
    accept a protobuf message; the receive side is unchanged (one decrypted
    frame, parsed as usual)."""

    __slots__ = ("parts",)

    def __init__(self, *parts: Buffer):
        self.parts: Tuple[Buffer, ...] = tuple(p for p in parts if len(p))

    @property
    def nbytes(self) -> int:
        return sum(len(part) for part in self.parts)

    def join(self) -> bytes:
        """Materialize (chaos injection / non-scatter-gather fallbacks only —
        the hot path must pass ``parts`` through unjoined)."""
        return b"".join(bytes(part) if not isinstance(part, bytes) else part for part in self.parts)

    def __len__(self) -> int:
        return self.nbytes


def split_for_streaming(data: bytes, chunk_size_bytes: int = STREAMING_CHUNK_SIZE_BYTES) -> Iterator[bytes]:
    """Split a byte string into chunks of at most chunk_size_bytes. Always yields at
    least one (possibly empty) chunk."""
    if not data:
        yield b""
        return
    for offset in range(0, len(data), chunk_size_bytes):
        yield data[offset : offset + chunk_size_bytes]


def combine_from_streaming(chunks: Iterable[bytes]) -> bytes:
    return b"".join(chunks)
