"""Split serialized tensors into stream-sized chunks and combine them back
(capability parity: reference hivemind/utils/streaming.py:14-46)."""

from __future__ import annotations

from typing import Iterable, Iterator, List, TypeVar

STREAMING_CHUNK_SIZE_BYTES = 2**16


def split_for_streaming(data: bytes, chunk_size_bytes: int = STREAMING_CHUNK_SIZE_BYTES) -> Iterator[bytes]:
    """Split a byte string into chunks of at most chunk_size_bytes. Always yields at
    least one (possibly empty) chunk."""
    if not data:
        yield b""
        return
    for offset in range(0, len(data), chunk_size_bytes):
        yield data[offset : offset + chunk_size_bytes]


def combine_from_streaming(chunks: Iterable[bytes]) -> bytes:
    return b"".join(chunks)
