"""Nested structure flatten/pack/map — used by MoE schemas and state (de)serialization
(capability parity: reference hivemind/utils/nested.py). In jax-land most pytree work is
done by jax.tree_util; these helpers exist for torch-free host-side structures and to
pack flat RPC tensor lists back into structures.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator


def nested_flatten(t: Any) -> Iterator[Any]:
    """Yield leaves of a nested structure of dicts/lists/tuples in deterministic order."""
    if isinstance(t, (list, tuple)):
        for item in t:
            yield from nested_flatten(item)
    elif isinstance(t, dict):
        for key in sorted(t):
            yield from nested_flatten(t[key])
    else:
        yield t


def nested_pack(flat: Any, structure: Any) -> Any:
    """Inverse of nested_flatten: arrange leaves from ``flat`` into the shape of ``structure``."""
    return _nested_pack(iter(flat), structure)


def _nested_pack(flat_iter: Iterator[Any], structure: Any) -> Any:
    if isinstance(structure, (list, tuple)):
        return type(structure)(_nested_pack(flat_iter, item) for item in structure)
    if isinstance(structure, dict):
        return {key: _nested_pack(flat_iter, structure[key]) for key in sorted(structure)}
    return next(flat_iter)


def nested_map(fn: Callable[[Any], Any], *structures: Any) -> Any:
    """Apply fn to corresponding leaves of one or more identically-shaped structures."""
    if not structures:
        raise ValueError("nested_map needs at least one structure")
    head = structures[0]
    if isinstance(head, (list, tuple)):
        return type(head)(nested_map(fn, *items) for items in zip(*structures))
    if isinstance(head, dict):
        return {key: nested_map(fn, *(s[key] for s in structures)) for key in sorted(head)}
    return fn(*structures)


def nested_compare(t: Any, u: Any) -> bool:
    """True if two structures have the same nesting (leaf values are not compared)."""
    if isinstance(t, (list, tuple)) and isinstance(u, (list, tuple)):
        return type(t) == type(u) and len(t) == len(u) and all(
            nested_compare(a, b) for a, b in zip(t, u)
        )
    if isinstance(t, dict) and isinstance(u, dict):
        return t.keys() == u.keys() and all(nested_compare(t[k], u[k]) for k in t)
    return not isinstance(t, (list, tuple, dict)) and not isinstance(u, (list, tuple, dict))
