"""Tensor schemas: shape/dtype descriptors used by MoE expert signatures, averaging
schema hashes, and RPC (de)serialization (capability parity: reference
hivemind/utils/tensor_descr.py:27-135). jax-native: dtypes are canonical numpy/jax
dtype names (bfloat16 included), arrays are created with jax.numpy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np

DUMMY_BATCH_SIZE = 3  # batch size used when tracing expert schemas with dummy inputs


def _canonical_dtype_name(dtype: Any) -> str:
    """Normalize numpy/jax/str dtypes to a canonical string name ('float32', 'bfloat16', ...)."""
    if isinstance(dtype, str):
        name = dtype
    else:
        name = np.dtype(dtype).name if not _is_bfloat16(dtype) else "bfloat16"
    if name == "bfloat16":
        return name
    return np.dtype(name).name


def _is_bfloat16(dtype: Any) -> bool:
    try:
        import ml_dtypes

        return np.dtype(dtype) == np.dtype(ml_dtypes.bfloat16)
    except Exception:
        return str(dtype) == "bfloat16"


def numpy_dtype(name: str):
    """The numpy dtype object for a canonical name (supports bfloat16 via ml_dtypes)."""
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


@dataclasses.dataclass(frozen=True)
class TensorDescriptor:
    """Declarative description of an array: enough to allocate it or validate a peer's."""

    shape: Tuple[int, ...]
    dtype: str = "float32"
    requires_grad: bool = False
    compression: Optional[int] = None  # CompressionType value, see hivemind_tpu.compression

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(int(d) for d in self.shape))
        object.__setattr__(self, "dtype", _canonical_dtype_name(self.dtype))

    @classmethod
    def from_array(cls, array: Any, compression: Optional[int] = None) -> "TensorDescriptor":
        dtype = "bfloat16" if str(array.dtype) == "bfloat16" else str(np.dtype(array.dtype))
        requires_grad = bool(getattr(array, "requires_grad", False))
        return cls(tuple(array.shape), dtype, requires_grad, compression)

    @property
    def numel(self) -> int:
        out = 1
        for dim in self.shape:
            out *= dim
        return out

    @property
    def itemsize(self) -> int:
        return numpy_dtype(self.dtype).itemsize

    @property
    def nbytes(self) -> int:
        return self.numel * self.itemsize

    def make_zeros(self, backend: str = "numpy"):
        if backend == "jax":
            import jax.numpy as jnp

            return jnp.zeros(self.shape, dtype=self.dtype)
        return np.zeros(self.shape, dtype=numpy_dtype(self.dtype))

    def packb(self) -> bytes:
        from hivemind_tpu.utils.serializer import MSGPackSerializer

        return MSGPackSerializer.dumps(
            [list(self.shape), self.dtype, self.requires_grad, self.compression]
        )

    @classmethod
    def unpackb(cls, data: bytes) -> "TensorDescriptor":
        from hivemind_tpu.utils.serializer import MSGPackSerializer

        shape, dtype, requires_grad, compression = MSGPackSerializer.loads(data)
        return cls(tuple(shape), dtype, requires_grad, compression)


@dataclasses.dataclass(frozen=True)
class BatchTensorDescriptor(TensorDescriptor):
    """A TensorDescriptor whose leading (batch) dimension is unspecified: shape[0] is
    stored as 0 and means 'any batch size'."""

    def __post_init__(self):
        super().__post_init__()

    @classmethod
    def from_array(cls, array: Any, compression: Optional[int] = None) -> "BatchTensorDescriptor":
        base = TensorDescriptor.from_array(array, compression)
        return cls((0, *base.shape[1:]), base.dtype, base.requires_grad, compression)

    def with_batch_size(self, batch_size: int) -> TensorDescriptor:
        return TensorDescriptor((batch_size, *self.shape[1:]), self.dtype, self.requires_grad, self.compression)

    def make_dummy(self, backend: str = "numpy"):
        return self.with_batch_size(DUMMY_BATCH_SIZE).make_zeros(backend)


from hivemind_tpu.utils.serializer import MSGPackSerializer  # noqa: E402

MSGPackSerializer.ext_serializable(0x51)(TensorDescriptor)
MSGPackSerializer.ext_serializable(0x52)(BatchTensorDescriptor)
