"""Asymmetric crypto primitives for peer identity and signed DHT records.

The reference uses 2048-bit RSA with PSS+SHA256 (hivemind/utils/crypto.py:36-101).
This build uses Ed25519 — the modern libp2p default — which is ~100x faster to sign
and produces 64-byte signatures; an RSA implementation is kept for parity/interop of
the record-validator surface.
"""

from __future__ import annotations

import base64
import threading
from abc import ABC, abstractmethod
from typing import Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519, padding, rsa
except ImportError:  # no cryptography wheel on this image: system libcrypto shim
    from hivemind_tpu.utils import _libcrypto as _compat

    InvalidSignature = _compat.InvalidSignature
    hashes, serialization = _compat.hashes, _compat.serialization
    ed25519, padding, rsa = _compat.ed25519, _compat.padding, _compat.rsa


class PrivateKeyBase(ABC):
    @abstractmethod
    def sign(self, data: bytes) -> bytes: ...

    @abstractmethod
    def get_public_key(self) -> "PublicKeyBase": ...

    @abstractmethod
    def to_bytes(self) -> bytes: ...


class PublicKeyBase(ABC):
    @abstractmethod
    def verify(self, data: bytes, signature: bytes) -> bool: ...

    @abstractmethod
    def to_bytes(self) -> bytes: ...


class Ed25519PrivateKey(PrivateKeyBase):
    def __init__(self, key: Optional[ed25519.Ed25519PrivateKey] = None):
        self._key = key if key is not None else ed25519.Ed25519PrivateKey.generate()

    def sign(self, data: bytes) -> bytes:
        return base64.b64encode(self._key.sign(data))

    def get_public_key(self) -> "Ed25519PublicKey":
        return Ed25519PublicKey(self._key.public_key())

    def to_bytes(self) -> bytes:
        return self._key.private_bytes(
            encoding=serialization.Encoding.Raw,
            format=serialization.PrivateFormat.Raw,
            encryption_algorithm=serialization.NoEncryption(),
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        return cls(ed25519.Ed25519PrivateKey.from_private_bytes(data))

    _process_wide: Optional["Ed25519PrivateKey"] = None
    _process_wide_lock = threading.Lock()

    @classmethod
    def process_wide(cls) -> "Ed25519PrivateKey":
        """A singleton key shared by all components in this process (reference
        crypto.py:63-71 does the same for RSA)."""
        with cls._process_wide_lock:
            if cls._process_wide is None:
                cls._process_wide = cls()
            return cls._process_wide

    @classmethod
    def reset_process_wide(cls) -> None:
        with cls._process_wide_lock:
            cls._process_wide = None


class Ed25519PublicKey(PublicKeyBase):
    def __init__(self, key: ed25519.Ed25519PublicKey):
        self._key = key

    def verify(self, data: bytes, signature: bytes) -> bool:
        try:
            self._key.verify(base64.b64decode(signature), data)
            return True
        except (InvalidSignature, ValueError):
            return False

    def to_bytes(self) -> bytes:
        return self._key.public_bytes(
            encoding=serialization.Encoding.Raw, format=serialization.PublicFormat.Raw
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        return cls(ed25519.Ed25519PublicKey.from_public_bytes(data))


class RSAPrivateKey(PrivateKeyBase):
    def __init__(self, key: Optional[rsa.RSAPrivateKey] = None):
        self._key = key if key is not None else rsa.generate_private_key(65537, 2048)

    def sign(self, data: bytes) -> bytes:
        signature = self._key.sign(
            data,
            padding.PSS(mgf=padding.MGF1(hashes.SHA256()), salt_length=padding.PSS.MAX_LENGTH),
            hashes.SHA256(),
        )
        return base64.b64encode(signature)

    def get_public_key(self) -> "RSAPublicKey":
        return RSAPublicKey(self._key.public_key())

    def to_bytes(self) -> bytes:
        return self._key.private_bytes(
            encoding=serialization.Encoding.DER,
            format=serialization.PrivateFormat.PKCS8,
            encryption_algorithm=serialization.NoEncryption(),
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "RSAPrivateKey":
        key = serialization.load_der_private_key(data, password=None)
        assert isinstance(key, rsa.RSAPrivateKey)
        return cls(key)


class RSAPublicKey(PublicKeyBase):
    def __init__(self, key: rsa.RSAPublicKey):
        self._key = key

    def verify(self, data: bytes, signature: bytes) -> bool:
        try:
            self._key.verify(
                base64.b64decode(signature),
                data,
                padding.PSS(mgf=padding.MGF1(hashes.SHA256()), salt_length=padding.PSS.MAX_LENGTH),
                hashes.SHA256(),
            )
            return True
        except (InvalidSignature, ValueError):
            return False

    def to_bytes(self) -> bytes:
        return self._key.public_bytes(
            encoding=serialization.Encoding.DER,
            format=serialization.PublicFormat.SubjectPublicKeyInfo,
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "RSAPublicKey":
        key = serialization.load_der_public_key(data)
        assert isinstance(key, rsa.RSAPublicKey)
        return cls(key)
