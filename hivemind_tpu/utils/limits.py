"""Raise OS file-descriptor limits (capability parity: reference
hivemind/utils/limits.py) — swarm peers hold many sockets."""

from __future__ import annotations

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def increase_file_limit(new_soft: int = 2**15, new_hard: int = 2**15) -> None:
    """Best-effort bump of RLIMIT_NOFILE up to the allowed hard limit."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        target_hard = max(hard, new_hard) if hard == resource.RLIM_INFINITY or new_hard <= hard else hard
        target_soft = min(max(soft, new_soft), target_hard if target_hard != resource.RLIM_INFINITY else new_soft)
        if target_soft > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (target_soft, target_hard))
            logger.info(f"raised file limit: {soft} -> {target_soft}")
    except Exception as e:
        logger.warning(f"could not increase file limit: {e!r}")
