"""Raise OS file-descriptor limits (capability parity: reference
hivemind/utils/limits.py) — swarm peers hold many sockets."""

from __future__ import annotations

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def increase_file_limit(new_soft: int = 2**15, new_hard: int = 2**15) -> None:
    """Best-effort bump of RLIMIT_NOFILE up to the allowed hard limit."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if hard == resource.RLIM_INFINITY:
            # never LOWER an unlimited hard limit (RLIM_INFINITY is -1: naive max()
            # would irreversibly clamp it)
            target_hard = resource.RLIM_INFINITY
            target_soft = max(soft, new_soft)
        else:
            target_hard = hard
            target_soft = min(max(soft, new_soft), hard)
        if target_soft > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (target_soft, target_hard))
            logger.info(f"raised file limit: {soft} -> {target_soft}")
    except Exception as e:
        logger.warning(f"could not increase file limit: {e!r}")
