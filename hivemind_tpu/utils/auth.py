"""Authorization framework (capability parity: reference hivemind/utils/auth.py:33-212).

``TokenAuthorizerBase`` issues signed access tokens; ``AuthRPCWrapper`` wraps a
servicer so every rpc_* call is validated (SERVICER role) or stamped (CLIENT role).
Tokens are Ed25519-signed blobs with expiry, the caller's public key, and a nonce;
replay is rejected within a clock window (reference: ±1 min window, nonce cache)."""

from __future__ import annotations

import os
import threading
from abc import ABC, abstractmethod
from typing import Any, Optional

from hivemind_tpu.utils.crypto import Ed25519PrivateKey, Ed25519PublicKey
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import TimedStorage, get_dht_time

logger = get_logger(__name__)

MAX_CLIENT_SERVICER_TIME_DIFF = 60.0  # seconds (reference: ±1 minute clock window)


class AuthorizationError(RuntimeError):
    pass


class AuthorizerBase(ABC):
    @abstractmethod
    def issue_token(self) -> bytes:
        """Authority-side: create a token for oneself."""

    @abstractmethod
    def get_local_token(self) -> bytes:
        """The token to stamp on outgoing requests."""

    @abstractmethod
    def validate_token(self, token: bytes, sender_peer_id: Optional[Any] = None) -> bool:
        """Check a presented token, optionally bound to the authenticated sender."""


class TokenAuthorizerBase(AuthorizerBase):
    """Signed access tokens bound to a client identity.

    Roles: the AUTHORITY (holds the signing key) grants a token for a specific
    client's transport public key via ``issue_token_for``; a CLIENT holds its granted
    token (``set_access_token``) and stamps outgoing requests; a SERVICER validates
    tokens AND that the authenticated sender matches the identity the token was
    granted to — an intercepted token is useless from any other peer. Subclasses may
    fetch tokens from an external auth service (the reference's design intent)."""

    def __init__(
        self,
        authority_key: Optional[Ed25519PrivateKey] = None,
        local_key: Optional[Ed25519PrivateKey] = None,
        token_lifetime: float = 600.0,
    ):
        self.authority_key = authority_key
        self.authority_public = (
            authority_key.get_public_key() if authority_key is not None else None
        )
        self.local_key = local_key if local_key is not None else Ed25519PrivateKey.process_wide()
        self.token_lifetime = token_lifetime
        self.access_token: Optional[bytes] = None
        self._seen_nonces: TimedStorage[bytes, bool] = TimedStorage(maxsize=100_000)
        self._lock = threading.Lock()

    def set_authority_public_key(self, public_key: Ed25519PublicKey) -> None:
        self.authority_public = public_key

    def set_access_token(self, token: bytes) -> None:
        """Install a token granted by the authority (delivered out-of-band)."""
        try:
            payload, _sig = MSGPackSerializer.loads(token)
            _pub, expiry, _nonce = MSGPackSerializer.loads(payload)
            self._access_token_expiry = float(expiry)
        except Exception:
            self._access_token_expiry = None
        self.access_token = token

    def issue_token_for(self, client_public_key: Ed25519PublicKey) -> bytes:
        """Authority-side: grant a token bound to one client's transport identity."""
        assert self.authority_key is not None, "only the authority can issue tokens"
        payload = MSGPackSerializer.dumps(
            [client_public_key.to_bytes(), get_dht_time() + self.token_lifetime, os.urandom(16)]
        )
        return MSGPackSerializer.dumps([payload, self.authority_key.sign(payload)])

    def issue_token(self) -> bytes:
        """Authority issuing for itself (e.g. the authority is also a peer)."""
        return self.issue_token_for(self.local_key.get_public_key())

    def get_local_token(self) -> bytes:
        """The token this peer stamps on requests: the granted one, or self-issued if
        this peer IS the authority. Raises loudly when the granted token expired
        (silent stamping of a dead token would fail remotely with no local signal)."""
        if self.access_token is not None:
            expiry = getattr(self, "_access_token_expiry", None)
            if expiry is not None and get_dht_time() > expiry:
                raise AuthorizationError("access token expired; obtain a fresh one from the authority")
            return self.access_token
        if self.authority_key is not None:
            return self.issue_token()
        raise AuthorizationError("no access token: call set_access_token() with a granted token")

    def validate_token(self, token: bytes, sender_peer_id: Optional[Any] = None) -> bool:
        """Check signature, expiry, replay — and, when ``sender_peer_id`` is given,
        that the token was granted to that transport identity."""
        if self.authority_public is None:
            logger.warning("no authority public key configured; rejecting token")
            return False
        try:
            payload, signature = MSGPackSerializer.loads(token)
            if not self.authority_public.verify(payload, signature):
                return False
            client_pubkey_bytes, expiry, nonce = MSGPackSerializer.loads(payload)
        except Exception:
            return False
        now = get_dht_time()
        if expiry < now - MAX_CLIENT_SERVICER_TIME_DIFF:
            return False
        if sender_peer_id is not None:
            from hivemind_tpu.p2p.peer_id import PeerID

            try:
                granted_to = PeerID.from_public_key(Ed25519PublicKey.from_bytes(client_pubkey_bytes))
            except Exception:
                return False
            if granted_to != sender_peer_id:
                logger.debug("token granted to a different peer identity; rejected")
                return False
            # identity binding IS the anti-replay mechanism here: the transport
            # authenticated the sender, so the same token may be reused by its owner
            return True
        with self._lock:
            if nonce in self._seen_nonces:
                logger.debug("replayed auth token rejected")
                return False
            self._seen_nonces.store(nonce, True, expiry + MAX_CLIENT_SERVICER_TIME_DIFF)
        return True


class AuthRole:
    CLIENT = "client"
    SERVICER = "servicer"


class AuthRPCWrapper:
    """Wraps a servicer's rpc_* methods (reference AuthRPCWrapper): in SERVICER role,
    requests whose ``peer.auth_token`` fails validation are rejected; in CLIENT role,
    outgoing requests get a fresh token stamped into ``peer.auth_token``."""

    def __init__(self, stub_or_servicer: Any, role: str, authorizer: AuthorizerBase):
        self._wrapped = stub_or_servicer
        self._role = role
        self._authorizer = authorizer

    def __getattr__(self, name: str):
        import inspect

        attr = getattr(self._wrapped, name)
        if not name.startswith("rpc_") or not callable(attr):
            return attr
        role, authorizer = self._role, self._authorizer

        def _check_or_stamp(message, context) -> None:
            sender = getattr(context, "remote_id", None)
            if role == AuthRole.SERVICER:
                token = getattr(getattr(message, "peer", None), "auth_token", b"")
                if not authorizer.validate_token(token, sender_peer_id=sender):
                    raise AuthorizationError(f"{name}: missing or invalid access token")
            elif role == AuthRole.CLIENT:
                peer = getattr(message, "peer", None)
                if peer is not None:
                    peer.auth_token = authorizer.get_local_token()

        async def _prepare(request, args):
            """Stream-input RPCs pass an iterator as the first argument: the auth
            check happens EAGERLY on the first message, before the handler runs (an
            empty or stalling stream must not reach the handler unauthenticated)."""
            context = args[0] if args else None
            if hasattr(request, "__aiter__"):
                iterator = request.__aiter__()
                try:
                    first_message = await iterator.__anext__()
                except StopAsyncIteration:
                    raise AuthorizationError(f"{name}: empty request stream") from None
                _check_or_stamp(first_message, context)

                async def chained():
                    yield first_message
                    async for message in iterator:
                        yield message

                return chained()
            _check_or_stamp(request, context)
            return request

        if inspect.isasyncgenfunction(attr):

            async def stream_wrapped(request, *args, **kwargs):
                request = await _prepare(request, args)
                async for item in attr(request, *args, **kwargs):
                    yield item

            return stream_wrapped

        async def wrapped(request, *args, **kwargs):
            request = await _prepare(request, args)
            result = attr(request, *args, **kwargs)
            if hasattr(result, "__aiter__"):
                # a stub's stream-output caller returns an async iterator, not a coroutine
                return result
            return await result

        return wrapped
