"""Authorization framework (capability parity: reference hivemind/utils/auth.py:33-212).

``TokenAuthorizerBase`` issues signed access tokens; ``AuthRPCWrapper`` wraps a
servicer so every rpc_* call is validated (SERVICER role) or stamped (CLIENT role).
Tokens are Ed25519-signed blobs with expiry, the caller's public key, and a nonce;
replay is rejected within a clock window (reference: ±1 min window, nonce cache)."""

from __future__ import annotations

import os
import threading
from abc import ABC, abstractmethod
from typing import Any, Optional

from hivemind_tpu.utils.crypto import Ed25519PrivateKey, Ed25519PublicKey
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import TimedStorage, get_dht_time

logger = get_logger(__name__)

MAX_CLIENT_SERVICER_TIME_DIFF = 60.0  # seconds (reference: ±1 minute clock window)


class AuthorizationError(RuntimeError):
    pass


class AuthorizerBase(ABC):
    @abstractmethod
    def issue_token(self) -> bytes: ...

    @abstractmethod
    def validate_token(self, token: bytes) -> bool: ...


class TokenAuthorizerBase(AuthorizerBase):
    """Self-issued signed tokens: [client_pubkey, expiry, nonce] signed by the trust
    authority's key. Subclasses may fetch tokens from an external auth server instead
    (the reference's design intent)."""

    def __init__(
        self,
        authority_key: Optional[Ed25519PrivateKey] = None,
        local_key: Optional[Ed25519PrivateKey] = None,
        token_lifetime: float = 600.0,
    ):
        self.authority_key = authority_key
        self.authority_public = (
            authority_key.get_public_key() if authority_key is not None else None
        )
        self.local_key = local_key if local_key is not None else Ed25519PrivateKey.process_wide()
        self.token_lifetime = token_lifetime
        self._seen_nonces: TimedStorage[bytes, bool] = TimedStorage(maxsize=100_000)
        self._lock = threading.Lock()

    def set_authority_public_key(self, public_key: Ed25519PublicKey) -> None:
        self.authority_public = public_key

    def issue_token(self) -> bytes:
        assert self.authority_key is not None, "only the authority can issue tokens"
        payload = MSGPackSerializer.dumps(
            [
                self.local_key.get_public_key().to_bytes(),
                get_dht_time() + self.token_lifetime,
                os.urandom(16),
            ]
        )
        return MSGPackSerializer.dumps([payload, self.authority_key.sign(payload)])

    def validate_token(self, token: bytes) -> bool:
        if self.authority_public is None:
            logger.warning("no authority public key configured; rejecting token")
            return False
        try:
            payload, signature = MSGPackSerializer.loads(token)
            if not self.authority_public.verify(payload, signature):
                return False
            _client_pubkey, expiry, nonce = MSGPackSerializer.loads(payload)
        except Exception:
            return False
        now = get_dht_time()
        if expiry < now - MAX_CLIENT_SERVICER_TIME_DIFF:
            return False
        with self._lock:
            if nonce in self._seen_nonces:
                logger.debug("replayed auth token rejected")
                return False
            self._seen_nonces.store(nonce, True, expiry + MAX_CLIENT_SERVICER_TIME_DIFF)
        return True


class AuthRole:
    CLIENT = "client"
    SERVICER = "servicer"


class AuthRPCWrapper:
    """Wraps a servicer's rpc_* methods (reference AuthRPCWrapper): in SERVICER role,
    requests whose ``peer.auth_token`` fails validation are rejected; in CLIENT role,
    outgoing requests get a fresh token stamped into ``peer.auth_token``."""

    def __init__(self, stub_or_servicer: Any, role: str, authorizer: AuthorizerBase):
        self._wrapped = stub_or_servicer
        self._role = role
        self._authorizer = authorizer

    def __getattr__(self, name: str):
        import inspect

        attr = getattr(self._wrapped, name)
        if not name.startswith("rpc_") or not callable(attr):
            return attr
        role, authorizer = self._role, self._authorizer

        def _check_or_stamp(request) -> None:
            if role == AuthRole.SERVICER:
                token = getattr(getattr(request, "peer", None), "auth_token", b"")
                if not authorizer.validate_token(token):
                    raise AuthorizationError(f"{name}: missing or invalid access token")
            elif role == AuthRole.CLIENT:
                peer = getattr(request, "peer", None)
                if peer is not None:
                    peer.auth_token = authorizer.issue_token()

        if inspect.isasyncgenfunction(attr):

            async def stream_wrapped(request, *args, **kwargs):
                _check_or_stamp(request)
                async for item in attr(request, *args, **kwargs):
                    yield item

            return stream_wrapped

        async def wrapped(request, *args, **kwargs):
            _check_or_stamp(request)
            return await attr(request, *args, **kwargs)

        return wrapped
