from hivemind_tpu.utils.asyncio_utils import (
    achain,
    aenumerate,
    aiter_with_timeout,
    amap_in_executor,
    anext_safe,
    as_aiter,
    attach_event_on_finished,
    azip,
    cancel_and_wait,
    enter_asynchronously,
    spawn,
    switch_to_uvloop,
)
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.loop import LoopRunner, get_loop_runner
from hivemind_tpu.utils.nested import (
    nested_compare,
    nested_flatten,
    nested_map,
    nested_pack,
)
from hivemind_tpu.utils.performance_ema import PerformanceEMA
from hivemind_tpu.utils.profiling import (
    StepProfiler,
    device_memory_stats,
    profile_to,
    trace_span,
)
from hivemind_tpu.utils.serializer import MSGPackSerializer, SerializerBase
from hivemind_tpu.utils.streaming import combine_from_streaming, split_for_streaming
from hivemind_tpu.utils.tensor_descr import BatchTensorDescriptor, TensorDescriptor
from hivemind_tpu.utils.timed_storage import (
    MAX_DHT_TIME_DISCREPANCY_SECONDS,
    DHTExpiration,
    TimedStorage,
    ValueWithExpiration,
    get_dht_time,
)
