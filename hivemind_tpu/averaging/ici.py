"""MeshAverager: a DecentralizedAverager whose averaged state lives SHARDED on a
jax device mesh — the bridge between the swarm (internet) tier and the ICI tier of
the two-tier communication backend (SURVEY §5).

One mesh = one logical swarm peer. Per round:

1. ``_pre_allreduce`` — the mesh-resident tree is staged to the host mirrors:
   an optional on-device ``pmean`` (ICI psum under shard_map) collapses per-replica
   values, then an XLA all-gather assembles each leaf once on the host. This replaces
   the reference's host-side part accumulation (hivemind/averaging/partition.py:242-260)
   with XLA collectives for everything inside the peer.
2. The inherited butterfly all-reduce averages the host mirrors across swarm peers
   over the network, exactly as for host-resident averagers.
3. ``_post_allreduce`` — the averaged mirrors are scattered back onto the mesh with
   the original shardings (each device receives only its shard).

The device tree is any pytree of jax Arrays (params, grads, opt state). With
``local_reduce_axis`` set, every leaf carries a leading per-replica dimension sharded
over that mesh axis (the jax encoding of "each data-parallel replica holds its own
copy"); the swarm contribution is the ICI mean and, post-round, every replica adopts
the swarm average."""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Optional

import jax

from hivemind_tpu.averaging.averager import DecentralizedAverager
from hivemind_tpu.dht import DHT
from hivemind_tpu.parallel.ici import MeshTensorBridge
from hivemind_tpu.utils.asyncio_utils import enter_asynchronously
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class MeshAverager(DecentralizedAverager):
    """See module docstring.

    :param device_tree: pytree of (possibly sharded) jax Arrays averaged with the swarm
    :param mesh: the jax Mesh this peer's state is sharded over
    :param local_reduce_axis: if set, leaves are per-replica stacks over this mesh
        axis; the peer's swarm contribution is their on-device mean (ICI psum)
    """

    def __init__(
        self,
        device_tree: Any,
        mesh,
        dht: DHT,
        *,
        local_reduce_axis: Optional[str] = None,
        external_staging: bool = False,
        **kwargs,
    ):
        self.bridge = MeshTensorBridge(mesh)
        self.local_reduce_axis = local_reduce_axis
        # multi-host slices (averaging/slice.py): staging/scatter are COLLECTIVE
        # jax operations that every process must join, so SliceAverager drives
        # them at synchronized points instead of the round's async hooks
        self.external_staging = external_staging
        self._device_tree = device_tree
        self._tree_lock = threading.Lock()
        # one mesh = one logical peer, so its advertised bandwidth to the LP load
        # balancer is the slice's AGGREGATE egress (SURVEY §5: a slice's swarm
        # bandwidth scales with its HOST count), unless the caller overrides it
        if kwargs.get("bandwidth") is None:
            num_hosts = len({device.process_index for device in mesh.devices.flat})
            kwargs["bandwidth"] = 1.0e8 * max(num_hosts, 1)
        host_tensors = self.bridge.gather_reduced_to_host(device_tree, reduce_axis=local_reduce_axis)
        super().__init__(host_tensors, dht, **kwargs)

    # ---------------------------------------------------------------- device tree

    def _reduced_tree(self, tree: Any) -> Any:
        if self.local_reduce_axis is not None:
            return self.bridge.mesh_mean(tree, self.local_reduce_axis)
        return tree

    @property
    def device_tree(self) -> Any:
        with self._tree_lock:
            return self._device_tree

    @device_tree.setter
    def device_tree(self, tree: Any) -> None:
        with self._tree_lock:
            self._device_tree = tree

    # ---------------------------------------------------------------- round hooks

    def _stage_to_host(self) -> None:
        """Blocking half of _pre_allreduce (runs in the executor): per-leaf ICI
        reduce streamed shard-by-shard DIRECTLY into the host mirrors — no
        on-device replication, no transient second host copy, and the reduced tree
        is never materialized whole (one reduced leaf in flight; VERDICT r2 weak #3
        + r3 #4)."""
        with self._tree_lock:
            tree = self._device_tree
        with self.lock_averaged_tensors:
            self.bridge.stage_reduced_into_mirrors(
                tree, self._averaged_tensors, reduce_axis=self.local_reduce_axis
            )

    def _scatter_to_mesh(self) -> None:
        """Blocking half of _post_allreduce: push averaged mirrors back as shards,
        one leaf at a time (peak transient host memory = one leaf, not one model)."""
        axis_size = (
            self.bridge.mesh.shape[self.local_reduce_axis]
            if self.local_reduce_axis is not None
            else None
        )
        with self._tree_lock:
            leaves, treedef = jax.tree_util.tree_flatten(self._device_tree)
            new_leaves = []
            with self.lock_averaged_tensors:
                assert len(leaves) == len(self._averaged_tensors)
                for leaf, mirror in zip(leaves, self._averaged_tensors):
                    # per-leaf copy: device_put reads the buffer asynchronously, so
                    # the mirror itself must stay mutable for the next round
                    new_leaves.append(
                        self.bridge.scatter_leaf(leaf, mirror.copy(), stack_axis_size=axis_size)
                    )
            self._device_tree = jax.tree_util.tree_unflatten(treedef, new_leaves)

    async def _pre_allreduce(self) -> None:
        if not self.external_staging:
            await asyncio.get_event_loop().run_in_executor(None, self._stage_to_host)

    async def _post_allreduce(self) -> None:
        if not self.external_staging:
            await asyncio.get_event_loop().run_in_executor(None, self._scatter_to_mesh)
