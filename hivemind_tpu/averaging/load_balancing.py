"""Bandwidth-aware partitioning of the butterfly all-reduce
(capability parity: reference hivemind/averaging/load_balancing.py).

Peer i reduces a fraction f_i of the concatenated vector. Its wire traffic is
(n-1)·f_i·S inbound parts + (n-1)·f_i·S outbound deltas + (1-f_i)·S sent + (1-f_i)·S
received, so time_i ∝ ((n-2)·f_i + 1)/bandwidth_i. We minimize the max over peers
(minimax LP, reference optimize_parts_lp at load_balancing.py:36-86), then round the
fractions to integer part counts by largest remainder (Hagenbach-Bischoff,
reference 89-105). Zero-bandwidth peers (client mode) get zero parts.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def optimize_parts_lp(vector_size: int, bandwidths: np.ndarray, min_size: int = 0) -> np.ndarray:
    """Solve the minimax LP for load fractions. Returns fractions summing to 1."""
    group_size = len(bandwidths)
    active = bandwidths > 0
    if not np.any(active):
        raise ValueError("all peers have zero bandwidth: nobody can reduce")
    if active.sum() == 1:
        return active.astype(np.float64)

    # variables: [f_0 … f_{n-1}, t]; minimize t
    # constraints: ((n-2)·f_i + 1) / b_i ≤ t  for active i;  Σf = 1;  f_i ≥ 0; f_inactive = 0
    from scipy.optimize import linprog

    n = group_size
    c = np.zeros(n + 1)
    c[-1] = 1.0
    a_ub = np.zeros((int(active.sum()), n + 1))
    b_ub = np.zeros(int(active.sum()))
    row = 0
    for i in range(n):
        if not active[i]:
            continue
        a_ub[row, i] = max(n - 2, 1) / bandwidths[i]
        a_ub[row, -1] = -1.0
        b_ub[row] = -1.0 / bandwidths[i]
        row += 1
    a_eq = np.zeros((1, n + 1))
    a_eq[0, :n] = 1.0
    b_eq = [1.0]
    bounds = [(0.0, None) if active[i] else (0.0, 0.0) for i in range(n)] + [(0.0, None)]
    result = linprog(c, A_ub=a_ub, b_ub=b_ub, A_eq=a_eq, b_eq=b_eq, bounds=bounds, method="highs")
    if not result.success:
        logger.warning(f"load-balancing LP failed ({result.message}); falling back to proportional split")
        fractions = np.where(active, bandwidths, 0.0)
        return fractions / fractions.sum()
    fractions = np.clip(result.x[:-1], 0.0, None)
    total = fractions.sum()
    return fractions / total if total > 0 else np.where(active, 1.0 / active.sum(), 0.0)


def hagenbach_bischoff(num_parts: int, fractions: np.ndarray) -> np.ndarray:
    """Largest-remainder apportionment of num_parts into integer counts ∝ fractions."""
    ideal = fractions * num_parts
    counts = np.floor(ideal).astype(np.int64)
    remainder = num_parts - counts.sum()
    if remainder > 0:
        order = np.argsort(-(ideal - counts))
        counts[order[:remainder]] += 1
    return counts


def load_balance_peers(
    vector_size: int, bandwidths: Sequence[Optional[float]], min_size: int = 0
) -> Tuple[int, ...]:
    """Main entry (reference load_balancing.py:13-33): ``bandwidths`` entries are
    floats (reducer capacity) or None/0 for client-mode peers. Returns per-peer part
    counts out of ``vector_size`` elements."""
    bandwidth_array = np.array([b if b is not None else 0.0 for b in bandwidths], dtype=np.float64)
    if np.any(bandwidth_array > 0):
        fractions = optimize_parts_lp(vector_size, bandwidth_array, min_size)
    else:
        raise ValueError("group has no peers capable of reducing (all client-mode?)")
    counts = hagenbach_bischoff(vector_size, fractions)
    # peers whose share fell below min_size contribute nothing; redistribute
    if min_size > 0:
        starved = (counts > 0) & (counts < min_size)
        if np.any(starved):
            freed = counts[starved].sum()
            counts[starved] = 0
            if counts.sum() > 0:
                top = np.argmax(counts)
                counts[top] += freed
    assert counts.sum() == vector_size
    return tuple(int(c) for c in counts)
