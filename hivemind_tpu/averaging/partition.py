"""Tensor partitioning for butterfly all-reduce (capability parity: reference
hivemind/averaging/partition.py).

``TensorPartContainer`` flattens a tensor list into one logical stream, slices it
into per-peer spans (element counts from the load balancer) and further into parts of
at most ``part_size_bytes``; compression runs in the shared executor with bounded
prefetch. ``TensorPartReducer`` accumulates incoming parts for the span this peer
reduces, with weighted averaging and denominator shrinking when senders fail."""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.compression import CompressionBase, CompressionInfo, NoCompression, deserialize_tensor, serialize_tensor
from hivemind_tpu.compression.base import as_numpy
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.utils.asyncio_utils import amap_in_executor, as_aiter
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_PART_SIZE_BYTES = 2**19  # 512 KiB pre-compression (reference partition.py:17)


def compute_span_part_sizes(element_count: int, part_size_bytes: int) -> List[int]:
    """Split one peer's reduction span into part sizes. THE single source of truth for
    part boundaries — senders (TensorPartContainer) and reducers (incl. AUX peers with
    no container) must agree byte-for-byte. Parts travel as fp32."""
    part_elements = max(1, part_size_bytes // 4)
    sizes = []
    remaining = element_count
    while remaining > 0:
        sizes.append(min(part_elements, remaining))
        remaining -= sizes[-1]
    return sizes


class AllreduceException(RuntimeError):
    pass


class TensorPartContainer:
    """Splits tensors into per-peer parts and reassembles processed deltas.

    :param tensors: the local tensors (numpy or jax; flattened copy is taken in fp32)
    :param peer_element_counts: elements assigned to each peer (sums to total numel)
    """

    def __init__(
        self,
        tensors: Sequence,
        peer_element_counts: Sequence[int],
        compression: CompressionBase = NoCompression(),
        part_size_bytes: int = DEFAULT_PART_SIZE_BYTES,
        tensor_infos: Optional[Sequence[CompressionInfo]] = None,
        prefetch: int = 4,
    ):
        self.tensors = [as_numpy(t) for t in tensors]
        self.peer_element_counts = tuple(peer_element_counts)
        self.compression = compression
        self.part_size_elements = max(1, part_size_bytes // 4)  # parts travel as fp32
        self.tensor_infos = tensor_infos
        total = sum(int(np.prod(t.shape)) for t in self.tensors)
        assert sum(peer_element_counts) == total, (sum(peer_element_counts), total)
        self.total_elements = total

        self._flat = np.concatenate([t.reshape(-1).astype(np.float32) for t in self.tensors]) if total else np.zeros(0, np.float32)
        # per-peer list of (start, stop) part spans in the flat stream
        self.parts_by_peer: List[List[Tuple[int, int]]] = []
        offset = 0
        for count in self.peer_element_counts:
            spans = []
            for size in compute_span_part_sizes(count, part_size_bytes):
                spans.append((offset, offset + size))
                offset += size
            self.parts_by_peer.append(spans)
        self.num_parts_by_peer = tuple(len(spans) for spans in self.parts_by_peer)

        self._delta = np.zeros_like(self._flat)
        self._part_ready: Dict[Tuple[int, int], asyncio.Event] = {}
        self._peer_failed = [False] * len(self.peer_element_counts)
        self.failed_size = 0
        self._finished = asyncio.Event()

    def get_raw_input_parts(self, peer_index: int) -> List[np.ndarray]:
        return [self._flat[start:stop] for start, stop in self.parts_by_peer[peer_index]]

    async def iterate_input_parts_for(self, peer_index: int) -> AsyncIterator[runtime_pb2.Tensor]:
        """Serialized parts destined for one peer; compression happens in the shared
        thread pool with prefetch (reference partition.py:104-112)."""
        parts = self.get_raw_input_parts(peer_index)

        def _compress(part: np.ndarray) -> runtime_pb2.Tensor:
            return serialize_tensor(part, self.compression)

        async for serialized in amap_in_executor(_compress, as_aiter(*parts), max_prefetch=4):
            yield serialized

    def register_processed_part(self, peer_index: int, part_index: int, delta_part: np.ndarray) -> None:
        """Store the delta (averaged − input) for one part."""
        start, stop = self.parts_by_peer[peer_index][part_index]
        expected = stop - start
        if delta_part.size != expected:
            raise AllreduceException(
                f"part size mismatch from peer {peer_index}: got {delta_part.size}, expected {expected}"
            )
        self._delta[start:stop] = delta_part.reshape(-1)
        self._mark_ready(peer_index, part_index)

    def register_failed_reducer(self, peer_index: int) -> None:
        """A reducer died: its unprocessed parts keep the local value (delta = 0)
        and count toward failed_size (reference partition.py:128-136)."""
        if self._peer_failed[peer_index]:
            return
        self._peer_failed[peer_index] = True
        for part_index, (start, stop) in enumerate(self.parts_by_peer[peer_index]):
            key = (peer_index, part_index)
            event = self._part_ready.get(key)
            if event is None or not event.is_set():
                self.failed_size += stop - start
                self._mark_ready(peer_index, part_index)

    def _mark_ready(self, peer_index: int, part_index: int) -> None:
        key = (peer_index, part_index)
        event = self._part_ready.setdefault(key, asyncio.Event())
        event.set()

    async def _wait_part(self, peer_index: int, part_index: int) -> None:
        key = (peer_index, part_index)
        event = self._part_ready.setdefault(key, asyncio.Event())
        await event.wait()

    async def iterate_output_tensors(self) -> AsyncIterator[np.ndarray]:
        """Yield per-tensor DELTAS (float32, original shape) as soon as all parts
        covering each tensor have arrived (reference partition.py:138-160)."""
        # map flat offsets back to (peer, part) completion events, in stream order
        ordered_parts = [
            (peer_index, part_index, start, stop)
            for peer_index, spans in enumerate(self.parts_by_peer)
            for part_index, (start, stop) in enumerate(spans)
        ]
        ordered_parts.sort(key=lambda item: item[2])
        cursor = 0  # next ordered part not yet awaited
        offset = 0
        for tensor in self.tensors:
            numel = int(np.prod(tensor.shape))
            tensor_end = offset + numel
            while cursor < len(ordered_parts) and ordered_parts[cursor][2] < tensor_end:
                peer_index, part_index, _start, _stop = ordered_parts[cursor]
                await self._wait_part(peer_index, part_index)
                cursor += 1
            yield self._delta[offset:tensor_end].reshape(tensor.shape)
            offset = tensor_end
        self._finished.set()

    def __repr__(self):
        return (
            f"TensorPartContainer({len(self.tensors)} tensors, {self.total_elements} elements, "
            f"parts_by_peer={self.num_parts_by_peer})"
        )


class TensorPartReducer:
    """Accumulates incoming parts for the span THIS peer reduces
    (reference partition.py:179-286)."""

    def __init__(self, part_shapes: Sequence[Tuple[int, ...]], num_senders: int):
        self.part_shapes = list(part_shapes)
        self.num_senders = num_senders
        self.sender_failed = [False] * num_senders
        # per-part: accumulator, total weight, contributed sender flags, done future
        self._parts: Dict[int, dict] = {}
        self._closed = False

    def _part_state(self, part_index: int) -> dict:
        if part_index not in self._parts:
            if not (0 <= part_index < len(self.part_shapes)):
                raise AllreduceException(f"invalid part index {part_index}")
            self._parts[part_index] = dict(
                accumulator=np.zeros(self.part_shapes[part_index], np.float32),
                total_weight=0.0,
                contributed=[False] * self.num_senders,
                future=asyncio.get_event_loop().create_future(),
            )
        return self._parts[part_index]

    @property
    def num_active_senders(self) -> int:
        return sum(not failed for failed in self.sender_failed)

    async def accumulate_part(
        self, sender_index: int, part_index: int, part: np.ndarray, weight: float = 1.0
    ) -> np.ndarray:
        """Add one sender's part; resolves to the weighted average once every active
        sender has contributed."""
        if self._closed:
            raise AllreduceException("reducer is closed")
        state = self._part_state(part_index)
        if state["contributed"][sender_index]:
            raise AllreduceException(f"sender {sender_index} sent part {part_index} twice")
        part32 = part.reshape(state["accumulator"].shape).astype(np.float32)
        state["accumulator"] += part32 * weight
        state["total_weight"] += weight
        state["contributed"][sender_index] = True
        self._maybe_finish(part_index)
        return await asyncio.shield(state["future"])

    def on_sender_failed(self, sender_index: int) -> None:
        """Shrink denominators for parts the dead sender had not contributed to
        (reference partition.py:248-255)."""
        if self.sender_failed[sender_index]:
            return
        self.sender_failed[sender_index] = True
        for part_index in range(len(self.part_shapes)):
            # started parts re-check completion; if ALL senders are gone, untouched
            # parts must fail immediately instead of hanging their awaiters
            if part_index in self._parts or self.num_active_senders == 0:
                self._maybe_finish(part_index)

    def _maybe_finish(self, part_index: int) -> None:
        if part_index not in self._parts and self.num_active_senders == 0:
            # everyone died before sending this part
            state = self._part_state(part_index)
            if not state["future"].done():
                state["future"].set_exception(AllreduceException("all senders failed"))
            return
        if part_index not in self._parts:
            return
        state = self._parts[part_index]
        if state["future"].done():
            return
        pending = [
            i for i in range(self.num_senders) if not state["contributed"][i] and not self.sender_failed[i]
        ]
        if pending:
            return
        if state["total_weight"] <= 0:
            state["future"].set_exception(AllreduceException(f"part {part_index}: no live contributions"))
            return
        state["future"].set_result(state["accumulator"] / state["total_weight"])

    # -------------------------------------------------------------- public queries
    # (the allreduce stream handler and laggard watchdog must observe reduction
    # state without touching the accumulator internals — this is the interface that
    # survives rewiring, VERDICT r1 "encapsulation leak")

    def result_nowait(self, part_index: int) -> Optional[np.ndarray]:
        """The averaged part if it resolved successfully already, else None."""
        state = self._parts.get(part_index)
        if state is None or not state["future"].done() or state["future"].cancelled():
            return None
        if state["future"].exception() is not None:
            return None
        return state["future"].result()

    def pending_senders(self, part_index: int) -> List[int]:
        """Ranks that have NOT contributed to a STARTED part and are still alive
        (empty for parts nobody started — there is no laggard to blame yet)."""
        state = self._parts.get(part_index)
        if state is None:
            return []
        return [
            rank
            for rank in range(self.num_senders)
            if not state["contributed"][rank] and not self.sender_failed[rank]
        ]

    async def wait_part(self, part_index: int, timeout: Optional[float] = None) -> np.ndarray:
        """Await one part's average (shielded: many callers may wait on the same
        future). Raises asyncio.TimeoutError / AllreduceException."""
        state = self._part_state(part_index)
        return await asyncio.wait_for(asyncio.shield(state["future"]), timeout=timeout)

    def finalize(self) -> None:
        self._closed = True
        for state in self._parts.values():
            if not state["future"].done():
                state["future"].set_exception(AllreduceException("reducer finalized early"))
