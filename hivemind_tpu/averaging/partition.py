"""Tensor partitioning for butterfly all-reduce (capability parity: reference
hivemind/averaging/partition.py).

``TensorPartContainer`` exposes a tensor list as one logical fp32 stream, slices it
into per-peer spans (element counts from the load balancer) and further into parts of
at most ``part_size_bytes``; compression runs in the shared executor with bounded
prefetch. ``TensorPartReducer`` accumulates incoming parts for the span this peer
reduces, with weighted averaging and denominator shrinking when senders fail.

Throughput notes (ISSUE 6): the container never materializes the concatenated
stream — it keeps per-tensor fp32 views (``astype(copy=False)``: zero-copy when the
input is already fp32) plus an offset index, so only the rare part that straddles a
tensor boundary is assembled with a copy. Parts that live in container-private
memory (dtype-conversion copies or boundary assemblies) are compressed with
``allow_inplace=True``. The reducer accumulates with ``np.add(..., out=...)`` into
the accumulator, stages weighted parts in one reusable scratch buffer, and divides
in place — no per-part temporaries. All replaced ops are bit-identical to the
naive forms (same fp32 instructions in the same order).

Quantized wire tiers (ISSUE 11): each peer's parts may travel under a
**per-link wire codec** (``peer_links``) negotiated at matchmaking time instead
of the single group-wide codec. Links on a lossy tier compress through
:func:`~hivemind_tpu.averaging.residual.compress_with_feedback` against the
averager-owned send-leg residual plane (error feedback, indexed by global
stream offset), and their processed results come back as **absolute averaged
values** (:meth:`TensorPartContainer.register_processed_absolute`) rather than
deltas — the sender subtracts its own input locally. Lossless links are
untouched: same codec instance, same ``allow_inplace`` policy, byte-identical
wire parts (pinned by tests/test_partition_equivalence.py)."""

from __future__ import annotations

import asyncio
import bisect
from typing import AsyncIterator, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.compression import CompressionBase, CompressionInfo, NoCompression, deserialize_tensor, serialize_tensor
from hivemind_tpu.compression.base import as_numpy
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.utils.asyncio_utils import amap_in_executor, as_aiter
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# pre-compression part size. The reference default is 512 KiB (partition.py:17);
# 2 MiB measures ~35% faster end-to-end on the loopback averaging benchmark (fewer
# per-part serialize/frame/seal round trips for the same bytes — benchmarks/RESULTS.md
# ISSUE 6) and still fits the mux message cap with fp32 headroom after compression.
# Part boundaries do not affect numerics: per-element accumulation order is the same.
DEFAULT_PART_SIZE_BYTES = 2**21


def compute_span_part_sizes(element_count: int, part_size_bytes: int) -> List[int]:
    """Split one peer's reduction span into part sizes. THE single source of truth for
    part boundaries — senders (TensorPartContainer) and reducers (incl. AUX peers with
    no container) must agree byte-for-byte. Parts travel as fp32."""
    part_elements = max(1, part_size_bytes // 4)
    sizes = []
    remaining = element_count
    while remaining > 0:
        sizes.append(min(part_elements, remaining))
        remaining -= sizes[-1]
    return sizes


class AllreduceException(RuntimeError):
    pass


class TensorPartContainer:
    """Splits tensors into per-peer parts and reassembles processed deltas.

    :param tensors: the local tensors (numpy or jax; viewed as fp32 without copying
        when possible)
    :param peer_element_counts: elements assigned to each peer (sums to total numel)
    :param prefetch: how many parts may be serialized ahead of the network consumer
    :param peer_links: optional per-peer negotiated wire links
        (:class:`~hivemind_tpu.averaging.wire_codec.WireLink` or None per peer);
        None entries fall back to ``compression``
    :param residuals: the averager's error-feedback store; required for links
        with ``error_feedback`` set
    """

    def __init__(
        self,
        tensors: Sequence,
        peer_element_counts: Sequence[int],
        compression: CompressionBase = NoCompression(),
        part_size_bytes: int = DEFAULT_PART_SIZE_BYTES,
        tensor_infos: Optional[Sequence[CompressionInfo]] = None,
        prefetch: int = 4,
        peer_links: Optional[Sequence] = None,
        residuals=None,
    ):
        assert prefetch > 0, "prefetch must be positive"
        self.tensors = [as_numpy(t) for t in tensors]
        self.peer_element_counts = tuple(peer_element_counts)
        self.compression = compression
        self.part_size_elements = max(1, part_size_bytes // 4)  # parts travel as fp32
        self.tensor_infos = tensor_infos
        self.prefetch = prefetch
        if peer_links is not None:
            assert len(peer_links) == len(self.peer_element_counts)
        self.peer_links = list(peer_links) if peer_links is not None else None
        self.residuals = residuals
        total = sum(int(np.prod(t.shape)) for t in self.tensors)
        assert sum(peer_element_counts) == total, (sum(peer_element_counts), total)
        self.total_elements = total

        # per-tensor fp32 flat views over the logical stream (no global concat);
        # a flat is "private" when conversion already forced a copy, which makes
        # in-place compression of its parts safe (the caller's memory is untouched
        # and every element belongs to exactly one part, read exactly once)
        self._tensor_flats: List[np.ndarray] = []
        self._flat_private: List[bool] = []
        self._tensor_offsets: List[int] = []  # start offset of each tensor in the stream
        offset = 0
        for tensor in self.tensors:
            flat32 = tensor.reshape(-1).astype(np.float32, copy=False)
            self._tensor_flats.append(flat32)
            self._flat_private.append(not np.may_share_memory(flat32, tensor))
            self._tensor_offsets.append(offset)
            offset += flat32.size

        # per-peer list of (start, stop) part spans in the flat stream
        self.parts_by_peer: List[List[Tuple[int, int]]] = []
        offset = 0
        for count in self.peer_element_counts:
            spans = []
            for size in compute_span_part_sizes(count, part_size_bytes):
                spans.append((offset, offset + size))
                offset += size
            self.parts_by_peer.append(spans)
        self.num_parts_by_peer = tuple(len(spans) for spans in self.parts_by_peer)

        # deltas accumulate per tensor (same total footprint as one flat buffer)
        self._tensor_deltas = [np.zeros(flat.size, np.float32) for flat in self._tensor_flats]
        self._part_ready: Dict[Tuple[int, int], asyncio.Event] = {}
        self._peer_failed = [False] * len(self.peer_element_counts)
        self.failed_size = 0
        self._finished = asyncio.Event()

    def _stream_slices(self, start: int, stop: int) -> Iterator[Tuple[int, int, int]]:
        """Yield (tensor_index, local_start, local_stop) covering stream range
        [start, stop) in order; zero-size tensors are skipped."""
        index = bisect.bisect_right(self._tensor_offsets, start) - 1
        while start < stop:
            tensor_start = self._tensor_offsets[index]
            tensor_stop = tensor_start + self._tensor_flats[index].size
            if tensor_stop <= start:
                index += 1
                continue
            take = min(stop, tensor_stop)
            yield index, start - tensor_start, take - tensor_start
            start = take
            index += 1

    def _input_part(self, start: int, stop: int) -> Tuple[np.ndarray, bool]:
        """One part of the logical stream and whether its memory is container-private
        (safe for in-place compression). The common case — a part inside one tensor —
        is a zero-copy view; only boundary-straddling parts are assembled."""
        pieces = [
            (index, self._tensor_flats[index][local_start:local_stop])
            for index, local_start, local_stop in self._stream_slices(start, stop)
        ]
        if len(pieces) == 1:
            index, view = pieces[0]
            return view, self._flat_private[index]
        return np.concatenate([view for _index, view in pieces]), True

    def get_raw_input_parts(self, peer_index: int) -> List[np.ndarray]:
        return [self._input_part(start, stop)[0] for start, stop in self.parts_by_peer[peer_index]]

    def link_for(self, peer_index: int):
        return self.peer_links[peer_index] if self.peer_links is not None else None

    async def iterate_input_parts_for(self, peer_index: int) -> AsyncIterator[runtime_pb2.Tensor]:
        """Serialized parts destined for one peer; compression happens in the shared
        thread pool with bounded prefetch (reference partition.py:104-112). A link
        on a lossy wire tier compresses through the send-leg error-feedback
        residual (global-offset indexed; parts are disjoint spans, so prefetched
        parts may run concurrently in the executor without racing)."""
        link = self.link_for(peer_index)
        codec = link.codec if link is not None else self.compression
        use_feedback = link is not None and link.error_feedback and self.residuals is not None
        if use_feedback:
            from hivemind_tpu.averaging.residual import compress_with_feedback

            self.residuals.ensure(self.total_elements)
        spans = self.parts_by_peer[peer_index]
        parts = [(start, stop, *self._input_part(start, stop)) for start, stop in spans]

        def _compress(item) -> runtime_pb2.Tensor:
            start, stop, part, private = item
            if use_feedback:
                return compress_with_feedback(part, codec, self.residuals.view("send", start, stop))
            return serialize_tensor(part, codec, allow_inplace=private)

        async for serialized in amap_in_executor(_compress, as_aiter(*parts), max_prefetch=self.prefetch):
            yield serialized

    def register_processed_part(self, peer_index: int, part_index: int, delta_part: np.ndarray) -> None:
        """Store the delta (averaged − input) for one part."""
        start, stop = self.parts_by_peer[peer_index][part_index]
        expected = stop - start
        if delta_part.size != expected:
            raise AllreduceException(
                f"part size mismatch from peer {peer_index}: got {delta_part.size}, expected {expected}"
            )
        flat_delta = delta_part.reshape(-1)
        consumed = 0
        for index, local_start, local_stop in self._stream_slices(start, stop):
            length = local_stop - local_start
            self._tensor_deltas[index][local_start:local_stop] = flat_delta[consumed : consumed + length]
            consumed += length
        self._mark_ready(peer_index, part_index)

    def register_processed_absolute(self, peer_index: int, part_index: int, value: np.ndarray) -> None:
        """Store a processed part that carries the reduced AVERAGE itself
        (quantized delta leg, ``absolute_part`` on the wire): the delta is
        recovered locally as ``value − own input``. Only error-feedback links
        use this path, and those never compress the container's flats in place,
        so the input part still holds the original local values."""
        start, stop = self.parts_by_peer[peer_index][part_index]
        value32 = value.reshape(-1).astype(np.float32, copy=False)
        if value32.size != stop - start:
            raise AllreduceException(
                f"absolute part size mismatch from peer {peer_index}: got {value32.size}, expected {stop - start}"
            )
        local, _private = self._input_part(start, stop)
        self.register_processed_part(peer_index, part_index, value32 - local)

    def register_failed_reducer(self, peer_index: int) -> None:
        """A reducer died: its unprocessed parts keep the local value (delta = 0)
        and count toward failed_size (reference partition.py:128-136)."""
        if self._peer_failed[peer_index]:
            return
        self._peer_failed[peer_index] = True
        for part_index, (start, stop) in enumerate(self.parts_by_peer[peer_index]):
            key = (peer_index, part_index)
            event = self._part_ready.get(key)
            if event is None or not event.is_set():
                self.failed_size += stop - start
                self._mark_ready(peer_index, part_index)

    def _mark_ready(self, peer_index: int, part_index: int) -> None:
        key = (peer_index, part_index)
        event = self._part_ready.setdefault(key, asyncio.Event())
        event.set()

    async def _wait_part(self, peer_index: int, part_index: int) -> None:
        key = (peer_index, part_index)
        event = self._part_ready.setdefault(key, asyncio.Event())
        await event.wait()

    async def iterate_output_tensors(self) -> AsyncIterator[np.ndarray]:
        """Yield per-tensor DELTAS (float32, original shape) as soon as all parts
        covering each tensor have arrived (reference partition.py:138-160)."""
        # map flat offsets back to (peer, part) completion events, in stream order
        ordered_parts = [
            (peer_index, part_index, start, stop)
            for peer_index, spans in enumerate(self.parts_by_peer)
            for part_index, (start, stop) in enumerate(spans)
        ]
        ordered_parts.sort(key=lambda item: item[2])
        cursor = 0  # next ordered part not yet awaited
        offset = 0
        for tensor_index, tensor in enumerate(self.tensors):
            numel = int(np.prod(tensor.shape))
            tensor_end = offset + numel
            while cursor < len(ordered_parts) and ordered_parts[cursor][2] < tensor_end:
                peer_index, part_index, _start, _stop = ordered_parts[cursor]
                await self._wait_part(peer_index, part_index)
                cursor += 1
            yield self._tensor_deltas[tensor_index].reshape(tensor.shape)
            offset = tensor_end
        self._finished.set()

    def __repr__(self):
        return (
            f"TensorPartContainer({len(self.tensors)} tensors, {self.total_elements} elements, "
            f"parts_by_peer={self.num_parts_by_peer})"
        )


class TensorPartReducer:
    """Accumulates incoming parts for the span THIS peer reduces
    (reference partition.py:179-286)."""

    def __init__(self, part_shapes: Sequence[Tuple[int, ...]], num_senders: int):
        self.part_shapes = list(part_shapes)
        self.num_senders = num_senders
        self.sender_failed = [False] * num_senders
        # per-part: accumulator, total weight, contributed sender flags, done future
        self._parts: Dict[int, dict] = {}
        self._closed = False
        self._scratch: Optional[np.ndarray] = None  # reusable weighted-part staging

    def _part_state(self, part_index: int) -> dict:
        if part_index not in self._parts:
            if not (0 <= part_index < len(self.part_shapes)):
                raise AllreduceException(f"invalid part index {part_index}")
            self._parts[part_index] = dict(
                accumulator=np.zeros(self.part_shapes[part_index], np.float32),
                total_weight=0.0,
                contributed=[False] * self.num_senders,
                future=asyncio.get_event_loop().create_future(),
            )
        return self._parts[part_index]

    @property
    def num_active_senders(self) -> int:
        return sum(not failed for failed in self.sender_failed)

    async def accumulate_part(
        self, sender_index: int, part_index: int, part: np.ndarray, weight: float = 1.0
    ) -> np.ndarray:
        """Add one sender's part; resolves to the weighted average once every active
        sender has contributed."""
        if self._closed:
            raise AllreduceException("reducer is closed")
        state = self._part_state(part_index)
        if state["contributed"][sender_index]:
            raise AllreduceException(f"sender {sender_index} sent part {part_index} twice")
        state["contributed"][sender_index] = True
        if not state["future"].done():
            # the accumulator IS the eventual result (divided in place), so a
            # laggard whose part arrives after resolution must not touch it
            accumulator = state["accumulator"]
            part32 = part.reshape(accumulator.shape).astype(np.float32, copy=False)
            if weight == 1.0:
                np.add(accumulator, part32, out=accumulator)
            else:
                if self._scratch is None or self._scratch.size < accumulator.size:
                    self._scratch = np.empty(max(int(np.prod(shape)) for shape in self.part_shapes), np.float32)
                scratch = self._scratch[: accumulator.size].reshape(accumulator.shape)
                np.multiply(part32, weight, out=scratch)
                np.add(accumulator, scratch, out=accumulator)
            state["total_weight"] += weight
            self._maybe_finish(part_index)
        return await asyncio.shield(state["future"])

    def on_sender_failed(self, sender_index: int) -> None:
        """Shrink denominators for parts the dead sender had not contributed to
        (reference partition.py:248-255)."""
        if self.sender_failed[sender_index]:
            return
        self.sender_failed[sender_index] = True
        for part_index in range(len(self.part_shapes)):
            # started parts re-check completion; if ALL senders are gone, untouched
            # parts must fail immediately instead of hanging their awaiters
            if part_index in self._parts or self.num_active_senders == 0:
                self._maybe_finish(part_index)

    def _maybe_finish(self, part_index: int) -> None:
        if part_index not in self._parts and self.num_active_senders == 0:
            # everyone died before sending this part
            state = self._part_state(part_index)
            if not state["future"].done():
                state["future"].set_exception(AllreduceException("all senders failed"))
            return
        if part_index not in self._parts:
            return
        state = self._parts[part_index]
        if state["future"].done():
            return
        pending = [
            i for i in range(self.num_senders) if not state["contributed"][i] and not self.sender_failed[i]
        ]
        if pending:
            return
        if state["total_weight"] <= 0:
            state["future"].set_exception(AllreduceException(f"part {part_index}: no live contributions"))
            return
        averaged = state["accumulator"]
        np.divide(averaged, state["total_weight"], out=averaged)
        state["future"].set_result(averaged)

    # -------------------------------------------------------------- public queries
    # (the allreduce stream handler and laggard watchdog must observe reduction
    # state without touching the accumulator internals — this is the interface that
    # survives rewiring, VERDICT r1 "encapsulation leak")

    def result_nowait(self, part_index: int) -> Optional[np.ndarray]:
        """The averaged part if it resolved successfully already, else None."""
        state = self._parts.get(part_index)
        if state is None or not state["future"].done() or state["future"].cancelled():
            return None
        if state["future"].exception() is not None:
            return None
        return state["future"].result()

    def pending_senders(self, part_index: int) -> List[int]:
        """Ranks that have NOT contributed to a STARTED part and are still alive
        (empty for parts nobody started — there is no laggard to blame yet)."""
        state = self._parts.get(part_index)
        if state is None:
            return []
        return [
            rank
            for rank in range(self.num_senders)
            if not state["contributed"][rank] and not self.sender_failed[rank]
        ]

    async def wait_part(self, part_index: int, timeout: Optional[float] = None) -> np.ndarray:
        """Await one part's average (shielded: many callers may wait on the same
        future). Raises asyncio.TimeoutError / AllreduceException."""
        state = self._part_state(part_index)
        return await asyncio.wait_for(asyncio.shield(state["future"]), timeout=timeout)

    def finalize(self) -> None:
        self._closed = True
        for state in self._parts.values():
            if not state["future"].done():
                state["future"].set_exception(AllreduceException("reducer finalized early"))
