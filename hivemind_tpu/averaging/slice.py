"""SliceAverager: ONE multi-process device mesh = ONE swarm peer.

A real TPU slice (e.g. a v4-32) is several hosts each running one jax process over
its local chips. `MeshAverager` alone assumes single-process jax; this module adds
the multi-host protocol (VERDICT r2 missing #3 / next-round #4):

- **Process 0 is the network process.** It alone constructs the DHT and the
  embedded `MeshAverager` — matchmaking, the butterfly all-reduce, state sharing
  and every other swarm interaction happen only there. Non-zero processes never
  hold a DHT object (structurally impossible to touch the swarm) and participate
  ONLY in collective jax operations over ICI.
- **`step()` is collective**: every process of the slice must call it (the usual
  SPMD contract). The round is three synchronized phases:

  1. *stage* (all processes): optional `mesh_mean` over the local-replica axis,
     then per-leaf staging to process-0 host mirrors. On a multi-process mesh the
     staging replicates ONE leaf at a time on device (`MeshTensorBridge`'s bounded
     fallback) so transient HBM stays one leaf, never a model copy.
  2. *swarm round* (process 0 only): the embedded `MeshAverager.step()` averages
     the host mirrors with other swarm peers over the internet/DCN. The other
     processes wait at the phase-3 collective — XLA's launch-group barrier IS the
     rendezvous; no host-side control channel exists or is needed.
  3. *adopt* (all processes): process 0 broadcasts a success flag and the averaged
     leaves (`multihost_utils.broadcast_one_to_all`, one leaf at a time); every
     process uploads its local shards and the device tree is rebuilt as global
     arrays with the original shardings.

Bandwidth note: the embedded averager advertises the slice's AGGREGATE egress
(`MeshAverager` multiplies by the host count) — the LP load balancer then assigns
the slice a proportionally larger share of the butterfly reduction, which is the
point of fronting a whole slice as a single high-bandwidth peer.

v4-32 topology example (4 hosts × 8 chips): run one process per host with
``jax.distributed.initialize``; build ``Mesh(devices.reshape(dp, tp, ...))``;
process 0 additionally gets the DHT's ``initial_peers``. Every host calls
``SliceAverager(...).step()`` at the same epoch boundaries. Long waits inside
phase 2 require the platform's collective timeout (barrier_timeout /
coordination service) to exceed ``averaging_timeout``.

The reference has no analog (its one peer = one process, p2p_daemon.py); this is
the TPU-native two-tier backend's top layer (SURVEY §5 "communication backend").
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from hivemind_tpu.averaging.ici import MeshAverager
from hivemind_tpu.parallel.ici import MeshTensorBridge
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _broadcast_from_network_process(value: np.ndarray) -> np.ndarray:
    """Broadcast one host array from process 0 to every process (device psum)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(value))


class SliceAverager:
    """See module docstring.

    :param device_tree: pytree of (sharded, possibly multi-process) jax Arrays
    :param mesh: the global Mesh (spanning every process of the slice)
    :param dht_factory: zero-arg callable building the network process's DHT;
        called ONLY on process 0 (other processes never own any networking)
    :param local_reduce_axis: as in :class:`MeshAverager`
    :param kwargs: forwarded to the embedded :class:`MeshAverager` (process 0)
    """

    def __init__(
        self,
        device_tree: Any,
        mesh,
        dht_factory: Callable[[], Any],
        *,
        local_reduce_axis: Optional[str] = None,
        **kwargs,
    ):
        self.mesh = mesh
        self.local_reduce_axis = local_reduce_axis
        self.process_index = jax.process_index()
        self.is_network_process = self.process_index == 0
        self._device_tree = device_tree
        self.bridge = MeshTensorBridge(mesh)
        self.dht = None
        self.averager: Optional[MeshAverager] = None
        if self.is_network_process:
            self.dht = dht_factory()
            self.averager = MeshAverager(
                device_tree,
                mesh,
                self.dht,
                local_reduce_axis=local_reduce_axis,
                external_staging=True,
                **kwargs,
            )
        else:
            # follower mirrors: a staging buffer only (nobody reads its contents).
            # This MUST be the same collective gather the network process runs
            # inside MeshAverager.__init__ (per-leaf replication on a multi-process
            # mesh is collective): an allocate-only follower would leave process 0
            # blocked in the init collective while the follower races ahead to
            # phase 1, pairing mismatched programs — a permanent deadlock
            self._follower_mirrors = self.bridge.gather_reduced_to_host(
                device_tree, reduce_axis=local_reduce_axis
            )

    # ------------------------------------------------------------------ helpers

    def _reduced_like(self, tree: Any) -> Any:
        if self.local_reduce_axis is not None:
            return self.bridge.mesh_mean(tree, self.local_reduce_axis)
        return tree

    @property
    def device_tree(self) -> Any:
        return self._device_tree

    @device_tree.setter
    def device_tree(self, tree: Any) -> None:
        self._device_tree = tree
        if self.averager is not None:
            self.averager.device_tree = tree

    # ------------------------------------------------------------------ the round

    def step(self, *, weight: Optional[float] = None, timeout: Optional[float] = None,
             **step_kwargs) -> bool:
        """One collective swarm round. Every process of the slice must call this;
        returns True when the swarm round succeeded and the averaged values were
        adopted, False when the round failed (device state is left unchanged)."""
        # -------- phase 1: stage (collective; per-leaf streaming reduce) --------
        if self.is_network_process:
            assert self.averager is not None
            with self.averager.lock_averaged_tensors:
                self.bridge.stage_reduced_into_mirrors(
                    self._device_tree, self.averager._averaged_tensors,
                    reduce_axis=self.local_reduce_axis,
                )
        else:
            self.bridge.stage_reduced_into_mirrors(
                self._device_tree, self._follower_mirrors,
                reduce_axis=self.local_reduce_axis,
            )

        # -------- phase 2: swarm round (network process only) --------
        ok = False
        if self.is_network_process:
            assert self.averager is not None
            try:
                self.averager.step(weight=weight, timeout=timeout, **step_kwargs)
                ok = True
            except Exception as e:
                logger.warning(f"slice swarm round failed: {e!r}")

        # -------- phase 3: adopt (collective; also the rendezvous barrier) --------
        flag = _broadcast_from_network_process(
            np.asarray([1.0 if ok else 0.0], np.float32)
        )
        ok = bool(flag[0] >= 0.5)
        if not ok:
            return False

        leaves, treedef = jax.tree_util.tree_flatten(self._device_tree)
        axis_size = (
            self.mesh.shape[self.local_reduce_axis]
            if self.local_reduce_axis is not None
            else None
        )
        mirrors = (
            self.averager._averaged_tensors
            if self.is_network_process
            else self._follower_mirrors
        )
        assert len(leaves) == len(mirrors)
        new_leaves = []
        for leaf, mirror in zip(leaves, mirrors):
            # per-leaf broadcast: every process ends up with process 0's averaged
            # value, then uploads only its local shards — peak transient memory is
            # one leaf, and the broadcast rides the same device fabric as phase 1
            value = _broadcast_from_network_process(np.ascontiguousarray(mirror))
            new_leaves.append(self.bridge.scatter_leaf(leaf, value, stack_axis_size=axis_size))
        self._device_tree = jax.tree_util.tree_unflatten(treedef, new_leaves)
        if self.averager is not None:
            self.averager.device_tree = self._device_tree
        return True

    def shutdown(self) -> None:
        if self.averager is not None:
            self.averager.shutdown()
        if self.dht is not None:
            self.dht.shutdown()
