"""Verified, resumable, striped state downloads (ISSUE 7 tentpole).

The paper's fault-tolerance story rests on newcomers bootstrapping model and
optimizer state from the swarm (reference averager.py:628-651). The original
port of that path trusted the network completely: no integrity check on the
payload, a whole-transfer restart when a donor died mid-stream, and no
freshness validation on the donor's epoch. This module is the hardened
receiver side of the manifest-first protocol:

- **Manifest-first.** Every ``rpc_download_state`` stream begins with a
  :class:`averaging_pb2.StateManifest`: the donor's schema fingerprint, epoch,
  per-tensor byte length + blake2b-16 digest, and an explicit
  ``state_unavailable`` marker so "sharing disabled" can never be mistaken for
  a truncated stream.
- **Verified.** Each tensor's digest is checked the moment its last byte
  lands; a corrupt tensor fails THAT donor, never the download — and a
  corrupted payload is never adopted.
- **Resumable.** Per-tensor completion is tracked in a :class:`StateAssembly`
  that outlives any one donor: failover re-requests only the missing tensors
  (``DownloadRequest.have_tensors``), so a donor dying after tensor 40 of 50
  costs 10 tensors, not 50.
- **Striped.** When several donors advertise bit-identical manifests, the
  missing tensors are split between up to ``max_stripes`` of them and
  downloaded concurrently (PAPERS: cross-replica sharding of weight updates) —
  large state syncs are no longer bottlenecked on one donor's uplink.
- **Bounded.** One :class:`~hivemind_tpu.resilience.Deadline` governs the
  whole download; failover pacing between candidate sweeps comes from a shared
  :class:`~hivemind_tpu.resilience.RetryPolicy`.

Chaos points ``state.download.send`` (donor side, per message, scoped by the
donor's peer id) and ``state.download.recv`` (receiver side, per message,
scoped by the donor's peer id) let the soak corrupt, drop, or stall the sync
path deterministically; the digests turn every injected corruption into a
counted failover instead of silently poisoned weights (docs/state_recovery.md).
"""

from __future__ import annotations

import asyncio
import hashlib
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from hivemind_tpu.compression import deserialize_tensor
from hivemind_tpu.compression.serialization import _clone_tensor_metadata
from hivemind_tpu.proto import averaging_pb2
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.resilience import Deadline, RetryPolicy
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import current_span as _current_span
from hivemind_tpu.telemetry.tracing import trace as _tracing_span
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)

DIGEST_SIZE = 16  # blake2b-16: plenty for integrity, cheap on the wire
STATE_CHUNK_BYTES = 2**20
# striping is only worth two streams when there is real payload to split
MIN_STRIPE_BYTES = 4 * STATE_CHUNK_BYTES

_STATE_SYNC_BYTES = _TELEMETRY.counter(
    "hivemind_state_sync_bytes_total", "state-sync payload bytes by direction", ("direction",)
)
# cached child for the donor-side hot loop (one inc per streamed chunk)
STATE_SYNC_BYTES_SENT = _STATE_SYNC_BYTES.labels(direction="sent")
_STATE_SYNC_FAILOVERS = _TELEMETRY.counter(
    "hivemind_state_sync_failovers_total", "state downloads that moved on to another donor"
)
_STATE_SYNC_DIGEST_FAILURES = _TELEMETRY.counter(
    "hivemind_state_sync_digest_failures_total",
    "state payloads rejected by digest verification",
    ("site",),  # download | checkpoint
)
_STATE_SYNC_UNVERIFIED = _TELEMETRY.counter(
    "hivemind_state_sync_unverified_adoptions_total",
    "state adopted from a donor that sent no manifest (legacy stream, digests unavailable)",
)
_STATE_SYNC_STALE_DONORS = _TELEMETRY.counter(
    "hivemind_state_sync_stale_donors_total",
    "donors rejected because their manifest epoch was behind the required minimum",
)

# failover pacing BETWEEN candidate sweeps (within a sweep, moving to the next
# donor is immediate); unlimited attempts — the Deadline is the real bound
_FAILOVER_RETRY = RetryPolicy(
    max_attempts=None, base_delay=0.5, backoff=1.5, max_delay=5.0, jitter="full",
    name="state_sync_failover",
)


def payload_digest(payload) -> bytes:
    """blake2b-16 over one serialized tensor payload (the ``Tensor.buffer``)."""
    return hashlib.blake2b(bytes(payload), digest_size=DIGEST_SIZE).digest()


def build_state_manifest(
    serialized_tensors: Sequence,
    *,
    schema_hash: str,
    epoch: int,
    metadata: bytes = b"",
) -> averaging_pb2.StateManifest:
    """The donor-side manifest: one digest entry per serialized tensor."""
    manifest = averaging_pb2.StateManifest(
        schema_hash=schema_hash, epoch=max(0, int(epoch)), metadata=metadata
    )
    for serialized in serialized_tensors:
        manifest.tensors.add(
            num_bytes=len(serialized.buffer), digest=payload_digest(serialized.buffer)
        )
    return manifest


class StateSyncError(Exception):
    """Base for receiver-side protocol failures (always scoped to ONE donor)."""


class DigestMismatch(StateSyncError):
    """A tensor's bytes did not match its manifest digest: corruption in flight
    or a donor mutating state mid-stream. The tensor is discarded, never adopted."""


class ManifestMismatch(StateSyncError):
    """A donor's manifest disagrees with the one this download already pinned
    (different digests/epoch): it cannot contribute to the same assembly."""


class StaleDonor(StateSyncError):
    """The donor's manifest epoch is behind the receiver's required minimum."""


class StateUnavailable(StateSyncError):
    """The donor explicitly declared state sharing disabled (NOT a truncation)."""


@dataclass
class StateDownloadResult:
    metadata: Any
    tensors: List[np.ndarray]
    epoch: int = 0
    verified: bool = False  # every adopted tensor passed digest verification
    donors: List[str] = field(default_factory=list)
    bytes_received: int = 0


class _TensorSlot:
    """Reassembly buffer for one in-flight tensor."""

    __slots__ = ("head", "buffer")

    def __init__(self):
        self.head: Optional[object] = None  # first chunk proto (carries dtype/codec)
        self.buffer = bytearray()


class StateAssembly:
    """Cross-donor download state. The manifest is pinned by the first donor that
    provides one; every later donor must match it bit-for-bit, and per-tensor
    verification progress survives donor failover."""

    def __init__(
        self,
        *,
        schema_hash: Optional[str] = None,
        min_epoch: Optional[int] = None,
        expected_tensors: Optional[int] = None,
    ):
        self.schema_hash = schema_hash
        self.min_epoch = min_epoch
        self.expected_tensors = expected_tensors
        self.manifest: Optional[averaging_pb2.StateManifest] = None
        self.metadata: Any = None
        self.verified: Dict[int, np.ndarray] = {}
        self.bytes_received = 0
        self.digest_failures = 0
        self.generation = 0  # bumped on every (re)pin — callers detect mid-stream repins
        self._slots: Dict[int, _TensorSlot] = {}

    # ---------------------------------------------------------------- manifest

    def pin_manifest(
        self, manifest: averaging_pb2.StateManifest, donor: str, allow_repin: bool = True
    ) -> None:
        """Validate a donor's manifest and adopt it (first donor) or compare it to
        the pinned one. A failover donor whose (valid) manifest diverges — normal
        in a live swarm, donors keep training between rounds — RESETS the assembly
        to its manifest (``allow_repin``); a striping donor must match bit-for-bit
        (``allow_repin=False``), because stripes of two different states would
        interleave into a tensor soup no digest could bless."""
        if manifest.state_unavailable:
            raise StateUnavailable(f"donor {donor} is not sharing state")
        if self.min_epoch is not None and manifest.epoch < self.min_epoch:
            _STATE_SYNC_STALE_DONORS.inc()
            raise StaleDonor(
                f"donor {donor} serves epoch {manifest.epoch} < required {self.min_epoch}"
            )
        if self.schema_hash is not None and manifest.schema_hash != self.schema_hash:
            raise ManifestMismatch(
                f"donor {donor} schema {manifest.schema_hash[:8]}… does not match ours"
            )
        if self.expected_tensors is not None and len(manifest.tensors) != self.expected_tensors:
            raise ManifestMismatch(
                f"donor {donor} manifests {len(manifest.tensors)} tensors, "
                f"expected {self.expected_tensors}"
            )
        if self.manifest is None:
            self._adopt_manifest(manifest)
            return
        ours = [(entry.num_bytes, entry.digest) for entry in self.manifest.tensors]
        theirs = [(entry.num_bytes, entry.digest) for entry in manifest.tensors]
        if ours != theirs or manifest.epoch != self.manifest.epoch:
            if not allow_repin:
                raise ManifestMismatch(f"donor {donor} manifest diverges from the pinned one")
            # resume progress only transfers between IDENTICAL states; this donor
            # is valid but different, so the download restarts on its manifest
            logger.debug(
                f"donor {donor} serves a different (valid) state; "
                f"re-pinning and discarding {len(self.verified)} verified tensors"
            )
            self.verified.clear()
            self._slots.clear()
            self._adopt_manifest(manifest)

    def _adopt_manifest(self, manifest: averaging_pb2.StateManifest) -> None:
        self.manifest = manifest
        self.metadata = MSGPackSerializer.loads(manifest.metadata) if manifest.metadata else None
        self.generation += 1

    # ---------------------------------------------------------------- tensor parts

    def feed(self, tensor_index: int, tensor_part) -> None:
        """Ingest one chunk. When a tensor's last byte lands its digest is checked
        immediately: a mismatch discards the tensor and raises (failing only the
        donor that sent it)."""
        assert self.manifest is not None, "manifest must be pinned before tensor parts"
        if tensor_index in self.verified:
            return  # duplicate delivery after a failover re-request: already safe
        if not 0 <= tensor_index < len(self.manifest.tensors):
            raise StateSyncError(f"tensor index {tensor_index} outside the manifest")
        entry = self.manifest.tensors[tensor_index]
        slot = self._slots.setdefault(tensor_index, _TensorSlot())
        if slot.head is None:
            slot.head = _clone_tensor_metadata(tensor_part)
        payload = tensor_part.buffer
        slot.buffer += payload
        self.bytes_received += len(payload)
        _STATE_SYNC_BYTES.inc(len(payload), direction="received")
        if len(slot.buffer) > entry.num_bytes:
            self._slots.pop(tensor_index, None)
            raise StateSyncError(
                f"tensor {tensor_index} overflowed its manifest length "
                f"({len(slot.buffer)} > {entry.num_bytes} bytes)"
            )
        if len(slot.buffer) < entry.num_bytes:
            return
        digest = payload_digest(slot.buffer)
        if digest != entry.digest:
            self._slots.pop(tensor_index, None)
            self.digest_failures += 1
            _STATE_SYNC_DIGEST_FAILURES.inc(site="download")
            raise DigestMismatch(f"tensor {tensor_index} failed digest verification")
        combined = _clone_tensor_metadata(slot.head)
        combined.buffer = bytes(slot.buffer)
        self._slots.pop(tensor_index, None)
        self.verified[tensor_index] = deserialize_tensor(combined)

    def drop_partial(self, indices: Optional[Sequence[int]] = None) -> None:
        """Discard in-flight (unverified) buffers — called when a donor's stream
        dies so a failover donor restarts those tensors from byte zero."""
        if indices is None:
            self._slots.clear()
        else:
            for index in indices:
                self._slots.pop(index, None)

    # ---------------------------------------------------------------- progress

    def missing(self) -> List[int]:
        if self.manifest is None:
            return []
        return [i for i in range(len(self.manifest.tensors)) if i not in self.verified]

    def complete(self) -> bool:
        return self.manifest is not None and not self.missing()

    def result(self, donors: List[str]) -> StateDownloadResult:
        assert self.complete()
        tensors = [self.verified[i] for i in range(len(self.manifest.tensors))]
        return StateDownloadResult(
            metadata=self.metadata,
            tensors=tensors,
            epoch=int(self.manifest.epoch),
            verified=True,
            donors=donors,
            bytes_received=self.bytes_received,
        )


# -------------------------------------------------------------------- receiver


# same family the averager counts its internal errors into (get-or-create):
# a malformed declaration is a swarm-hygiene problem, not a download failure
_DECLARATION_PARSE_ERRORS = _TELEMETRY.counter(
    "hivemind_averaging_internal_errors_total",
    "errors in averager plumbing that do not fail a step",
    ("site",),
).labels(site="state_declaration_parse")


async def _list_donor_candidates(dht, prefix: str, exclude_peer_id) -> List:
    """Donors declared under ``{prefix}.all_averagers``, best priority first.
    ``None`` values are retraction tombstones from cleanly-departed donors."""
    from hivemind_tpu.p2p import PeerID

    key = f"{prefix}.all_averagers"
    result = await dht.node.get(key, latest=True)
    candidates = []
    if result is not None and isinstance(result.value, dict):
        for subkey, entry in result.value.items():
            try:
                if entry.value is None:
                    continue  # retracted on shutdown: do not waste a dial on it
                peer_id = PeerID.from_base58(subkey)
                priority = entry.value
                if peer_id != exclude_peer_id and isinstance(priority, (int, float, list, tuple)):
                    candidates.append((priority, random.random(), peer_id))
            except Exception as e:
                # skipping is correct, but it must be visible: a swarm full of
                # these means someone is publishing junk under our prefix
                # (ISSUE 3 satellite: no silent swallowing)
                logger.warning(f"ignoring malformed averager declaration {subkey!r}: {e!r}")
                _DECLARATION_PARSE_ERRORS.inc()
    candidates.sort(reverse=True)
    return [peer_id for _priority, _jitter, peer_id in candidates]


async def _stream_from_donor(
    stub,
    assembly: StateAssembly,
    donor,
    *,
    want: Optional[Sequence[int]],
    deadline: Deadline,
    manifest_only: bool = False,
    allow_repin: bool = True,
    legacy_sink: Optional[list] = None,
) -> None:
    """One donor's stream into the shared assembly. ``want=None`` means "send
    everything we do not already hold verified"; a striping donor gets an explicit
    subset. Raises a :class:`StateSyncError` subclass (or transport error) on any
    failure; the assembly keeps whatever was verified before the failure."""
    if want is None:
        have = sorted(assembly.verified)
    else:
        total = len(assembly.manifest.tensors) if assembly.manifest is not None else 0
        have = sorted(set(range(total)) - set(want))
    request = averaging_pb2.DownloadRequest(have_tensors=have, manifest_only=manifest_only)
    per_message_timeout = deadline.remaining_or(30.0)
    if per_message_timeout <= 0:
        raise asyncio.TimeoutError("state-sync deadline expired before the dial")
    stream = stub.rpc_download_state(request, timeout=per_message_timeout)
    donor_scope = str(donor)
    saw_manifest = False
    touched: set = set()
    try:
        async for message in stream:
            deadline.require("state download stream")
            if _CHAOS.enabled:
                payload = message.tensor_part.buffer if message.HasField("tensor_part") else None
                injected = await _CHAOS.inject(
                    "state.download.recv", payload=payload, scope=donor_scope
                )
                if payload is not None and injected is not payload:
                    message.tensor_part.buffer = injected
            if message.HasField("manifest"):
                assembly.pin_manifest(message.manifest, donor_scope, allow_repin=allow_repin)
                saw_manifest = True
                if manifest_only:
                    return
                continue
            if not saw_manifest:
                # pre-manifest donor (legacy stream): hand the raw messages to the
                # caller's unverified-path sink; nothing lands in the assembly
                if legacy_sink is None:
                    raise StateSyncError(f"donor {donor_scope} sent data before any manifest")
                legacy_sink.append(message)
                continue
            if message.HasField("tensor_part"):
                index = int(message.tensor_index)
                touched.add(index)
                assembly.feed(index, message.tensor_part)
    except BaseException:
        # this donor's in-flight tensors restart from zero at the next donor;
        # everything already VERIFIED is kept — that is the resume guarantee
        assembly.drop_partial(sorted(touched))
        raise
    if manifest_only and not saw_manifest:
        raise StateSyncError(f"donor {donor_scope} ended a manifest probe without a manifest")
    if saw_manifest and not manifest_only:
        remaining = set(want) & set(assembly.missing()) if want is not None else set(assembly.missing())
        if remaining:
            raise StateSyncError(
                f"donor {donor_scope} ended its stream with {len(remaining)} tensors still missing"
            )


def _split_for_striping(assembly: StateAssembly, n_stripes: int) -> List[List[int]]:
    """Greedy balance of the missing tensors across ``n_stripes`` donors by
    manifest byte size (largest first), so stripes finish together."""
    sizes = sorted(
        ((int(assembly.manifest.tensors[i].num_bytes), i) for i in assembly.missing()),
        reverse=True,
    )
    loads = [0] * n_stripes
    stripes: List[List[int]] = [[] for _ in range(n_stripes)]
    for size, index in sizes:
        slot = loads.index(min(loads))
        stripes[slot].append(index)
        loads[slot] += size
    return [sorted(stripe) for stripe in stripes if stripe]


async def _legacy_collect(messages: List, assembly: StateAssembly) -> StateDownloadResult:
    """Assemble a pre-manifest donor's stream (old wire format: ``metadata`` blob
    + chunked tensors delimited by ``chunks``). Unverifiable — counted, so a swarm
    quietly full of legacy donors is visible in the monitor."""
    from hivemind_tpu.compression import deserialize_tensor_stream

    metadata = None
    for message in messages:
        if message.metadata:
            metadata = MSGPackSerializer.loads(message.metadata)
            break

    async def _parts():
        for message in messages:
            if message.HasField("tensor_part"):
                yield [message.tensor_part]

    tensors = await deserialize_tensor_stream(_parts())
    if assembly.expected_tensors is not None and len(tensors) != assembly.expected_tensors:
        raise StateSyncError(
            f"legacy donor sent {len(tensors)}/{assembly.expected_tensors} tensors (truncated)"
        )
    if not tensors and metadata is None:
        raise StateSyncError("legacy donor sent an empty stream")
    epoch = int(metadata["epoch"]) if isinstance(metadata, dict) and "epoch" in metadata else 0
    if assembly.min_epoch is not None and epoch < assembly.min_epoch:
        _STATE_SYNC_STALE_DONORS.inc()
        raise StaleDonor(f"legacy donor serves epoch {epoch} < required {assembly.min_epoch}")
    _STATE_SYNC_UNVERIFIED.inc()
    return StateDownloadResult(metadata=metadata, tensors=tensors, epoch=epoch, verified=False)


async def download_state_verified(
    dht,
    p2p,
    prefix: str,
    get_stub,
    *,
    exclude_peer_id=None,
    timeout: Optional[float] = None,
    expected_tensors: Optional[int] = None,
    schema_hash: Optional[str] = None,
    min_epoch: Optional[int] = None,
    max_stripes: int = 2,
    retry_policy: RetryPolicy = _FAILOVER_RETRY,
    on_donor_failure=None,
) -> Optional[StateDownloadResult]:
    """Download (metadata, tensors) from the swarm with digest verification,
    per-tensor resume across donor failover, and optional 2-way striping.

    Returns ``None`` only when no donor could serve a complete verified (or,
    for legacy donors, length-consistent) state within the deadline.
    ``on_donor_failure(donor, exc)`` observes every failed donor attempt.
    """
    deadline = Deadline(timeout)
    assembly = StateAssembly(
        schema_hash=schema_hash, min_epoch=min_epoch, expected_tensors=expected_tensors
    )
    used_donors: List[str] = []
    sweep = 0

    async def _full_stream(stub, donor, legacy_sink=None) -> None:
        """Full (non-striped) stream with one repin retry: the request's
        ``have_tensors`` was computed against the OLD manifest — if this donor's
        (valid, divergent) manifest re-pins the assembly mid-stream, the donor
        was told to skip tensors the repin just discarded, so one immediate
        retry re-requests against the fresh (now-empty) verified set instead of
        failing over and repeating the same inversion against the next donor."""
        for attempt in range(2):
            # only a REPIN (a manifest replacing an already-pinned one) warrants
            # the same-donor retry; the first pin also bumps the generation, and
            # retrying on it would hand every failing donor a free second stream
            had_pinned_manifest = assembly.manifest is not None
            generation_before = assembly.generation
            try:
                await _stream_from_donor(
                    stub, assembly, donor, want=None, deadline=deadline, legacy_sink=legacy_sink
                )
                return
            except StateSyncError:
                if (
                    attempt == 0
                    and had_pinned_manifest
                    and assembly.generation != generation_before
                    and not assembly.complete()
                ):
                    continue
                raise

    with _tracing_span("state_sync.download", prefix=prefix, min_epoch=min_epoch or 0) as span:
        while not deadline.expired:
            candidates = await _list_donor_candidates(dht, prefix, exclude_peer_id)
            for position, donor in enumerate(candidates):
                if deadline.expired:
                    break
                stub = get_stub(p2p, donor, namespace=prefix)
                legacy_sink: List = []
                try:
                    if (
                        assembly.manifest is None
                        and position + 1 < len(candidates)
                        and max_stripes >= 2
                    ):
                        # probe first so striping can be decided before bytes move
                        await _stream_from_donor(
                            stub, assembly, donor, want=None, deadline=deadline,
                            manifest_only=True,
                        )
                    if assembly.manifest is not None:
                        striped = await _try_striped_fetch(
                            assembly, donor, candidates[position + 1:], get_stub, p2p, prefix,
                            deadline=deadline, max_stripes=max_stripes,
                            used_donors=used_donors, on_donor_failure=on_donor_failure,
                        )
                        if not striped and not assembly.complete():
                            await _full_stream(stub, donor)
                            if str(donor) not in used_donors:
                                used_donors.append(str(donor))
                    else:
                        # sole candidate: stream directly (legacy donors allowed)
                        await _full_stream(stub, donor, legacy_sink=legacy_sink)
                        if str(donor) not in used_donors:
                            used_donors.append(str(donor))
                    if assembly.complete():
                        result = assembly.result(used_donors)
                        if span is not None:
                            span.set("donors", len(used_donors))
                            span.set("bytes", result.bytes_received)
                            span.set("epoch", result.epoch)
                        return result
                    if legacy_sink and assembly.manifest is None:
                        result = await _legacy_collect(legacy_sink, assembly)
                        result.donors = [str(donor)]
                        if span is not None:
                            span.set("legacy", True)
                        return result
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    if on_donor_failure is not None:
                        on_donor_failure(donor, e)
                    if assembly.verified or assembly.manifest is not None:
                        _STATE_SYNC_FAILOVERS.inc()
                        if span is not None:
                            span.add_event(
                                "state_sync.failover",
                                donor=str(donor),
                                error=type(e).__name__,
                                verified=len(assembly.verified),
                            )
                    level = (
                        logger.debug
                        if isinstance(e, (StateUnavailable, StaleDonor))
                        else logger.warning
                    )
                    level(f"state download from {donor} failed: {e!r}")
            if not candidates and span is not None:
                span.add_event("state_sync.no_candidates", sweep=sweep)
            remaining = deadline.remaining()
            pause = retry_policy.delay(sweep)
            if remaining is not None and remaining <= pause:
                return None
            if remaining is None and sweep >= 2:
                # unbounded download that keeps finding nothing usable: give up
                # rather than spin forever (callers decide whether to re-enter)
                return None
            retry_policy._account_retry(sweep)
            await asyncio.sleep(pause)
            sweep += 1
    return None


async def _try_striped_fetch(
    assembly: StateAssembly,
    primary,
    rest: List,
    get_stub,
    p2p,
    prefix: str,
    *,
    deadline: Deadline,
    max_stripes: int,
    used_donors: List[str],
    on_donor_failure=None,
) -> bool:
    """Attempt a striped fetch of the missing tensors across ``primary`` plus
    donors from ``rest`` whose manifests match the pinned one. Returns True when
    striping ran (the assembly may still be incomplete if a stripe died — the
    caller's failover loop finishes the remainder); False when striping is not
    worth a second stream."""
    missing = assembly.missing()
    missing_bytes = sum(int(assembly.manifest.tensors[i].num_bytes) for i in missing)
    if max_stripes < 2 or len(missing) < 2 or missing_bytes < MIN_STRIPE_BYTES or not rest:
        return False
    donors = [primary]
    for candidate in rest:
        if len(donors) >= max_stripes:
            break
        try:
            # pin_manifest on the SHARED assembly validates the candidate's
            # manifest matches the pinned one bit-for-bit (no repin: stripes
            # of two different states must never interleave)
            await _stream_from_donor(
                get_stub(p2p, candidate, namespace=prefix), assembly, candidate,
                want=None, deadline=deadline, manifest_only=True, allow_repin=False,
            )
            donors.append(candidate)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.debug(f"striping probe of {candidate} failed: {e!r}")
    if len(donors) < 2:
        return False
    stripes = _split_for_striping(assembly, len(donors))
    span = _current_span()
    if span is not None:
        span.add_event("state_sync.striped", donors=len(stripes), tensors=len(missing))

    async def _one(donor, want):
        # no repin mid-stripe: a donor whose state moved since the probe fails
        # its stripe rather than resetting the other stripe's verified tensors
        await _stream_from_donor(
            get_stub(p2p, donor, namespace=prefix), assembly, donor,
            want=want, deadline=deadline, allow_repin=False,
        )
        if str(donor) not in used_donors:
            used_donors.append(str(donor))

    outcomes = await asyncio.gather(
        *(_one(donor, want) for donor, want in zip(donors, stripes)),
        return_exceptions=True,
    )
    for donor, outcome in zip(donors, outcomes):
        if isinstance(outcome, asyncio.CancelledError):
            raise outcome
        if isinstance(outcome, BaseException):
            _STATE_SYNC_FAILOVERS.inc()
            if on_donor_failure is not None:
                on_donor_failure(donor, outcome)
            logger.warning(f"striped state download from {donor} failed: {outcome!r}")
    return True
