"""Assembled group descriptor (parity: reference hivemind/averaging/group_info.py)."""

from __future__ import annotations

from typing import NamedTuple, Tuple

from hivemind_tpu.p2p import PeerID


class GroupInfo(NamedTuple):
    group_id: bytes  # random unique id assigned by the leader
    peer_ids: Tuple[PeerID, ...]  # group members in leader-shuffled order
    gathered: Tuple[bytes, ...]  # opaque per-peer metadata blobs, same order

    @property
    def group_size(self) -> int:
        return len(self.peer_ids)

    def __contains__(self, peer_id: PeerID) -> bool:
        return peer_id in self.peer_ids
