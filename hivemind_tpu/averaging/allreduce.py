"""One fault-tolerant butterfly all-reduce round inside a fixed group
(capability parity: reference hivemind/averaging/allreduce.py).

Each peer reduces the span of the concatenated vector assigned by the load balancer;
senders stream their parts to every reducer, reducers stream back DELTAS
(averaged − that sender's input — reference allreduce.py:39: deltas keep precision and
make a dead reducer equivalent to delta 0). Modes (reference allreduce.py:26-29):
NODE sends + reduces, CLIENT sends only (firewalled/zero-bandwidth), AUX reduces only
(e.g. a CPU helper with no gradients of its own)."""

from __future__ import annotations

import asyncio
import time
from enum import Enum
from typing import AsyncIterator, Dict, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.averaging.partition import (
    DEFAULT_PART_SIZE_BYTES,
    AllreduceException,
    TensorPartContainer,
    TensorPartReducer,
)
from hivemind_tpu.compression import CompressionBase, NoCompression, deserialize_tensor, serialize_tensor
from hivemind_tpu.p2p import P2P, P2PContext, PeerID
from hivemind_tpu.proto import averaging_pb2, runtime_pb2
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.resilience import BreakerBoard
from hivemind_tpu.utils.asyncio_utils import aiter_with_timeout, run_in_executor
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

# layer-3 telemetry (docs/observability.md): where the all-reduce round's time
# goes (local reduction vs per-peer exchange vs whole round) and which senders
# get banned, by cause — the straggler-banning visibility VERDICT r5 asked for
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import (
    finish_span as _finish_span,
    start_span as _start_span,
    trace as _tracing_span,
)

_ALLREDUCE_PHASE = _TELEMETRY.histogram(
    "hivemind_averaging_allreduce_phase_seconds",
    "duration of one all-reduce phase",
    ("phase",),
)
_BANNED_SENDERS = _TELEMETRY.counter(
    "hivemind_averaging_banned_senders_total", "senders banned mid-round", ("cause",)
)
# wire accounting for the averaging data path (docs/observability.md): serialized
# tensor-part payload bytes crossing this peer's wall in each direction (parts it
# ships + deltas it returns vs parts it receives as a reducer + deltas it gets
# back), and the per-round effective throughput using the same fp32-equivalent
# formula as benchmarks/benchmark_averaging.py — so the bench's headline number
# can be cross-checked against internal accounting
_AVG_BYTES_SENT = _TELEMETRY.counter(
    "hivemind_averaging_bytes_sent_total", "serialized averaging payload bytes sent"
)
_AVG_BYTES_RECEIVED = _TELEMETRY.counter(
    "hivemind_averaging_bytes_received_total", "serialized averaging payload bytes received"
)
_AVG_EFFECTIVE_RATE = _TELEMETRY.gauge(
    "hivemind_averaging_round_effective_bytes_per_second",
    "last successful round's effective rate: 2 * total_elements * 4 bytes / round "
    "seconds (divide by 1e9 for benchmark_averaging's GB/s-per-peer headline)",
)

# largest pre-compression part that still fits one mux message even uncompressed
# (MAX_MESSAGE_SIZE = 4 MiB minus headroom for tensor metadata + frame header)
from hivemind_tpu.p2p.mux import MAX_MESSAGE_SIZE

MAX_PART_SIZE_BYTES = MAX_MESSAGE_SIZE - 2**16


class AveragingMode(Enum):
    NODE = 0
    CLIENT = 1
    AUX = 2


class AllReduceRunner:
    """Runs one allreduce round. The owning averager routes incoming
    ``rpc_aggregate_part`` streams for this group_id to ``handle_aggregate_stream``.

    :param peer_element_counts: reduction span sizes per peer (load balancer output)
    :param get_stub: callable (peer_id) -> stub with .rpc_aggregate_part(stream)
    :param links: negotiated per-link wire codecs (peer_index ->
        :class:`~hivemind_tpu.averaging.wire_codec.WireLink`); absent entries
        fall back to ``compression`` (exact pre-negotiation behavior)
    :param residuals: the averager's error-feedback store (required for links
        with ``error_feedback``; survives the runner — one round borrows it)
    """

    def __init__(
        self,
        *,
        p2p: P2P,
        group_id: bytes,
        tensors: Sequence,
        ordered_peer_ids: Sequence[PeerID],
        peer_element_counts: Sequence[int],
        modes: Sequence[AveragingMode],
        get_stub,
        weight: float = 1.0,
        compression: CompressionBase = NoCompression(),
        part_size_bytes: int = DEFAULT_PART_SIZE_BYTES,
        sender_timeout: float = 30.0,
        reducer_timeout: float = 60.0,
        prefetch: int = 8,
        links: Optional[Dict[int, "WireLink"]] = None,
        residuals=None,
    ):
        self.p2p, self.group_id = p2p, group_id
        # one part travels as ONE mux message: a part whose wire size exceeded
        # MAX_MESSAGE_SIZE would kill the stream mid-round and silently degrade
        # the average. The clamp uses the same formula on every peer, so senders
        # and reducers (which derive part shapes independently) stay in agreement.
        if part_size_bytes > MAX_PART_SIZE_BYTES:
            logger.info(
                f"part_size_bytes={part_size_bytes} exceeds the per-message cap; "
                f"using {MAX_PART_SIZE_BYTES}"
            )
            part_size_bytes = MAX_PART_SIZE_BYTES
        self.ordered_peer_ids = tuple(ordered_peer_ids)
        self.modes = tuple(modes)
        self.peer_element_counts = tuple(peer_element_counts)
        self.get_stub = get_stub
        self.weight = weight
        self.sender_timeout, self.reducer_timeout = sender_timeout, reducer_timeout
        self.my_index = self.ordered_peer_ids.index(p2p.peer_id)
        self.my_mode = self.modes[self.my_index]
        assert len(self.modes) == len(self.ordered_peer_ids) == len(self.peer_element_counts)
        for peer_index, (mode, count) in enumerate(zip(self.modes, self.peer_element_counts)):
            if mode == AveragingMode.CLIENT:
                assert count == 0, "client-mode peers cannot be assigned reduction work"

        self.sender_ranks: Dict[int, int] = {}  # peer_index -> sender rank
        for peer_index, mode in enumerate(self.modes):
            if mode != AveragingMode.AUX:
                self.sender_ranks[peer_index] = len(self.sender_ranks)
        self.num_senders = len(self.sender_ranks)

        self.links = dict(links) if links else {}
        self.residuals = residuals
        if self.residuals is not None and any(link.error_feedback for link in self.links.values()):
            self.residuals.ensure(sum(self.peer_element_counts))
        peer_links = (
            [self.links.get(index) for index in range(len(self.ordered_peer_ids))] if self.links else None
        )
        # prefetch widens the in-flight part window per peer exchange: up to this
        # many parts may sit serialized ahead of the stream writer, keeping the
        # compress → encrypt → send stages concurrently busy
        self.container = TensorPartContainer(
            tensors, peer_element_counts, compression, part_size_bytes, prefetch=prefetch,
            peer_links=peer_links, residuals=residuals,
        ) if self.my_mode != AveragingMode.AUX else None
        my_part_shapes = self._span_part_shapes(self.my_index, part_size_bytes)
        self.reducer = TensorPartReducer(my_part_shapes, self.num_senders)
        self.compression = compression
        self.part_size_bytes = part_size_bytes
        # quantized delta leg (ISSUE 11): the averaged value of each part is
        # quantized ONCE per lossy tier and the same payload goes to every
        # lossy-link sender; EF touches the "reduce" residual exactly once per
        # part per round. Offsets map part_index -> global stream position.
        self._my_span_start = sum(self.peer_element_counts[: self.my_index])
        self._part_offsets = [0]
        for shape in my_part_shapes:
            self._part_offsets.append(self._part_offsets[-1] + int(np.prod(shape)))
        self._absolute_payloads: Dict[Tuple[int, str], "asyncio.Future"] = {}
        self._absolute_consumed: Dict[Tuple[int, str], int] = {}
        self._reduce_ef_parts: set = set()
        # how many sender streams will consume each cached absolute payload:
        # once all of them have taken a part, its payload is dropped (the cache
        # stays bounded by the in-flight window, not the whole reduced span)
        self._lossy_sender_count = sum(
            1
            for peer_index in self.sender_ranks
            if (lossy_link := self.links.get(peer_index)) is not None and lossy_link.error_feedback
        )
        # sender bans are the degenerate case of the shared cross-layer breaker
        # (resilience/breaker.py): threshold 1, infinite recovery — tripped once,
        # banned for the round's lifetime. `rank in banned_senders` still works.
        self.banned_senders = BreakerBoard(
            "allreduce_senders", failure_threshold=1, recovery_time=float("inf")
        )
        self._sender_last_active: Dict[int, float] = {}
        self._parts_received: Dict[int, int] = {}  # sender rank -> parts accepted
        self._finished = asyncio.Event()
        self._round_span = None  # set by run(); phase spans parent to it

    def _span_part_shapes(self, peer_index: int, part_size_bytes: int) -> list:
        """Part shapes of one peer's reduction span (derivable by every group member
        from the element counts alone — AUX peers have no container). Uses the shared
        partitioning rule so sender splits and reducer expectations cannot drift."""
        from hivemind_tpu.averaging.partition import compute_span_part_sizes

        return [(size,) for size in compute_span_part_sizes(self.peer_element_counts[peer_index], part_size_bytes)]

    # ------------------------------------------------------------------ sending side

    async def run(self) -> AsyncIterator[np.ndarray]:
        """Send parts to all reducers, reduce own span, yield per-tensor deltas
        (AUX mode: reduces only, yields nothing)."""
        round_started = time.perf_counter()
        # detached (run() is a generator — no contextvar install); phase spans
        # below take it as their explicit parent so the trace shows the round
        # decomposed exactly like the _ALLREDUCE_PHASE histogram labels
        self._round_span = _start_span(
            "allreduce.round",
            peer=str(self.p2p.peer_id),
            group_size=len(self.ordered_peer_ids),
            rank=self.my_index,
        )
        communicate_tasks = []
        if self.my_mode != AveragingMode.AUX:
            for peer_index, count in enumerate(self.peer_element_counts):
                if count == 0:
                    continue
                if peer_index == self.my_index:
                    communicate_tasks.append(asyncio.create_task(self._reduce_local_parts()))
                else:
                    communicate_tasks.append(
                        asyncio.create_task(self._communicate_with_peer(peer_index))
                    )
        watchdog = asyncio.create_task(self._sender_watchdog()) if self.peer_element_counts[self.my_index] else None
        try:
            if self.my_mode == AveragingMode.AUX:
                await self._wait_all_parts_reduced()
                return
            assert self.container is not None
            async for delta_tensor in self.container.iterate_output_tensors():
                yield delta_tensor
        finally:
            _finish_span(self._round_span)
            round_elapsed = time.perf_counter() - round_started
            _ALLREDUCE_PHASE.observe(round_elapsed, phase="total")
            if (
                self.my_mode != AveragingMode.AUX
                and self.container is not None
                and round_elapsed > 0
                and self.container._finished.is_set()
                and self.container.failed_size == 0
            ):
                # fp32-equivalent effective rate, same formula as benchmark_averaging
                # — only for rounds that actually moved every byte (a cancelled or
                # degraded round would publish a fictitious rate)
                _AVG_EFFECTIVE_RATE.set(
                    2 * self.container.total_elements * 4 / round_elapsed
                )
            self._finished.set()
            if watchdog is not None:
                watchdog.cancel()
            for task in communicate_tasks:
                if not task.done():
                    task.cancel()
            self.reducer.finalize()

    async def _reduce_local_parts(self) -> None:
        """Loopback: feed own parts into own reducer without serialization."""
        assert self.container is not None
        my_rank = self.sender_ranks[self.my_index]
        phase_started = time.perf_counter()
        with _tracing_span(
            "allreduce.local_reduce", parent=self._round_span, peer=str(self.p2p.peer_id)
        ):
            try:
                for part_index, part in enumerate(self.container.get_raw_input_parts(self.my_index)):
                    self._sender_last_active[my_rank] = get_dht_time()  # lint: single-writer — own rank's key only
                    averaged = await self.reducer.accumulate_part(my_rank, part_index, part, self.weight)
                    self.container.register_processed_part(
                        self.my_index, part_index, averaged - part.astype(np.float32, copy=False)
                    )
            except AllreduceException as e:
                logger.debug(f"local reduction failed: {e}")
                self.container.register_failed_reducer(self.my_index)
            finally:
                _ALLREDUCE_PHASE.observe(time.perf_counter() - phase_started, phase="local_reduce")

    async def _communicate_with_peer(self, peer_index: int) -> None:
        """Stream our parts to one reducer and apply the deltas it returns
        (reference allreduce.py:201-245)."""
        assert self.container is not None
        peer_id = self.ordered_peer_ids[peer_index]
        phase_started = time.perf_counter()
        with _tracing_span(
            "allreduce.peer_exchange",
            parent=self._round_span,
            peer=str(self.p2p.peer_id),
            remote=str(peer_id),
            codec=self._link_tier(peer_index),
        ) as exchange_span:
            await self._communicate_with_peer_traced(peer_index, peer_id, phase_started, exchange_span)

    async def _communicate_with_peer_traced(self, peer_index, peer_id, phase_started, exchange_span) -> None:
        try:
            stub = self.get_stub(peer_id)

            async def _requests():
                first = True
                async for serialized in self.container.iterate_input_parts_for(peer_index):
                    if _CHAOS.enabled:  # injection point: per part shipped to a reducer
                        payload = serialized.buffer
                        injected = await _CHAOS.inject(
                            "allreduce.load", payload=payload, scope=str(self.p2p.peer_id)
                        )
                        if injected is not payload:
                            serialized.buffer = injected
                    _AVG_BYTES_SENT.inc(serialized.ByteSize())
                    yield averaging_pb2.AveragingData(
                        code=averaging_pb2.PART_DATA,
                        group_id=self.group_id if first else b"",
                        tensor_part=serialized,
                        weight=self.weight,
                    )
                    first = False

            part_index = 0
            stream = stub.rpc_aggregate_part(_requests())
            # outlast the reducer's own laggard recovery: it may take up to
            # reducer_timeout to fail a stalled sender and produce our delta
            per_delta_timeout = self.reducer_timeout + self.sender_timeout
            async for response in aiter_with_timeout(stream, per_delta_timeout):
                if response.code != averaging_pb2.PART_DATA:
                    raise AllreduceException(
                        f"peer {peer_id} replied {averaging_pb2.MessageCode.Name(response.code)}"
                    )
                _AVG_BYTES_RECEIVED.inc(response.tensor_part.ByteSize())
                # decode off the event loop (symmetric to the serialize side) so the
                # loop keeps shoveling frames while numpy unpacks the previous delta
                processed = await run_in_executor(deserialize_tensor, response.tensor_part)
                if response.absolute_part:
                    # quantized leg: the payload is the reduced average itself
                    # (quantized once, with the reducer's error feedback); the
                    # delta is recovered against our own input locally
                    self.container.register_processed_absolute(peer_index, part_index, processed)
                else:
                    self.container.register_processed_part(peer_index, part_index, processed)
                part_index += 1
            if part_index < self.container.num_parts_by_peer[peer_index]:
                raise AllreduceException(
                    f"peer {peer_id} closed early: {part_index}/{self.container.num_parts_by_peer[peer_index]} parts"
                )
        except (Exception, asyncio.CancelledError) as e:
            if not isinstance(e, asyncio.CancelledError):
                # swallowed here (the round degrades to local values), so the
                # span must record the failure explicitly — a cancelled task
                # propagates and gets its error event from the with block
                if exchange_span is not None:
                    exchange_span.add_event("error", type=type(e).__name__)
                logger.warning(f"reducer {peer_id} failed: {e!r}; keeping local values for its parts")
                self.container.register_failed_reducer(peer_index)
            else:
                raise
        finally:
            _ALLREDUCE_PHASE.observe(time.perf_counter() - phase_started, phase="peer_exchange")

    # ------------------------------------------------------------------ reducing side

    async def handle_aggregate_stream(
        self,
        first_message: averaging_pb2.AveragingData,
        requests: AsyncIterator[averaging_pb2.AveragingData],
        context: P2PContext,
    ) -> AsyncIterator[averaging_pb2.AveragingData]:
        """Serve one sender's part stream for our reduction span; called by the
        averager's rpc_aggregate_part once the group_id is matched."""
        try:
            sender_peer_index = self.ordered_peer_ids.index(context.remote_id)
        except ValueError:
            yield averaging_pb2.AveragingData(code=averaging_pb2.PROTOCOL_VIOLATION)
            return
        sender_rank = self.sender_ranks.get(sender_peer_index)
        if sender_rank is None or sender_rank in self.banned_senders:
            yield averaging_pb2.AveragingData(code=averaging_pb2.PROTOCOL_VIOLATION)
            return

        # read EAGERLY on a side task: a sender's liveness must be judged by when its
        # parts ARRIVE, not by when the (possibly laggard-blocked) reduction loop gets
        # to them — otherwise one slow sender makes every other sender look stalled
        arrived: asyncio.Queue = asyncio.Queue()

        async def _reader():
            try:
                self._sender_last_active[sender_rank] = get_dht_time()  # lint: single-writer — one reader per sender rank
                self._parts_received[sender_rank] = 1  # lint: single-writer — one reader per sender rank
                await arrived.put(first_message)
                count = 1
                async for message in requests:
                    count += 1
                    self._sender_last_active[sender_rank] = get_dht_time()
                    self._parts_received[sender_rank] = count
                    await arrived.put(message)
            finally:
                await arrived.put(None)

        reader_task = asyncio.create_task(_reader())
        part_index = 0
        try:
            while True:
                message = await arrived.get()
                if message is None:
                    break
                if sender_rank in self.banned_senders:
                    # the watchdog failed this sender; late parts must not leak into
                    # parts that were already averaged without it
                    yield averaging_pb2.AveragingData(code=averaging_pb2.CANCELLED)
                    return
                _AVG_BYTES_RECEIVED.inc(message.tensor_part.ByteSize())
                part = await run_in_executor(deserialize_tensor, message.tensor_part)
                if sender_rank in self.banned_senders:
                    # re-check after the executor hop: the watchdog may have failed
                    # this sender while the decode ran, and a late part must not
                    # slip into an average computed without it
                    yield averaging_pb2.AveragingData(code=averaging_pb2.CANCELLED)
                    return
                try:
                    # weight 0.0 is legitimate (zero-weight peers contribute nothing);
                    # senders always set the field explicitly
                    averaged = await asyncio.wait_for(
                        self.reducer.accumulate_part(
                            sender_rank, part_index, part, float(message.weight)
                        ),
                        timeout=self.reducer_timeout,
                    )
                except asyncio.TimeoutError:
                    # failing the laggards may resolve the part right now — the
                    # on-time sender whose wait expired must still get its delta
                    self._fail_laggards(part_index)
                    averaged = self.reducer.result_nowait(part_index)
                    if averaged is None:
                        yield averaging_pb2.AveragingData(code=averaging_pb2.CANCELLED)
                        return
                link = self.links.get(sender_peer_index)
                if link is not None and link.error_feedback and self.residuals is not None:
                    # quantized leg: ship the averaged part itself, quantized
                    # ONCE per tier with reducer-side error feedback — every
                    # lossy sender gets the same bytes, and senders recover
                    # their delta locally (absolute_part)
                    serialized_part = await self._absolute_average(part_index, averaged, link)
                    if _CHAOS.enabled:  # injection point: per delta returned to a sender
                        payload = serialized_part.buffer
                        injected = await _CHAOS.inject(
                            "allreduce.reduce", payload=payload, scope=str(self.p2p.peer_id)
                        )
                        if injected is not payload:
                            # the cached message is shared across senders: only
                            # THIS sender's copy gets the corruption
                            corrupted_part = runtime_pb2.Tensor()
                            corrupted_part.CopyFrom(serialized_part)
                            corrupted_part.buffer = injected
                            serialized_part = corrupted_part
                    _AVG_BYTES_SENT.inc(serialized_part.ByteSize())
                    yield averaging_pb2.AveragingData(
                        code=averaging_pb2.PART_DATA,
                        tensor_part=serialized_part,
                        absolute_part=True,
                    )
                else:
                    delta = averaged - part.astype(np.float32, copy=False)
                    # the delta is a fresh private array: the codec may clip/normalize
                    # it in place instead of allocating another copy
                    serialized_delta = await run_in_executor(
                        serialize_tensor, delta,
                        link.codec if link is not None else self.compression, None, True,
                    )
                    if _CHAOS.enabled:  # injection point: per delta returned to a sender
                        payload = serialized_delta.buffer
                        injected = await _CHAOS.inject(
                            "allreduce.reduce", payload=payload, scope=str(self.p2p.peer_id)
                        )
                        if injected is not payload:
                            serialized_delta.buffer = injected
                    _AVG_BYTES_SENT.inc(serialized_delta.ByteSize())
                    yield averaging_pb2.AveragingData(
                        code=averaging_pb2.PART_DATA,
                        tensor_part=serialized_delta,
                    )
                part_index += 1
        except (ConnectionError, asyncio.CancelledError, GeneratorExit):
            self._ban_sender(sender_rank, "stream interrupted", cause="interrupted")
            raise
        except AllreduceException as e:
            logger.debug(f"aggregate stream from {context.remote_id} failed: {e}")
            self._ban_sender(sender_rank, str(e))
            yield averaging_pb2.AveragingData(code=averaging_pb2.INTERNAL_ERROR)
            return
        except Exception as e:
            # ANY unexpected reducer failure must release this sender's pending
            # parts: without the ban, other parts of our span wait forever for a
            # contribution this stream will never finish (found by the chaos
            # engine's abort injection at allreduce.reduce — the old test-local
            # fault subclasses always surfaced as GeneratorExit and hid this)
            self._ban_sender(sender_rank, f"reducer error: {e!r}", cause="internal_error")
            raise
        finally:
            reader_task.cancel()
        if part_index < len(self.reducer.part_shapes):
            self._ban_sender(
                sender_rank, f"sent only {part_index}/{len(self.reducer.part_shapes)} parts", cause="incomplete"
            )

    def _link_tier(self, peer_index: int) -> str:
        """The wire tier name of one link, for span/ledger attribution."""
        link = self.links.get(peer_index)
        if link is not None:
            return link.tier
        from hivemind_tpu.compression.serialization import codec_name

        return codec_name(self.compression)

    async def _absolute_average(self, part_index: int, averaged: np.ndarray, link) -> runtime_pb2.Tensor:
        """Quantize one averaged part for the lossy delta leg, single-flight per
        (part, tier): concurrent sender streams share the payload, and the EF
        residual update runs exactly once per part per round (a second lossy
        tier in the same group — rare — quantizes the raw average)."""
        key = (part_index, link.tier)
        future = self._absolute_payloads.get(key)
        if future is not None:
            serialized = await asyncio.shield(future)
            self._consume_absolute(key)
            return serialized
        future = asyncio.get_event_loop().create_future()
        self._absolute_payloads[key] = future
        self._absolute_consumed[key] = 0
        apply_feedback = part_index not in self._reduce_ef_parts
        if apply_feedback:
            self._reduce_ef_parts.add(part_index)

        def _quantize() -> runtime_pb2.Tensor:
            if apply_feedback:
                from hivemind_tpu.averaging.residual import compress_with_feedback

                start = self._my_span_start + self._part_offsets[part_index]
                residual = self.residuals.view("reduce", start, start + averaged.size)
                return compress_with_feedback(averaged, link.codec, residual)
            return serialize_tensor(averaged, link.codec)

        try:
            serialized = await run_in_executor(_quantize)
        except BaseException as e:
            future.set_exception(e)
            # a co-waiting stream will consume it; if none does, don't warn
            future.exception()
            raise
        future.set_result(serialized)
        self._consume_absolute(key)
        return serialized

    def _consume_absolute(self, key: Tuple[int, str]) -> None:
        """One lossy sender took this cached payload; drop it once every lossy
        sender has (a banned sender simply leaves its parts cached until the
        round ends — bounded by the original lifetime, not worse)."""
        count = self._absolute_consumed.get(key)
        if count is None:
            return
        self._absolute_consumed[key] = count + 1
        if self._absolute_consumed[key] >= self._lossy_sender_count:
            self._absolute_payloads.pop(key, None)
            self._absolute_consumed.pop(key, None)

    def _ban_sender(self, sender_rank: int, reason: str, cause: str = "error") -> None:
        if sender_rank not in self.banned_senders:
            logger.debug(f"banning sender {sender_rank}: {reason}")
            _BANNED_SENDERS.inc(cause=cause)
            self.banned_senders.register_failure(sender_rank)  # trips permanently
            self.reducer.on_sender_failed(sender_rank)

    def _fail_laggards(self, part_index: int) -> None:
        """A part timed out: fail every sender that has not contributed to it."""
        for rank in self.reducer.pending_senders(part_index):
            self._ban_sender(rank, f"no part {part_index} within reducer_timeout", cause="reducer_timeout")

    async def _sender_watchdog(self) -> None:
        """Fail senders that never open their stream OR stall mid-stream
        (reference allreduce.py:192-199)."""
        start_time = get_dht_time()
        total_parts = len(self.reducer.part_shapes)
        while not self._finished.is_set():
            await asyncio.sleep(self.sender_timeout / 4)
            now = get_dht_time()
            for peer_index, rank in self.sender_ranks.items():
                if rank in self.banned_senders:
                    continue
                last_active = self._sender_last_active.get(rank)
                reference_time = last_active if last_active is not None else start_time
                unfinished = self._parts_received.get(rank, 0) < total_parts
                if unfinished and now - reference_time > self.sender_timeout:
                    reason = "never started sending" if last_active is None else "stalled mid-stream"
                    self._ban_sender(rank, reason, cause="never_started" if last_active is None else "stalled")

    async def _wait_all_parts_reduced(self) -> None:
        """AUX mode: stay alive until every part of our span is reduced."""
        num_parts = len(self.reducer.part_shapes)
        for part_index in range(num_parts):
            try:
                await self.reducer.wait_part(part_index, timeout=self.reducer_timeout)
            except (asyncio.TimeoutError, AllreduceException):
                self._fail_laggards(part_index)
