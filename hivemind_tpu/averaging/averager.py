"""DecentralizedAverager: iteratively average tensors with random groups of peers
(capability parity: reference hivemind/averaging/averager.py).

The reference is an mp.Process with shared-memory tensors; here the averager is an
asyncio component on the shared loop thread, holding host (numpy) mirrors of the
tensors under a threading lock. ``step()`` is the sync entrypoint; it returns a
StepControl whose two-phase trigger lets callers pre-schedule matchmaking before
gradients are ready (reference averager.py:367-419 + control.py)."""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import os
import threading
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from hivemind_tpu.averaging.allreduce import AllReduceRunner, AveragingMode
from hivemind_tpu.averaging.control import AveragingStage, StepControl
from hivemind_tpu.averaging.group_info import GroupInfo
from hivemind_tpu.averaging.key_manager import GroupKeyManager
from hivemind_tpu.averaging.load_balancing import load_balance_peers
from hivemind_tpu.averaging.matchmaking import Matchmaking, MatchmakingException
from hivemind_tpu.averaging.partition import AllreduceException, DEFAULT_PART_SIZE_BYTES
from hivemind_tpu.averaging.residual import ResidualStore
from hivemind_tpu.averaging.wire_codec import (
    WIRE_TIERS,
    LinkCodecPolicy,
    WireLink,
    make_advert,
    negotiate_link,
    parse_advert,
    publish_link_gauges,
    tier_of_codec,
)
from hivemind_tpu.averaging.state_sync import (
    STATE_CHUNK_BYTES,
    STATE_SYNC_BYTES_SENT as _STATE_SYNC_BYTES_SENT,
    StateDownloadResult,
    build_state_manifest,
    download_state_verified,
)
from hivemind_tpu.compression import (
    CompressionBase,
    NoCompression,
    deserialize_tensor,
    serialize_tensor,
    split_tensor_for_streaming,
)
from hivemind_tpu.compression.base import as_numpy
from hivemind_tpu.dht import DHT
from hivemind_tpu.p2p import P2P, P2PContext, PeerID, ServicerBase
from hivemind_tpu.proto import averaging_pb2, runtime_pb2
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.resilience import Deadline, RetryPolicy
from hivemind_tpu.utils.asyncio_utils import anext_safe, enter_asynchronously
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.loop import LoopRunner, get_loop_runner
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import DHTExpiration, ValueWithExpiration, get_dht_time

logger = get_logger(__name__)

GatheredData = Dict[PeerID, Any]

# layer-3 telemetry (docs/observability.md + ISSUE 3 satellite): internal errors
# this module used to swallow silently, now logged AND counted by site
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import trace as _tracing_span

_AVERAGER_INTERNAL_ERRORS = _TELEMETRY.counter(
    "hivemind_averaging_internal_errors_total",
    "errors in averager plumbing that do not fail a step",
    ("site",),
)

# retry pacing for failed averaging attempts: base 1.6 with equal jitter yields
# exactly the historical U(0.8, 1.6) window multiplier, but through the shared
# policy (resilience/policy.py) so the backoff shape is declared, not hand-rolled
_STEP_RETRY = RetryPolicy(
    max_attempts=None, base_delay=1.6, backoff=1.0, jitter="equal", name="averager_step"
)


class DecentralizedAverager(ServicerBase):
    """See module docstring.

    :param averaged_tensors: tensors (numpy or jax) whose values will be averaged;
        the averager keeps float-preserving numpy mirrors, accessible via get_tensors()
    :param dht: a running DHT instance for matchmaking and state declaration
    :param prefix: swarm-unique namespace; peers with the same prefix average together
    """

    _class_handle_name = "DecentralizedAverager"  # all subclasses share the wire name

    def __init__(
        self,
        averaged_tensors: Sequence,
        dht: DHT,
        *,
        prefix: str,
        start: bool = False,
        target_group_size: Optional[int] = None,
        min_group_size: int = 2,
        initial_group_bits: str = "",
        min_matchmaking_time: float = 5.0,
        request_timeout: float = 3.0,
        allreduce_timeout: Optional[float] = None,
        sender_timeout: float = 30.0,
        reducer_timeout: float = 60.0,
        compression: CompressionBase = NoCompression(),
        part_size_bytes: int = DEFAULT_PART_SIZE_BYTES,
        wire_tiers: Optional[Sequence[str]] = None,
        adaptive_link_codec: bool = False,
        link_policy: Optional[LinkCodecPolicy] = None,
        bandwidth: Optional[float] = None,
        client_mode: bool = False,
        auxiliary: bool = False,
        allow_state_sharing: Optional[bool] = None,
        state_compression: Optional[CompressionBase] = None,
        declare_state_period: float = 30.0,
        shutdown_timeout: float = 5.0,
        blackbox_dir: Optional[Any] = None,
        loop_runner: Optional[LoopRunner] = None,
    ):
        assert "." not in prefix, "prefix may not contain '.'"
        self.dht = dht
        if blackbox_dir is not None:
            # crash-durable flight recorder (docs/observability.md): arm the
            # process-wide spool before the first round; idempotent per directory
            from hivemind_tpu.telemetry.blackbox import arm_blackbox

            arm_blackbox(blackbox_dir, peer=str(dht.peer_id))
        self.prefix = prefix
        self.client_mode, self.auxiliary = client_mode, auxiliary
        self.mode = (
            AveragingMode.CLIENT if client_mode else AveragingMode.AUX if auxiliary else AveragingMode.NODE
        )
        self.target_group_size, self.min_group_size = target_group_size, min_group_size
        self.min_matchmaking_time = min_matchmaking_time
        self.request_timeout, self.allreduce_timeout = request_timeout, allreduce_timeout
        self.sender_timeout, self.reducer_timeout = sender_timeout, reducer_timeout
        self.compression, self.part_size_bytes = compression, part_size_bytes
        self.state_compression = state_compression if state_compression is not None else compression
        # per-link wire-codec negotiation (ISSUE 11): advertise the tiers we
        # support + our default (= the configured codec's tier) in every
        # matchmaking gather blob. A configured codec outside the tier ladder
        # (meanstd/quantile) disables negotiation — links use it as-is.
        self._wire_tier = tier_of_codec(self.compression)
        tiers = tuple(wire_tiers) if wire_tiers is not None else WIRE_TIERS
        if self._wire_tier is not None and self._wire_tier not in tiers:
            tiers = (*tiers, self._wire_tier)
        self._wire_tiers = tuple(t for t in tiers if t in WIRE_TIERS)
        self._wire_residuals = ResidualStore()
        if link_policy is not None:
            self._link_policy: Optional[LinkCodecPolicy] = link_policy
            if self._link_policy.default_tier is None:
                self._link_policy.default_tier = self._wire_tier
        else:
            self._link_policy = (
                LinkCodecPolicy(default_tier=self._wire_tier)
                if adaptive_link_codec and self._wire_tier is not None
                else None
            )
        self.bandwidth = bandwidth if bandwidth is not None else (0.0 if client_mode else 1.0e8)
        self.declare_state_period = declare_state_period
        self.shutdown_timeout = shutdown_timeout

        self._averaged_tensors: List[np.ndarray] = [np.array(as_numpy(t), copy=True) for t in averaged_tensors]
        self.lock_averaged_tensors = threading.Lock()
        self._allow_state_sharing = (
            allow_state_sharing if allow_state_sharing is not None else not (client_mode or auxiliary)
        )
        self._state_sharing_priority = 0.0

        self.schema_hash = self._compute_schema_hash()
        self._runner = loop_runner if loop_runner is not None else get_loop_runner()
        self._running_allreduces: Dict[bytes, AllReduceRunner] = {}
        self._allreduce_registered = asyncio.Condition()  # created lazily on loop? see _setup
        self._ready = threading.Event()
        self._shutdown = False
        self.matchmaking: Optional[Matchmaking] = None
        self.key_manager: Optional[GroupKeyManager] = None
        self._declare_state_task: Optional[asyncio.Task] = None
        self.initial_group_bits = initial_group_bits

        if start:
            self.run_in_background(await_ready=True)

    # ------------------------------------------------------------------ lifecycle

    def run_in_background(self, await_ready: bool = True, timeout: Optional[float] = None) -> None:
        future = self._runner.run_coroutine(self._setup(), return_future=True)
        if await_ready:
            future.result(timeout)

    async def _setup(self) -> None:
        if self._ready.is_set():
            return
        # the shared loop carries every RPC/matchmaking/allreduce await of this
        # peer: arm the stall watchdog before any of them can run (idempotent —
        # the DHT usually armed it already)
        from hivemind_tpu.telemetry.watchdog import ensure_watchdog

        ensure_watchdog(asyncio.get_event_loop())
        self.p2p: P2P = await self.dht.replicate_p2p()
        self.peer_id: PeerID = self.p2p.peer_id
        self._allreduce_registered = asyncio.Condition()
        self.key_manager = GroupKeyManager(
            self.dht, self.prefix, self.initial_group_bits, self.target_group_size
        )
        self.matchmaking = Matchmaking(
            self.p2p,
            self.key_manager,
            self._get_peer_stub,
            schema_hash=self.schema_hash,
            target_group_size=self.target_group_size,
            min_group_size=self.min_group_size,
            min_matchmaking_time=self.min_matchmaking_time,
            request_timeout=self.request_timeout,
            client_mode=self.client_mode,
        )
        await self.add_p2p_handlers(self.p2p, namespace=self.prefix)
        if self._allow_state_sharing:
            self._declare_state_task = spawn(self._declare_for_download_periodically(), name="averager.declare_state")
        # opportunistic: never gates readiness (fire-and-forget task)
        self._warmup_task = spawn(self._warm_data_path(), name="averager.warmup")
        self._ready.set()

    async def _warm_data_path(self) -> None:
        """Spin up the lazy machinery the first all-reduce round would otherwise pay
        for inside its measured window: executor threads, the AEAD worker pool and
        cipher context, numpy's allocator, and protobuf serialization. Runs in the
        background; failures are cosmetic (the round would just warm things itself)."""
        try:
            import concurrent.futures

            # the channel's own resolved cipher binding (wheel or libcrypto shim),
            # so the warmup heats the implementation SecureChannel actually uses
            from hivemind_tpu.p2p.crypto_channel import ChaCha20Poly1305, _get_aead_executor
            from hivemind_tpu.utils.asyncio_utils import _blocking_executor

            def _touch() -> None:
                block = np.zeros(1 << 16, np.float32)
                serialize_tensor(block.astype(np.float32, copy=False), self.compression)

            warm_futures = [_blocking_executor.submit(_touch) for _ in range(4)]
            aead_executor = _get_aead_executor()
            if aead_executor is not None:
                aead = ChaCha20Poly1305(bytes(32))
                warm_futures += [
                    aead_executor.submit(aead.encrypt, bytes(12), b"\x00" * (1 << 17), None)
                    for _ in range(2)
                ]
            await asyncio.get_event_loop().run_in_executor(
                None, concurrent.futures.wait, warm_futures, 2.0
            )
        except Exception as e:
            logger.debug(f"data-path warmup skipped: {e!r}")

    @property
    def is_alive(self) -> bool:
        return self._ready.is_set() and not self._shutdown

    @property
    def allow_state_sharing(self) -> bool:
        return self._allow_state_sharing

    @allow_state_sharing.setter
    def allow_state_sharing(self, value: bool) -> None:
        self._allow_state_sharing = value
        if value and self._ready.is_set() and not self._shutdown:
            # the declare loop may never have been started (e.g. sharing was off at
            # construction); without it peers can never discover our state
            async def _ensure_declare_task():
                if self._declare_state_task is None or self._declare_state_task.done():
                    self._declare_state_task = spawn(
                        self._declare_for_download_periodically(), name="averager.declare_state"
                    )

            self._runner.run_coroutine(_ensure_declare_task(), return_future=True)

    @property
    def state_sharing_priority(self) -> float:
        return self._state_sharing_priority

    @state_sharing_priority.setter
    def state_sharing_priority(self, value: float) -> None:
        self._state_sharing_priority = value

    def shutdown(self) -> None:
        if self._shutdown or not self._ready.is_set():
            self._shutdown = True
            return
        self._shutdown = True

        async def _teardown():
            if self._declare_state_task is not None:
                self._declare_state_task.cancel()
                await self._retract_state_declaration()
            warmup_task = getattr(self, "_warmup_task", None)
            if warmup_task is not None:
                warmup_task.cancel()
            with contextlib.suppress(Exception):
                await self.remove_p2p_handlers(self.p2p, namespace=self.prefix)

        coro = _teardown()
        try:
            future = self._runner.run_coroutine(coro, return_future=True)
        except Exception as e:
            # the loop is already gone (interpreter teardown / runner shut down):
            # shutdown still succeeds, but say so — a silent pass here hid real
            # teardown bugs for two rounds (ISSUE 3 satellite)
            logger.warning(f"averager teardown could not be scheduled: {e!r}")
            _AVERAGER_INTERNAL_ERRORS.inc(site="shutdown_schedule")
            coro.close()  # never scheduled: release the un-awaited coroutine cleanly
        else:
            try:
                future.result(self.shutdown_timeout)
            except Exception as e:
                logger.warning(f"averager teardown did not finish cleanly: {e!r}")
                _AVERAGER_INTERNAL_ERRORS.inc(site="shutdown_teardown")

    def __enter__(self):
        if not self._ready.is_set():
            self.run_in_background(await_ready=True)
        return self

    def __exit__(self, *args):
        self.shutdown()

    def __del__(self):
        with contextlib.suppress(Exception):
            if self.is_alive:
                self.shutdown()

    # ------------------------------------------------------------------ tensors

    @contextlib.contextmanager
    def get_tensors(self):
        """Host-side access to the averaged tensors (mutable, lock-guarded —
        reference averager.py:564-572)."""
        with self.lock_averaged_tensors:
            yield self._averaged_tensors

    def _compute_schema_hash(self) -> str:
        schema = [[list(t.shape), str(t.dtype)] for t in self._averaged_tensors]
        payload = MSGPackSerializer.dumps([schema, type(self.compression).__name__, "v1"])
        return hashlib.sha256(payload).hexdigest()[:32]

    def _suggested_lead(self) -> float:
        """Adaptive matchmaking lead time (VERDICT r3 #5): when the caller does not
        pin a scheduled_time, use the matchmaking layer's observed declare→fill
        latency + failure backoff instead of the raw ``min_matchmaking_time``."""
        if self.matchmaking is not None:
            return self.matchmaking.suggested_lead_time()
        return self.min_matchmaking_time

    def _get_peer_stub(self, peer_id: PeerID):
        return type(self).get_stub(self.p2p, peer_id, namespace=self.prefix)

    # ------------------------------------------------------------------ stepping

    def step(
        self,
        gather: Any = None,
        *,
        weight: Optional[float] = None,
        scheduled_time: Optional[DHTExpiration] = None,
        timeout: Optional[float] = None,
        allow_retries: bool = True,
        require_trigger: bool = False,
        wait: bool = True,
    ) -> Union[Optional[GatheredData], StepControl]:
        """Try to average tensors with a group of peers.

        :param gather: opaque metadata exchanged with groupmates (returned as a dict)
        :param require_trigger: two-phase mode — matchmaking may start now, but the
            all-reduce waits for control.allow_allreduce()
        :param wait: block and return gathered data; else return the StepControl
        """
        if self.mode == AveragingMode.AUX and weight is not None and weight != 0:
            logger.warning("auxiliary peers always have weight 0; ignoring")
            weight = 0.0
        weight = weight if weight is not None else float(self.mode != AveragingMode.AUX)
        now = get_dht_time()
        control = StepControl(
            scheduled_time=scheduled_time if scheduled_time is not None else now + self._suggested_lead(),
            deadline=now + timeout if timeout is not None else None,
            allow_retries=allow_retries,
            weight=weight,
            data_for_gather=MSGPackSerializer.dumps(
                [self.bandwidth, self.mode.value, gather, self._wire_advert()]
            ),
        )
        if not require_trigger:
            control.allow_allreduce()
        self._runner.run_coroutine(self._step(control), return_future=True)
        return control.result(timeout) if wait else control

    async def _step(self, control: StepControl) -> None:
        try:
            while not control.done():
                try:
                    control.stage = AveragingStage.LOOKING_FOR_GROUP
                    assert self.matchmaking is not None
                    group_info = await self.matchmaking.look_for_group(
                        data_for_gather=control.data_for_gather,
                        scheduled_time=control.scheduled_time,
                        timeout=control.get_timeout(),
                    )
                    if control.cancelled:
                        return
                    if group_info is None:
                        raise MatchmakingException("could not find a group this attempt")
                    control.stage = AveragingStage.AWAITING_TRIGGER
                    await control.wait_for_trigger()
                    if control.cancelled:
                        return
                    control.began_allreduce = True
                    control.stage = AveragingStage.RUNNING_ALLREDUCE
                    gathered = await self._aggregate_with_group(group_info, control.weight)
                    control.set_result(gathered)
                    return
                except (
                    MatchmakingException,
                    AllreduceException,
                    AssertionError,
                    asyncio.TimeoutError,
                    ConnectionError,
                ) as e:
                    deadline_passed = control.deadline is not None and get_dht_time() >= control.deadline
                    if not control.allow_retries or deadline_passed:
                        logger.info(f"averaging step failed: {e!r}")
                        control.set_exception(e)
                        return
                    logger.debug(f"averaging attempt failed: {e!r}; retrying")
                    # fresh matchmaking window with jitter: symmetric failures would
                    # otherwise re-synchronize and livelock (everyone re-declares the
                    # same deadline and nobody becomes anyone's leader)
                    control.reset_for_retry(
                        get_dht_time() + self._suggested_lead() * _STEP_RETRY.delay(0)
                    )
        except asyncio.CancelledError:
            control.cancel()
            raise
        except Exception as e:
            control.set_exception(e)

    def _wire_advert(self) -> Optional[Dict[str, Any]]:
        """The codec advert riding this peer's matchmaking gather blob — the
        zero-extra-round-trip negotiation channel (every groupmate sees every
        advert at BEGIN_ALLREDUCE, mirroring the serving path's ``peer|codec``
        DHT records). Carries the straggler policy's current demotions."""
        if self._wire_tier is None:
            return None
        demotions: Dict[str, str] = {}
        if self._link_policy is not None:
            try:
                local = str(self.peer_id) if hasattr(self, "peer_id") else None
                demotions = self._link_policy.refresh(exclude=(local,) if local else ())
            except Exception as e:
                logger.warning(f"link-codec policy refresh failed: {e!r}")
                _AVERAGER_INTERNAL_ERRORS.inc(site="link_policy")
        return make_advert(self._wire_tiers, self._wire_tier, demotions)

    def _decode_gathered(self, group_info: GroupInfo):
        """(bandwidths, modes, user_gathered, adverts) from the gather blobs.
        Slot 3 — the wire-codec advert (ISSUE 11) — is optional and tolerant
        (``parse_advert`` maps anything malformed to None: that peer's links
        just fall back to the configured codec); slots 0-2 are load-bearing
        and a blob without them fails the round, exactly as before."""
        bandwidths, modes, user_gathered = [], [], {}
        adverts: Dict[PeerID, Optional[Dict[str, Any]]] = {}
        for peer_id, blob in zip(group_info.peer_ids, group_info.gathered):
            decoded = MSGPackSerializer.loads(blob)
            peer_bandwidth, peer_mode, user_data = decoded[0], decoded[1], decoded[2]
            bandwidths.append(float(peer_bandwidth))
            modes.append(AveragingMode(peer_mode))
            user_gathered[peer_id] = user_data
            adverts[peer_id] = parse_advert(decoded[3]) if len(decoded) > 3 else None
        return bandwidths, modes, user_gathered, adverts

    def _negotiate_links(
        self, group_info: GroupInfo, adverts: Dict[PeerID, Optional[Dict[str, Any]]]
    ) -> Optional[Dict[int, WireLink]]:
        """Resolve the wire link for every groupmate from the gathered adverts.
        Symmetric by construction: both endpoints evaluate the same pure
        function over the same two adverts (ours is read back from the gather,
        i.e. exactly what the remote saw). Returns None when negotiation is
        disabled or nobody advertised — the byte-identical legacy path."""
        if self._wire_tier is None:
            return None
        local_advert = adverts.get(self.peer_id)
        if local_advert is None:
            return None
        links: Dict[int, WireLink] = {}
        tiers_by_remote: Dict[str, str] = {}
        for index, peer_id in enumerate(group_info.peer_ids):
            if peer_id == self.peer_id:
                continue
            tier = negotiate_link(local_advert, adverts.get(peer_id), str(self.peer_id), str(peer_id))
            if tier is None:
                continue
            links[index] = WireLink.for_tier(tier)
            tiers_by_remote[str(peer_id)] = tier
        if not links:
            return None
        publish_link_gauges(tiers_by_remote)
        from hivemind_tpu.telemetry.tracing import current_span

        span = current_span()
        if span is not None:
            for remote, tier in tiers_by_remote.items():
                if tier != self._wire_tier:  # only negotiated-away links are events
                    span.add_event("link_codec", remote=remote, tier=tier)
        return links

    async def _pre_allreduce(self) -> None:
        """Hook: refresh the host tensor mirrors just before an all-reduce round.
        MeshAverager stages the mesh-resident state here (ICI tier); the default
        host-resident averager needs nothing."""

    async def _post_allreduce(self) -> None:
        """Hook: propagate the averaged host mirrors after a round (MeshAverager
        scatters them back onto the mesh)."""

    async def _aggregate_with_group(self, group_info: GroupInfo, weight: float) -> GatheredData:
        """Decode gathered metadata, balance load, run the all-reduce, apply deltas
        (reference averager.py:514-562)."""
        with _tracing_span(
            "averaging.aggregate",
            peer=str(self.peer_id),
            group_size=len(group_info.peer_ids),
        ):
            return await self._aggregate_with_group_traced(group_info, weight)

    async def _aggregate_with_group_traced(self, group_info: GroupInfo, weight: float) -> GatheredData:
        bandwidths, modes, user_gathered, adverts = self._decode_gathered(group_info)
        await self._pre_allreduce()

        with self.lock_averaged_tensors:
            total_elements = sum(int(np.prod(t.shape)) for t in self._averaged_tensors)
        reducer_bandwidths = [
            bandwidth if mode != AveragingMode.CLIENT else 0.0
            for bandwidth, mode in zip(bandwidths, modes)
        ]
        peer_element_counts = load_balance_peers(total_elements, reducer_bandwidths)

        if _CHAOS.enabled:  # injection point: die between matchmaking and the round
            await _CHAOS.inject("allreduce.setup", scope=str(self.peer_id))
        links = self._negotiate_links(group_info, adverts)
        runner = self._make_allreduce_runner(group_info, peer_element_counts, modes, weight, links=links)
        async with self._allreduce_registered:
            self._running_allreduces[group_info.group_id] = runner  # lint: single-writer — holds _allreduce_registered's lock
            self._allreduce_registered.notify_all()
        try:
            iterator = runner.run()
            if self.allreduce_timeout is not None:
                from hivemind_tpu.utils.asyncio_utils import aiter_with_timeout

                iterator = aiter_with_timeout(iterator, self.allreduce_timeout)
            index = 0
            async for delta in iterator:
                await self._apply_delta(index, delta)
                index += 1
            if runner.container is not None and runner.container.failed_size:
                logger.warning(
                    f"allreduce degraded: {runner.container.failed_size}/{runner.container.total_elements} "
                    f"elements kept local values (failed reducers)"
                )
            await self._post_allreduce()
            return user_gathered
        finally:
            self._running_allreduces.pop(group_info.group_id, None)

    async def _run_manual_allreduce(
        self,
        group_info: GroupInfo,
        tensors: List[np.ndarray],
        *,
        group_id_suffix: bytes,
        modes: Sequence[AveragingMode],
        bandwidths: Sequence[float],
        weight: float,
    ) -> List[np.ndarray]:
        """One all-reduce over arbitrary tensors within an already-matched group —
        the building block for multi-phase schemes like PowerSGD (which chains two
        rounds per group, reference power_sgd_averager.py:117-178). Returns the
        averaged tensors (inputs are not mutated)."""
        group_id = group_info.group_id + group_id_suffix
        total_elements = sum(int(np.prod(t.shape)) for t in tensors)
        reducer_bandwidths = [
            bandwidth if mode != AveragingMode.CLIENT else 0.0
            for bandwidth, mode in zip(bandwidths, modes)
        ]
        peer_element_counts = load_balance_peers(total_elements, reducer_bandwidths)
        runner = AllReduceRunner(
            p2p=self.p2p,
            group_id=group_id,
            tensors=tensors,
            ordered_peer_ids=group_info.peer_ids,
            peer_element_counts=peer_element_counts,
            modes=modes,
            get_stub=self._get_peer_stub,
            weight=weight,
            compression=self.compression,
            part_size_bytes=self.part_size_bytes,
            sender_timeout=self.sender_timeout,
            reducer_timeout=self.reducer_timeout,
        )
        async with self._allreduce_registered:
            self._running_allreduces[group_id] = runner  # lint: single-writer — holds _allreduce_registered's lock
            self._allreduce_registered.notify_all()
        try:
            averaged = [np.array(t, dtype=np.float32, copy=True) for t in tensors]
            index = 0
            async for delta in runner.run():
                averaged[index] += delta.reshape(averaged[index].shape)
                index += 1
            return averaged
        finally:
            self._running_allreduces.pop(group_id, None)

    def _make_allreduce_runner(
        self,
        group_info: GroupInfo,
        peer_element_counts: Sequence[int],
        modes: Sequence[AveragingMode],
        weight: float,
        links: Optional[Dict[int, WireLink]] = None,
    ) -> AllReduceRunner:
        """Overridable factory — the designed-in fault-injection seam (the reference's
        tests override the equivalent to inject mid-stream failures, SURVEY §4)."""
        return AllReduceRunner(
            p2p=self.p2p,
            group_id=group_info.group_id,
            tensors=self._snapshot_tensors(),
            ordered_peer_ids=group_info.peer_ids,
            peer_element_counts=peer_element_counts,
            modes=modes,
            get_stub=self._get_peer_stub,
            weight=weight,
            compression=self.compression,
            part_size_bytes=self.part_size_bytes,
            sender_timeout=self.sender_timeout,
            reducer_timeout=self.reducer_timeout,
            links=links,
            residuals=self._wire_residuals,
        )

    def _snapshot_tensors(self) -> List[np.ndarray]:
        with self.lock_averaged_tensors:
            return [t.copy() for t in self._averaged_tensors]

    async def _apply_delta(self, index: int, delta: np.ndarray) -> None:
        async with enter_asynchronously(self.lock_averaged_tensors):
            tensor = self._averaged_tensors[index]
            tensor += delta.astype(tensor.dtype, copy=False)

    # ------------------------------------------------------------------ RPCs

    async def rpc_join_group(
        self, request: averaging_pb2.JoinRequest, context: P2PContext
    ) -> AsyncIterator[averaging_pb2.MessageFromLeader]:
        assert self.matchmaking is not None
        async for message in self.matchmaking.rpc_join_group(request, context):
            yield message

    async def rpc_aggregate_part(
        self, requests: AsyncIterator[averaging_pb2.AveragingData], context: P2PContext
    ) -> AsyncIterator[averaging_pb2.AveragingData]:
        """Route one sender's part stream to the matching allreduce runner; tolerates
        the sender arriving before our own group registration (the race at reference
        averager.py:585-590)."""
        first = await anext_safe(requests.__aiter__() if hasattr(requests, "__aiter__") else requests)
        if not isinstance(first, averaging_pb2.AveragingData):
            return
        runner = await self._find_runner(first.group_id)
        if runner is None:
            yield averaging_pb2.AveragingData(code=averaging_pb2.PROTOCOL_VIOLATION)
            return
        async for message in runner.handle_aggregate_stream(first, requests, context):
            yield message

    async def _find_runner(self, group_id: bytes, timeout: Optional[float] = None) -> Optional[AllReduceRunner]:
        budget = Deadline(timeout if timeout is not None else self.request_timeout * 2)
        async with self._allreduce_registered:
            while group_id not in self._running_allreduces:
                try:
                    await budget.wait_for(self._allreduce_registered.wait())
                except asyncio.TimeoutError:  # includes DeadlineExceeded
                    return None
            return self._running_allreduces[group_id]

    # ------------------------------------------------------------------ state sharing

    async def _get_current_state(self) -> Tuple[Any, List[np.ndarray]]:
        """Overridable: the state downloadable by joining peers. Default: no metadata,
        the averaged tensors (reference get_current_state)."""
        return None, self._snapshot_tensors()

    # serialized-state snapshots are shared across concurrent downloads for this
    # long: striping probes + two stripe streams pay ONE serialize+digest pass,
    # and the manifest always matches the exact bytes streamed
    state_snapshot_ttl: float = 1.0

    async def _serialized_state_snapshot(self):
        """(metadata_blob, serialized tensors, manifest), built at most once per
        TTL window. Concurrent callers (striping probes + stripe streams + other
        joiners) await ONE shared task instead of each running their own full
        serialize+digest pass — otherwise N concurrent downloads would hold N
        serialized state copies in donor memory. The expiry is anchored at pass
        COMPLETION (a multi-GB pass takes seconds; anchoring at the start would
        publish an already-expired cache), and the pass runs in an executor so
        the event loop keeps serving matchmaking/allreduce meanwhile."""
        entry = getattr(self, "_state_snapshot_entry", None)
        if entry is not None:
            task, expiry_box = entry
            if not task.done():
                reusable = True  # join the in-flight pass
            elif task.cancelled() or task.exception() is not None:
                reusable = False  # failed pass: rebuild for this caller
            else:
                reusable = expiry_box[0] is not None and time.monotonic() < expiry_box[0]
            if reusable:
                return await task
        expiry_box: List[Optional[float]] = [None]
        task = asyncio.get_event_loop().create_task(self._build_state_snapshot(expiry_box))
        self._state_snapshot_entry = (task, expiry_box)
        return await task

    async def _build_state_snapshot(self, expiry_box):
        metadata, tensors = await self._get_current_state()
        metadata_blob = MSGPackSerializer.dumps(metadata)
        epoch = int(metadata["epoch"]) if isinstance(metadata, dict) and "epoch" in metadata else 0

        def _serialize_and_digest():
            serialized = [serialize_tensor(tensor, self.state_compression) for tensor in tensors]
            manifest = build_state_manifest(
                serialized, schema_hash=self.schema_hash, epoch=epoch, metadata=metadata_blob
            )
            return serialized, manifest

        loop = asyncio.get_event_loop()
        serialized, manifest = await loop.run_in_executor(None, _serialize_and_digest)
        expiry_box[0] = time.monotonic() + self.state_snapshot_ttl

        # the cache must not pin a full serialized state copy forever: drop the
        # entry shortly after its TTL unless a newer snapshot replaced it
        def _drop_if_expired():
            current = getattr(self, "_state_snapshot_entry", None)
            if (
                current is not None
                and current[1][0] is not None
                and time.monotonic() >= current[1][0]
            ):
                self._state_snapshot_entry = None

        loop.call_later(self.state_snapshot_ttl + 0.1, _drop_if_expired)
        return metadata_blob, serialized, manifest

    async def rpc_download_state(
        self, request: averaging_pb2.DownloadRequest, context: P2PContext
    ) -> AsyncIterator[averaging_pb2.DownloadData]:
        """Manifest-first state stream (reference averager.py:628-651, hardened per
        ISSUE 7): the first message carries a :class:`StateManifest` — schema
        fingerprint, donor epoch, per-tensor length + digest — so the receiver can
        verify every tensor as it lands, resume across donors, and distinguish
        "sharing disabled" from a truncated stream. ``request.have_tensors`` names
        already-verified tensors the receiver does not need again."""
        if not self._allow_state_sharing:
            # explicit refusal: a clean "no" must never look like a dead donor
            yield averaging_pb2.DownloadData(
                manifest=averaging_pb2.StateManifest(state_unavailable=True)
            )
            return
        metadata_blob, serialized, manifest = await self._serialized_state_snapshot()
        # legacy ``metadata`` field kept alongside the manifest for old readers
        yield averaging_pb2.DownloadData(manifest=manifest, metadata=metadata_blob)
        if request.manifest_only:
            return
        have = set(request.have_tensors)
        donor_scope = str(self.peer_id)
        for index, tensor in enumerate(serialized):
            if index in have:
                continue
            for chunk in split_tensor_for_streaming(tensor, STATE_CHUNK_BYTES):
                if _CHAOS.enabled:  # injection point: donor dies / corrupts mid-stream
                    payload = chunk.buffer
                    injected = await _CHAOS.inject(
                        "state.download.send", payload=payload, scope=donor_scope
                    )
                    if injected is not payload:
                        chunk.buffer = injected
                _STATE_SYNC_BYTES_SENT.inc(len(chunk.buffer))
                yield averaging_pb2.DownloadData(tensor_part=chunk, tensor_index=index)

    @classmethod
    async def _download_verified_async(
        cls,
        dht: DHT,
        p2p: P2P,
        prefix: str,
        *,
        exclude_peer_id: Optional[PeerID] = None,
        timeout: Optional[float] = None,
        expected_tensors: Optional[int] = None,
        schema_hash: Optional[str] = None,
        min_epoch: Optional[int] = None,
    ) -> Optional[StateDownloadResult]:
        """Verified, resumable, optionally striped state download from the donors
        declared under ``{prefix}.all_averagers`` (state_sync.py, ISSUE 7).
        Classmethod on purpose: peers that do not yet KNOW the tensor schema
        (auxiliary helpers) can bootstrap it from the swarm before constructing
        their averager (reference aux peers are schema-free)."""

        def _count_donor_failure(donor, exc) -> None:
            # ISSUE 7 satellite: a swarm where EVERY donor fails must be visible —
            # each failed attempt is counted (state_sync already logs a warning).
            # Clean protocol answers (sharing disabled / stale epoch) are not
            # errors and carry their own dedicated counters.
            from hivemind_tpu.averaging.state_sync import StaleDonor, StateUnavailable

            if not isinstance(exc, (StaleDonor, StateUnavailable)):
                _AVERAGER_INTERNAL_ERRORS.inc(site="state_download")

        result = await download_state_verified(
            dht, p2p, prefix, cls.get_stub,
            exclude_peer_id=exclude_peer_id,
            timeout=timeout,
            expected_tensors=expected_tensors,
            schema_hash=schema_hash,
            min_epoch=min_epoch,
            on_donor_failure=_count_donor_failure,
        )
        if result is None:
            logger.warning(f"could not download state for {prefix!r} from any peer")
            return None
        logger.info(
            f"downloaded state for {prefix!r} from {result.donors} at epoch {result.epoch} "
            f"({'digest-verified' if result.verified else 'UNVERIFIED legacy stream'}, "
            f"{result.bytes_received} bytes)"
        )
        return result

    @classmethod
    async def _download_state_async(
        cls,
        dht: DHT,
        p2p: P2P,
        prefix: str,
        *,
        exclude_peer_id: Optional[PeerID] = None,
        timeout: Optional[float] = None,
        expected_tensors: Optional[int] = None,
    ) -> Optional[Tuple[Any, List[np.ndarray]]]:
        """(metadata, tensors) view of :meth:`_download_verified_async` — the
        schema-free entry point used by aux bootstrap and old call sites."""
        result = await cls._download_verified_async(
            dht, p2p, prefix, exclude_peer_id=exclude_peer_id, timeout=timeout,
            expected_tensors=expected_tensors,
        )
        return None if result is None else (result.metadata, result.tensors)

    async def _load_state_from_peers_async(
        self, timeout: Optional[float] = None, min_epoch: Optional[int] = None
    ) -> Optional[StateDownloadResult]:
        # an averager KNOWS its schema: donors serving a different tensor count
        # (truncated mid-download or mismatched run) are rejected at the manifest,
        # and stale donors (epoch < min_epoch) are skipped before any bytes move.
        # The manifest's schema fingerprint is NOT pinned here: it embeds the
        # donor's codec, and heterogeneous-but-compatible donors (e.g. an aux
        # NoCompression donor feeding a Float16 state averager) are a designed
        # pattern — integrity comes from the per-tensor digests + tensor count.
        with self.get_tensors() as tensors:
            expected = len(tensors)
        return await type(self)._download_verified_async(
            self.dht, self.p2p, self.prefix, exclude_peer_id=self.peer_id, timeout=timeout,
            expected_tensors=expected, min_epoch=min_epoch,
        )

    def load_state_from_peers(self, timeout: Optional[float] = None, wait: bool = True):
        """Fetch (metadata, tensors) from the best-priority peer sharing state."""

        async def _tuple_view():
            result = await self._load_state_from_peers_async(timeout)
            return None if result is None else (result.metadata, result.tensors)

        future = self._runner.run_coroutine(_tuple_view(), return_future=True)
        return future.result(timeout) if wait else future

    @classmethod
    def download_state_from_swarm(
        cls, dht: DHT, prefix: str, timeout: Optional[float] = None, wait: bool = True
    ):
        """Schema-free state download: no averager instance required (used by aux
        peers to learn the gradient schema before joining; VERDICT r1 item 7)."""

        async def _run(_dht, _node):
            p2p = await _dht.replicate_p2p()
            return await cls._download_state_async(_dht, p2p, prefix, timeout=timeout)

        future = dht.run_coroutine(_run, return_future=True)
        return future.result(timeout) if wait else future

    async def _declare_for_download_periodically(self) -> None:
        key = f"{self.prefix}.all_averagers"
        while True:
            if self._allow_state_sharing:
                try:
                    expiration = get_dht_time() + self.declare_state_period * 2
                    await self.dht.node.store(
                        key,
                        value=self._state_sharing_priority,
                        expiration_time=expiration,
                        subkey=self.peer_id.to_base58(),
                    )
                    # remembered so shutdown can retract with a FRESHER record
                    # (per-subkey stores are newest-expiration-wins; an older
                    # tombstone would simply be ignored)
                    self._declared_state_expiration = expiration
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # failing to declare is survivable (peers just cannot download
                    # state from us until the next period) but must be counted
                    logger.warning(f"could not declare state under {key!r}: {e!r}")
                    _AVERAGER_INTERNAL_ERRORS.inc(site="declare_state")
            await asyncio.sleep(self.declare_state_period)

    async def _retract_state_declaration(self) -> None:
        """ISSUE 7 satellite: a cleanly-departing donor overwrites its
        ``{prefix}.all_averagers`` record with a ``None`` tombstone, so joiners
        stop spending a dial + timeout on a peer that is provably gone. The DHT
        refuses past-expiration and older-than-existing stores, so the tombstone
        must be *fresher* than the last declaration; readers filter ``None``."""
        declared = getattr(self, "_declared_state_expiration", None)
        if declared is None:
            return
        try:
            # strictly fresher than ANY declaration the loop could have issued —
            # including one still in flight when the task was cancelled, whose
            # expiration (its now + 2*period) exceeds the last RECORDED one
            tombstone_expiration = get_dht_time() + self.declare_state_period * 2 + 1.0
            await asyncio.wait_for(
                self.dht.node.store(
                    f"{self.prefix}.all_averagers",
                    value=None,
                    expiration_time=max(tombstone_expiration, declared + 1.0),
                    subkey=self.peer_id.to_base58(),
                ),
                timeout=max(0.5, self.shutdown_timeout / 2),
            )
        except Exception as e:
            # best-effort: joiners fall back to the dial-timeout path they
            # always had — but a chronically failing retract should be visible
            logger.warning(f"could not retract state declaration: {e!r}")
            _AVERAGER_INTERNAL_ERRORS.inc(site="state_retract")

    def get_group_bits(self) -> str:
        assert self.key_manager is not None
        return self.key_manager.group_bits

    def set_group_bits(self, bits: str) -> None:
        assert self.key_manager is not None
        self.key_manager.group_bits = bits

    def __repr__(self):
        return f"{type(self).__name__}(prefix={self.prefix!r}, mode={self.mode.name}, alive={self.is_alive})"
