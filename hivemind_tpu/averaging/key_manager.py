"""Group keys: where averagers advertise themselves for matchmaking
(capability parity: reference hivemind/averaging/key_manager.py).

Averagers looking for a group declare themselves as subkeys of ``{prefix}.0b{bits}``.
After every successful round the group id seeds an RNG that scatters the members into
fresh buckets, so information mixes across the whole swarm over successive rounds
(reference key_manager.py:94-105; the "Moshpit SGD" rebucketing)."""

from __future__ import annotations

import random
import re
from typing import List, Optional, Tuple

from hivemind_tpu.averaging.group_info import GroupInfo
from hivemind_tpu.dht import DHT
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time

logger = get_logger(__name__)

GroupKey = str
GROUP_PATTERN = re.compile(r"^(([^.])+)[.]0b[01]*$")


def is_valid_group(maybe_group: str) -> bool:
    return bool(GROUP_PATTERN.fullmatch(maybe_group))


class GroupKeyManager:
    def __init__(self, dht: DHT, prefix: str, initial_group_bits: str = "", target_group_size: Optional[int] = None):
        assert all(bit in "01" for bit in initial_group_bits)
        self.dht, self.prefix = dht, prefix
        self.group_bits = initial_group_bits
        self.target_group_size = target_group_size
        self.peer_id = dht.peer_id

    @property
    def current_key(self) -> GroupKey:
        return f"{self.prefix}.0b{self.group_bits}"

    async def declare_averager(
        self, group_key: GroupKey, peer_id: PeerID, expiration_time: DHTExpiration, looking_for_group: bool = True
    ) -> bool:
        """Advertise (or retract) an averager under the group key
        (reference key_manager.py:46-68)."""
        expiration = expiration_time if looking_for_group else get_dht_time() + 1
        return await self.dht.node.store(
            key=group_key,
            subkey=peer_id.to_base58(),
            value=looking_for_group,
            expiration_time=expiration,
        )

    async def get_averagers(self, group_key: GroupKey, only_active: bool = True) -> List[Tuple[PeerID, DHTExpiration]]:
        """All averagers currently declared under the key
        (reference key_manager.py:70-92)."""
        result = await self.dht.node.get(group_key, latest=True)
        if result is None or not isinstance(result.value, dict):
            return []
        averagers = []
        for subkey, entry in result.value.items():
            try:
                if only_active and entry.value is not True:
                    continue
                averagers.append((PeerID.from_base58(subkey), entry.expiration_time))
            except Exception as e:
                logger.debug(f"malformed averager record {subkey!r}: {e!r}")
        return averagers

    async def update_key_on_group_assembled(self, group_info: GroupInfo) -> None:
        """Deterministic rebucketing: every member derives a distinct pseudo-random
        bucket from the shared group id, so groups mix across rounds."""
        nbits = len(self.group_bits)
        if nbits == 0:
            return
        rng = random.Random(group_info.group_id)
        num_buckets = 2**nbits
        assignments = [rng.randrange(num_buckets) for _ in range(group_info.group_size)]
        index = group_info.peer_ids.index(self.peer_id)
        self.group_bits = format(assignments[index], f"0{nbits}b")
        logger.debug(f"rebucketed to group bits {self.group_bits}")

    async def update_key_on_not_enough_peers(self) -> None:
        """Failed to assemble: drop one bit so the bucket is larger next time
        (reference behavior on starvation)."""
        if self.group_bits:
            self.group_bits = self.group_bits[:-1]
            logger.debug(f"too few peers; widened bucket to {self.group_bits!r}")
