"""Per-link wire-codec negotiation for the butterfly all-reduce (ISSUE 11).

The averaging wire supports a small ladder of **tiers** — ``none`` (raw fp32
or native dtype), ``float16``, ``uniform8``, ``blockwise8`` — ordered by how
few bytes they put on the wire. Which tier a *link* (an ordered pair of
groupmates) uses is negotiated with ZERO extra round trips, mirroring the
``peer|codec`` DHT records of the serving path (ISSUE 10): every peer's
matchmaking gather blob carries a :func:`make_advert` — the tiers it supports,
its default tier, and any per-peer **demotions** its straggler policy has
decided — and both endpoints of a link run the same pure function
(:func:`negotiate_link`) over the two adverts, so they agree without talking.

The adaptive part is :class:`LinkCodecPolicy`: it reads the
:class:`~hivemind_tpu.telemetry.ledger.RoundLedger`'s per-peer straggler
scores (which name each round's slowest exchange partner and its excess
seconds over the round median), demotes chronically slow links to the 8-bit
tier, and promotes them back after a sustained clean streak. Decisions are
exposed three ways: a ``hivemind_averaging_link_codec`` gauge per remote, an
``averaging.link_codec`` span event in the flight recorder, and a
demote/promote event ring on the ledger (shown in ``hivemind-top``).

Negotiation rule (symmetric + deterministic): each side's *proposal* for a
link is its demotion for that remote if any, else its default tier; the link
runs at the most-compressed proposal, clamped to the tiers BOTH sides support.
Peers whose gather blob carries no advert (a codec outside the ladder, or a
malformed/absent slot) negotiate nothing — the link falls back to the
averager's configured codec, byte-identical to pre-negotiation behavior.

Version-compat note: this tolerance is one-directional. An UPGRADED peer
decodes legacy 3-slot gather blobs fine, but a pre-ISSUE-11 peer's strict
3-tuple unpack cannot read the extended blob — mixed-version swarms must
upgrade together (the usual rule for this codebase; gather-blob consumers are
positional-and-tolerant from here on so the NEXT extension is painless).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Mapping, Optional, Sequence, Tuple

from hivemind_tpu.compression import CompressionBase, CompressionType, get_codec
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import finish_span as _finish_span, start_span as _start_span
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# least → most compressed; rank = index. The order IS the negotiation lattice.
WIRE_TIERS: Tuple[str, ...] = ("none", "float16", "uniform8", "blockwise8")

_TIER_TYPES = {
    "none": CompressionType.NONE,
    "float16": CompressionType.FLOAT16,
    "uniform8": CompressionType.UNIFORM_8BIT,
    "blockwise8": CompressionType.BLOCKWISE_8BIT,
}
_TYPE_TIERS = {value: name for name, value in _TIER_TYPES.items()}

# tiers whose codecs are lossy enough to need error-feedback residuals
# (float16 is excluded on purpose: its wire behavior is pinned bit-identical
# by the partition-equivalence suite and needs no compensation in practice)
EF_TIERS = frozenset(("uniform8", "blockwise8"))

_LINK_CODEC = _TELEMETRY.gauge(
    "hivemind_averaging_link_codec",
    "negotiated wire tier for the averaging link to `remote` "
    "(0=none, 1=float16, 2=uniform8, 3=blockwise8)",
    ("remote",),
)
_LINK_CODEC_EVENTS = _TELEMETRY.counter(
    "hivemind_averaging_link_codec_events_total",
    "adaptive link-codec decisions",
    ("action",),
)

# remote peer ids are swarm-supplied: the gauge keeps only the most recently
# seen remotes, evicting stale series from the registry (a churning swarm must
# not grow the metric — and with it every DHT snapshot — without bound)
_LINK_GAUGE_CAP = 64
_link_gauge_lru: "OrderedDict[str, None]" = OrderedDict()
_link_gauge_lock = threading.Lock()


def _set_link_gauge(remote: str, rank: int) -> None:
    with _link_gauge_lock:
        _LINK_CODEC.set(rank, remote=remote)
        _link_gauge_lru[remote] = None
        _link_gauge_lru.move_to_end(remote)
        while len(_link_gauge_lru) > _LINK_GAUGE_CAP:
            stale, _ = _link_gauge_lru.popitem(last=False)
            _LINK_CODEC.remove(remote=stale)


def tier_rank(tier: str) -> int:
    return WIRE_TIERS.index(tier)


def tier_of_codec(codec: CompressionBase) -> Optional[str]:
    """The wire tier a codec instance belongs to, or None (not on the ladder —
    e.g. MEANSTD_16BIT/QUANTILE_8BIT, which disable negotiation)."""
    return _TYPE_TIERS.get(codec.compression_type)


@dataclass(frozen=True)
class WireLink:
    """Resolved per-link wire behavior, handed to the all-reduce runner."""

    tier: str
    codec: CompressionBase = field(compare=False)
    error_feedback: bool

    @classmethod
    def for_tier(cls, tier: str) -> "WireLink":
        return cls(tier=tier, codec=get_codec(_TIER_TYPES[tier]), error_feedback=tier in EF_TIERS)


def make_advert(
    supported: Sequence[str], default_tier: str, demotions: Optional[Mapping[str, str]] = None
) -> Dict[str, Any]:
    """The msgpack-able advert that rides the matchmaking gather blob."""
    return {
        "t": [tier for tier in supported if tier in _TIER_TYPES],
        "d": default_tier,
        "m": dict(demotions or {}),
    }


def parse_advert(obj: Any) -> Optional[Dict[str, Any]]:
    """Normalize a remote-supplied advert; None for anything malformed (the
    link then falls back to the configured codec — never an exception: gather
    blobs are remote-controlled)."""
    if not isinstance(obj, dict):
        return None
    tiers = obj.get("t")
    default = obj.get("d")
    demotions = obj.get("m", {})
    if not isinstance(tiers, (list, tuple)) or not isinstance(default, str):
        return None
    supported = tuple(t for t in tiers if isinstance(t, str) and t in _TIER_TYPES)
    if default not in supported:
        return None
    clean_demotions = {}
    if isinstance(demotions, dict):
        for peer, tier in demotions.items():
            if isinstance(peer, str) and isinstance(tier, str) and tier in _TIER_TYPES:
                clean_demotions[peer] = tier
    return {"t": supported, "d": default, "m": clean_demotions}


def negotiate_link(
    local_advert: Optional[Dict[str, Any]],
    remote_advert: Optional[Dict[str, Any]],
    local_peer_id: str,
    remote_peer_id: str,
) -> Optional[str]:
    """The tier for the link between local and remote, or None when either end
    did not advertise (caller falls back to its configured codec). Symmetric:
    both endpoints compute the identical answer from the same two adverts."""
    if not local_advert or not remote_advert:
        return None
    common = set(local_advert["t"]) & set(remote_advert["t"])
    if not common:
        return None
    local_proposal = local_advert["m"].get(remote_peer_id, local_advert["d"])
    remote_proposal = remote_advert["m"].get(local_peer_id, remote_advert["d"])
    if local_proposal not in _TIER_TYPES:
        local_proposal = local_advert["d"]
    if remote_proposal not in _TIER_TYPES:
        remote_proposal = remote_advert["d"]
    target = max(tier_rank(local_proposal), tier_rank(remote_proposal))
    feasible = sorted(tier_rank(tier) for tier in common)
    at_or_below = [rank for rank in feasible if rank <= target]
    chosen = at_or_below[-1] if at_or_below else feasible[0]
    return WIRE_TIERS[chosen]


class LinkCodecPolicy:
    """Demote chronically slow links to an 8-bit tier; promote them back after
    a sustained clean streak. Driven by the RoundLedger's straggler scores —
    which are CUMULATIVE, so the policy differences them per :meth:`refresh`
    (one refresh per averaging step) into a bounded rolling window.

    Demotion needs evidence, not noise: *some* peer is slowest every round, so
    a link is demoted only when, within the window, it was the slowest exchange
    in at least ``demote_rounds`` rounds AND its mean excess over the round
    median exceeds ``min_excess_s``. Promotion needs ``promote_after``
    consecutive refreshes in which the peer was never slowest-with-excess.
    State is bounded (``max_peers``, LRU on last sighting) so a churning swarm
    cannot grow it — and :meth:`forget` drops a departed peer outright."""

    def __init__(
        self,
        ledger=None,
        *,
        demote_tier: str = "uniform8",
        default_tier: Optional[str] = None,
        demote_rounds: int = 3,
        min_excess_s: float = 0.15,
        promote_after: int = 8,
        window: int = 16,
        max_peers: int = 256,
    ):
        if ledger is None:
            from hivemind_tpu.telemetry.ledger import LEDGER as ledger  # noqa: PLW0127

        assert demote_tier in _TIER_TYPES
        self._ledger = ledger
        self.demote_tier = demote_tier
        # the tier a promoted link returns to; when known, promote/forget reset
        # the hivemind_averaging_link_codec gauge so it never reads a stale
        # demotion (the owning averager sets this to its configured tier)
        self.default_tier = default_tier if default_tier in _TIER_TYPES else None
        self.demote_rounds = demote_rounds
        self.min_excess_s = min_excess_s
        self.promote_after = promote_after
        self._window_size = window
        self._max_peers = max_peers
        self._last_totals: Dict[str, Tuple[float, float]] = {}
        self._windows: Dict[str, deque] = {}
        self._clean_streak: Dict[str, int] = {}
        self._demoted: Dict[str, str] = {}
        self._last_seen: Dict[str, float] = {}

    def demotions(self) -> Dict[str, str]:
        return dict(self._demoted)

    def refresh(self, exclude: Iterable[str] = ()) -> Dict[str, str]:
        """Fold the latest straggler scores into the windows, apply the
        demote/promote rules, and return the current demotion map (the adverts'
        ``m`` field). Call once per averaging step — cheap: a few dict ops per
        known peer."""
        excluded = set(exclude)
        now = time.monotonic()
        try:
            scores = self._ledger.straggler_scores()
        except Exception:
            return self.demotions()
        for peer, score in scores.items():
            if peer in excluded:
                continue
            totals = (float(score.get("rounds_slowest", 0)), float(score.get("excess_s", 0.0)))
            previous = self._last_totals.get(peer, (0.0, 0.0))
            self._last_totals[peer] = totals
            self._last_seen[peer] = now
            # retro-attribution can MOVE credit between peers (late exchange
            # spans), so deltas may go negative — clamp, it is not new evidence
            delta_slow = max(0.0, totals[0] - previous[0])
            delta_excess = max(0.0, totals[1] - previous[1])
            window = self._windows.setdefault(peer, deque(maxlen=self._window_size))
            window.append((delta_slow, delta_excess))
            if peer in self._demoted:
                if delta_slow > 0 and delta_excess > 0:
                    self._clean_streak[peer] = 0
                else:
                    self._clean_streak[peer] = self._clean_streak.get(peer, 0) + 1
                    if self._clean_streak[peer] >= self.promote_after:
                        self._promote(peer)
            else:
                window_slow = sum(slow for slow, _excess in window)
                window_excess = sum(excess for _slow, excess in window)
                if (
                    window_slow >= self.demote_rounds
                    and window_excess / max(window_slow, 1.0) >= self.min_excess_s
                ):
                    self._demote(peer)
        self._prune()
        return self.demotions()

    def forget(self, peer: str) -> None:
        """A peer departed: drop every trace of it (no-leak guarantee)."""
        for table in (self._last_totals, self._windows, self._clean_streak, self._last_seen):
            table.pop(peer, None)
        if self._demoted.pop(peer, None) is not None:
            _LINK_CODEC_EVENTS.inc(action="forget")
            if self.default_tier is not None:
                _set_link_gauge(peer, tier_rank(self.default_tier))

    def _demote(self, peer: str) -> None:
        self._demoted[peer] = self.demote_tier
        self._clean_streak[peer] = 0
        self._emit(peer, "demote", self.demote_tier)

    def _promote(self, peer: str) -> None:
        self._demoted.pop(peer, None)
        self._clean_streak.pop(peer, None)
        self._windows.pop(peer, None)  # fresh evidence required to re-demote
        self._emit(peer, "promote", None)

    def _emit(self, peer: str, action: str, tier: Optional[str]) -> None:
        logger.info(f"link codec {action}: {peer} -> {tier or 'default'}")
        _LINK_CODEC_EVENTS.inc(action=action)
        effective = tier if tier is not None else self.default_tier
        if effective is not None:
            _set_link_gauge(peer, tier_rank(effective))
        # a detached span so the decision is visible in the flight recorder /
        # GET /trace even when no round is active on this thread
        span = _start_span("averaging.link_codec", remote=peer, action=action, tier=tier or "default")
        _finish_span(span)
        try:
            self._ledger.record_codec_event(peer=peer, action=action, tier=tier)
        except AttributeError:
            pass  # private ledgers in tests may predate the event ring

    def _prune(self) -> None:
        if len(self._last_seen) <= self._max_peers:
            return
        evictable = sorted(
            (peer for peer in self._last_seen if peer not in self._demoted),
            key=lambda peer: self._last_seen[peer],
        )
        for peer in evictable[: len(self._last_seen) - self._max_peers]:
            self.forget(peer)


def publish_link_gauges(links: Mapping[str, str]) -> None:
    """Record the negotiated tier per remote at group-assembly time."""
    for remote, tier in links.items():
        if tier in _TIER_TYPES:
            _set_link_gauge(remote, tier_rank(tier))
