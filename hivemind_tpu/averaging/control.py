"""StepControl: the caller's handle for one averaging step
(capability parity: reference hivemind/averaging/control.py).

The reference backs this with an 18-byte shared-memory buffer piped between processes;
in the single-process runtime it is a plain object whose mutable fields are read from
both the user thread and the event-loop thread (GIL-atomic scalar reads/writes), with
concurrent futures for the cross-thread completion path."""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from enum import Enum
from typing import Any, Optional

from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time


class AveragingStage(Enum):
    IDLE = 0
    LOOKING_FOR_GROUP = 1
    AWAITING_TRIGGER = 2
    RUNNING_ALLREDUCE = 3
    FINISHED = 4


class StepControl:
    """Two-phase step handle: schedule (matchmaking may begin early) → trigger
    (caller permits the all-reduce to actually run once gradients are ready)."""

    def __init__(
        self,
        scheduled_time: DHTExpiration,
        deadline: Optional[float],
        allow_retries: bool,
        weight: float,
        data_for_gather: bytes = b"",
    ):
        self._scheduled_time = scheduled_time
        self.deadline = deadline
        self.allow_retries = allow_retries
        self._weight = weight
        self.data_for_gather = data_for_gather
        self.stage = AveragingStage.IDLE
        self.began_allreduce = False
        self._trigger_event = threading.Event()
        self._trigger_waiters: list = []  # (loop, asyncio.Event) pairs
        self._lock = threading.Lock()
        self.future: concurrent.futures.Future = concurrent.futures.Future()
        self._cancelled = False

    # ---------------------------------------------------------------- schedule/weight

    @property
    def scheduled_time(self) -> DHTExpiration:
        return self._scheduled_time

    @scheduled_time.setter
    def scheduled_time(self, value: DHTExpiration) -> None:
        if self.began_allreduce:
            raise RuntimeError("cannot reschedule: all-reduce already started")
        self._scheduled_time = value

    def reset_for_retry(self, new_scheduled_time: DHTExpiration) -> None:
        """A failed attempt is being retried: rearm scheduling state (the property
        setters deliberately refuse changes once began_allreduce is set)."""
        self.began_allreduce = False
        self._scheduled_time = new_scheduled_time

    @property
    def weight(self) -> float:
        return self._weight

    @weight.setter
    def weight(self, value: float) -> None:
        assert value >= 0
        if self.began_allreduce:
            raise RuntimeError("cannot change weight: all-reduce already started")
        self._weight = value

    # ---------------------------------------------------------------- trigger

    def allow_allreduce(self) -> None:
        """Phase-two commit: permit the scheduled step to run its all-reduce."""
        self.triggered or self._fire_trigger()

    def _fire_trigger(self) -> None:
        with self._lock:
            self._trigger_event.set()
            for loop, event in self._trigger_waiters:
                loop.call_soon_threadsafe(event.set)
            self._trigger_waiters.clear()

    @property
    def triggered(self) -> bool:
        return self._trigger_event.is_set()

    async def wait_for_trigger(self) -> None:
        if self._trigger_event.is_set():
            return
        loop = asyncio.get_event_loop()
        event = asyncio.Event()
        with self._lock:
            if self._trigger_event.is_set():
                return
            self._trigger_waiters.append((loop, event))
        await event.wait()

    # ---------------------------------------------------------------- completion

    def cancel(self) -> bool:
        self._cancelled = True
        self._fire_trigger()  # wake anything waiting so it can observe cancellation
        if not self.future.done():
            return self.future.cancel()
        return False

    @property
    def cancelled(self) -> bool:
        return self._cancelled or self.future.cancelled()

    def done(self) -> bool:
        return self.future.done()

    def result(self, timeout: Optional[float] = None) -> Any:
        return self.future.result(timeout)

    def exception(self, timeout: Optional[float] = None):
        return self.future.exception(timeout)

    def set_result(self, result: Any) -> None:
        if not self.future.done():
            self.future.set_result(result)
        self.stage = AveragingStage.FINISHED

    def set_exception(self, exc: BaseException) -> None:
        if not self.future.done():
            self.future.set_exception(exc)
        self.stage = AveragingStage.FINISHED

    def get_timeout(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - get_dht_time())

    def __repr__(self):
        return (
            f"StepControl(stage={self.stage.name}, scheduled_in={self._scheduled_time - get_dht_time():.2f}s, "
            f"weight={self._weight}, triggered={self.triggered}, done={self.done()})"
        )
