"""Error-feedback residual state for the quantized averaging wire (ISSUE 11).

When a link's wire codec is lossy (8-bit tiers), each quantization discards
``x − dequantize(quantize(x))``. Left uncompensated, those errors random-walk
into the model across rounds. Error feedback fixes this by carrying the
discarded remainder forward: round N's quantization error is added back to the
value *before* quantizing round N+1, so the time-average of what crosses the
wire is unbiased (the classic EF-SGD argument).

A :class:`ResidualStore` lives on the AVERAGER (not the per-round runner) and
holds one fp32 plane per wire leg, indexed by **global offset in the logical
concatenated tensor stream**:

- ``"send"`` — the reduce-scatter leg: the quantization error of each part this
  peer ships to its reducers. Every element is shipped to exactly one reducer
  per round, so one full-size plane covers the leg no matter how the group (and
  therefore the partition) is composed.
- ``"reduce"`` — the all-gather leg: this peer, as a reducer, quantizes each
  averaged part ONCE (the same bytes go to every lossy-tier sender — see
  ``absolute_part`` in averaging.proto) and keeps the quantization error of the
  average, again by global offset.

Because planes are offset-indexed, residual state **survives group-composition
changes**: a different partition next round still lines up element-for-element.
Planes are allocated lazily on first lossy use (a lossless swarm pays nothing)
and ``ensure(total_elements)`` resets them when the tensor schema changes — the
"reset on group change" rule: residuals from a different schema are garbage.
Memory is O(total_elements) per plane and **independent of the number of
peers** (no per-peer buffers to leak when a peer departs).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from hivemind_tpu.compression import CompressionBase
from hivemind_tpu.proto import runtime_pb2

PLANES = ("send", "reduce")


class ResidualStore:
    """Per-averager error-feedback residual planes (see module docstring).

    Thread-safe: parts are compressed concurrently in the shared executor, but
    each part touches a disjoint global span, so only plane *allocation* needs
    the lock."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._planes: Dict[str, np.ndarray] = {}
        self._total_elements: Optional[int] = None

    def ensure(self, total_elements: int) -> None:
        """Pin the stream size; a CHANGED size (new tensor schema / partition
        universe) discards all residual state — stale offsets would compensate
        the wrong elements."""
        with self._lock:
            if self._total_elements != total_elements:
                self._planes.clear()
                self._total_elements = int(total_elements)

    def view(self, plane: str, start: int, stop: int) -> np.ndarray:
        """A writable fp32 view of ``plane`` over global span [start, stop),
        allocating the plane (zeros) on first use."""
        assert plane in PLANES, f"unknown residual plane {plane!r}"
        with self._lock:
            buffer = self._planes.get(plane)
            if buffer is None:
                assert self._total_elements is not None, "call ensure() before view()"
                buffer = np.zeros(self._total_elements, np.float32)
                self._planes[plane] = buffer
        return buffer[start:stop]

    def reset(self) -> None:
        """Drop all residual state (e.g. after adopting state from peers: the
        new tensors owe nothing to our old quantization errors)."""
        with self._lock:
            self._planes.clear()

    def footprint_bytes(self) -> int:
        with self._lock:
            return sum(buffer.nbytes for buffer in self._planes.values())


def compress_with_feedback(
    part32: np.ndarray, codec: CompressionBase, residual: np.ndarray
) -> runtime_pb2.Tensor:
    """Quantize ``part32 + residual`` and fold the new quantization error back
    into ``residual`` (both legs use this; ``part32`` is never mutated).

    The residual buffer doubles as the compensated staging area, so the only
    allocations are the codec's own outputs:

        residual += part            # residual now holds the compensated value
        wire      = quantize(residual)
        residual -= dequantize(wire)  # what the wire discarded this round
    """
    assert residual.shape == part32.reshape(-1).shape, (residual.shape, part32.shape)
    flat32 = part32.reshape(-1).astype(np.float32, copy=False)
    np.add(residual, flat32, out=residual)
    try:
        serialized = codec.compress(residual)  # must not mutate its input (no allow_inplace)
        decoded = codec.extract(serialized).reshape(-1).astype(np.float32, copy=False)
    except BaseException:
        # the residual doubles as staging: a codec failure mid-flight must not
        # leave the whole part folded into EF state as phantom "error" (the
        # next round would ship a ~2x-magnitude span) — roll the staging back
        np.subtract(residual, flat32, out=residual)
        raise
    np.subtract(residual, decoded, out=residual)
    return serialized
