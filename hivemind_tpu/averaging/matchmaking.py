"""Decentralized group formation (capability parity: reference
hivemind/averaging/matchmaking.py).

Every averager looking for a group declares itself in the DHT with an expiration (its
step deadline). Peers always request to join the declared averager with the EARLIEST
expiration below their own — so the join graph is a DAG and the earliest-expiring peer
becomes the leader. A leader assembles its group when full or when its own deadline
arrives; an averager that itself got accepted elsewhere disbands its followers with a
redirect to its new leader (suggested_leader). The documented deadlock (two peers
waiting on each other through a chain) is broken by ``request_timeout`` on the first
response (reference matchmaking.py:29-35)."""

from __future__ import annotations

import asyncio
import contextlib
import os
import random
import time
from typing import AsyncIterator, Dict, Optional, Tuple

from hivemind_tpu.averaging.group_info import GroupInfo
from hivemind_tpu.averaging.key_manager import GroupKeyManager
from hivemind_tpu.p2p import P2P, P2PContext, P2PHandlerError, PeerID
from hivemind_tpu.proto import averaging_pb2
from hivemind_tpu.resilience import RetryPolicy
from hivemind_tpu.utils.asyncio_utils import anext_safe, cancel_and_wait, spawn
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time

logger = get_logger(__name__)

# layer-3 telemetry (docs/observability.md): how long group formation takes and
# how often it fails — the first place to look when a training round stalls
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import trace as _tracing_span

_MATCHMAKING_WAIT = _TELEMETRY.histogram(
    "hivemind_averaging_matchmaking_seconds",
    "declare-to-outcome wall time of one look_for_group",
    ("outcome",),
)
_MATCHMAKING_ROUNDS = _TELEMETRY.counter(
    "hivemind_averaging_matchmaking_rounds_total", "look_for_group attempts", ("outcome",)
)
_GROUP_SIZE = _TELEMETRY.gauge(
    "hivemind_averaging_group_size", "size of the most recently assembled group"
)


class MatchmakingException(Exception):
    pass


class Matchmaking:
    """One per averager; drives both the follower side (look_for_group →
    request-join) and the leader side (rpc_join_group → assemble)."""

    def __init__(
        self,
        p2p: P2P,
        key_manager: GroupKeyManager,
        get_stub,  # callable(peer_id) -> averager stub (for rpc_join_group)
        *,
        schema_hash: str,
        target_group_size: Optional[int],
        min_group_size: int = 2,
        min_matchmaking_time: float = 5.0,
        request_timeout: float = 3.0,
        client_mode: bool = False,
    ):
        self.p2p = p2p
        self.peer_id = p2p.peer_id
        self.key_manager = key_manager
        self.get_stub = get_stub
        self.schema_hash = schema_hash
        self.target_group_size = target_group_size
        self.min_group_size = min_group_size
        self.min_matchmaking_time = min_matchmaking_time
        self.request_timeout = request_timeout
        self.client_mode = client_mode

        # pacing between leader-candidate polls: request_timeout/2 plus a small
        # full-jitter slice through the shared policy (resilience/policy.py) —
        # the historical U(rt/2, rt/2 + 0.2) desynchronization window, declared
        self._poll_floor = request_timeout / 2
        self._poll_policy = RetryPolicy(
            max_attempts=None,
            base_delay=0.2,
            backoff=1.0,
            jitter="full",
            name="matchmaking_poll",
        )
        self.lock_looking_for_group = asyncio.Lock()
        self.looking_for_group = False
        self.declared_expiration_time: DHTExpiration = -float("inf")
        self.current_leader: Optional[PeerID] = None
        # follower peer_id -> (JoinRequest, outbox queue for BEGIN/DISBAND messages)
        self.current_followers: Dict[PeerID, Tuple[averaging_pb2.JoinRequest, asyncio.Queue]] = {}
        self.data_for_gather: bytes = b""
        self.assembled_group: Optional[GroupInfo] = None
        # wakes the leader's search loop the moment its group assembles: without
        # this, a leader whose group filled early slept out the remainder of its
        # declared window (up to the full min_matchmaking_time), gating every
        # follower's round start on a timer instead of an event (ISSUE 6: the
        # measured ~0.7 s/round idle gap on the averaging benchmark)
        self._group_assembled = asyncio.Event()
        self._tried_leaders: set = set()
        self._join_in_progress = False  # excludes full-group assembly while we court a leader
        # adaptive lead time (VERDICT r3 #5): a fixed min_matchmaking_time collapses
        # under contention (32 peers / 1 s window / one core: declare+fetch storms
        # outlast the window and success drops to 0). Track the declare→group-fill
        # latency (EMA over successful rounds) and back off multiplicatively on
        # window-expired failures, so bare DecentralizedAverager users self-heal
        # without an operator re-sizing the lead time.
        self.fill_latency_ema: Optional[float] = None
        self._lead_backoff = 1.0
        # set once another declared averager (or an inbound join request) has
        # EVER been seen. Backoff applies to EVERY window expiry — under a
        # 32-peer declare storm that unconditional stretch is what lets the
        # swarm converge (gating it on per-window observations regressed the
        # storm case to success 0.48, RESULTS.md) — but FIRST CONTACT resets it:
        # a peer that started before its swarm may have ratcheted to the cap
        # while alone (harmless: nobody to match with), and must form its first
        # real group at the base lead time, not 30 s later (advisor r4)
        self._others_observed = False

    def suggested_lead_time(self) -> float:
        """The effective matchmaking window to use when the caller did not pin a
        scheduled_time: at least ``min_matchmaking_time``, stretched by observed
        fill latency and by failure backoff, capped so a dead swarm cannot push
        retries out indefinitely."""
        observed = 1.25 * self.fill_latency_ema if self.fill_latency_ema is not None else 0.0
        base = max(self.min_matchmaking_time, observed)
        cap = max(8.0 * self.min_matchmaking_time, 30.0)
        return min(base * self._lead_backoff, cap)

    def _record_round_outcome(self, latency: Optional[float]) -> None:
        """latency = declare→assembled seconds on success, None on a window-expired
        failure."""
        if latency is not None:
            self.fill_latency_ema = (
                latency if self.fill_latency_ema is None
                else 0.7 * self.fill_latency_ema + 0.3 * latency
            )
            self._lead_backoff = max(1.0, self._lead_backoff / 2.0)
        else:
            self._lead_backoff = min(self._lead_backoff * 2.0, 16.0)

    def _note_others_observed(self) -> None:
        """First contact with the swarm: discard any solo-era backoff so the
        first REAL group forms at the base lead time (see __init__ notes)."""
        if not self._others_observed:
            self._others_observed = True
            self._lead_backoff = 1.0

    @property
    def is_looking_for_group(self) -> bool:
        return self.looking_for_group

    # ------------------------------------------------------------------ follower side

    async def look_for_group(
        self, *, data_for_gather: bytes, scheduled_time: Optional[DHTExpiration] = None, timeout: Optional[float] = None
    ) -> Optional[GroupInfo]:
        """Search until a group assembles or the deadline passes. Returns None if no
        group could be formed this attempt."""
        if self.lock_looking_for_group.locked():
            logger.debug("another look_for_group is in progress; waiting")
        async with self.lock_looking_for_group:
            self.looking_for_group = True
            self.data_for_gather = data_for_gather
            self.assembled_group = None
            self._group_assembled.clear()
            self._tried_leaders.clear()
            now = get_dht_time()
            self.declared_expiration_time = max(
                scheduled_time if scheduled_time is not None else now + self.min_matchmaking_time,
                now + 1e-2,
            )
            if timeout is not None:
                self.declared_expiration_time = min(self.declared_expiration_time, now + timeout)
            declared_key = self.key_manager.current_key  # rebucketing may change it mid-round
            declare_task = None
            if not self.client_mode:
                # land our own declaration BEFORE searching: peers must be able to
                # find us for the whole window, or near-simultaneous searchers can
                # repeatedly miss each other
                with contextlib.suppress(Exception):
                    await self.key_manager.declare_averager(
                        declared_key, self.peer_id, self.declared_expiration_time
                    )
                declare_task = spawn(self._declare_periodically(declared_key), name="matchmaking.declare_periodically")
            search_started = get_dht_time()
            wait_started = time.perf_counter()  # the metric must survive clock steps
            group = None
            outcome = "error"  # overwritten on a normal return; errors stay visible
            # the with block (not manual enter/exit) so an unexpected exception
            # leaves its `error` event on the span; cleanup runs inside it — the
            # retract/disband time is part of the round's wall time
            with _tracing_span("averaging.matchmaking", peer=str(self.peer_id)) as match_span:
                try:
                    group = await self._search_until_deadline()
                    outcome = "assembled" if group is not None else "expired"
                    self._record_round_outcome(
                        get_dht_time() - search_started if group is not None else None
                    )
                    return group
                except asyncio.CancelledError:
                    outcome = "cancelled"  # control.cancel / shutdown: not an error
                    raise
                finally:
                    if match_span is not None:
                        match_span.set("outcome", outcome)
                        if group is not None:
                            match_span.set("group_size", len(group.peer_ids))
                    _MATCHMAKING_WAIT.observe(time.perf_counter() - wait_started, outcome=outcome)
                    _MATCHMAKING_ROUNDS.inc(outcome=outcome)
                    if group is not None:
                        _GROUP_SIZE.set(len(group.peer_ids))
                    self.looking_for_group = False
                    self.current_leader = None
                    if declare_task is not None:
                        await cancel_and_wait(declare_task)
                        # retract under the key we DECLARED under, not the new
                        # bucket — in the background: a successful round must not
                        # delay its all-reduce behind a DHT store (the storage is
                        # newest-expiration-wins, so a late retract can never
                        # clobber the next round's declaration; until it lands,
                        # join requests get REJECT_NOT_LOOKING_FOR_GROUP)
                        spawn(self._retract_declaration(declared_key), name="matchmaking.retract_declaration")
                    if self.current_followers and self.assembled_group is None:
                        self._disband_followers(suggested_leader=None)

    async def _retract_declaration(self, key: str) -> None:
        with contextlib.suppress(Exception):
            await self.key_manager.declare_averager(
                key, self.peer_id, get_dht_time(), looking_for_group=False
            )

    async def _declare_periodically(self, key: str) -> None:
        # sleep FIRST: look_for_group already stored the initial declaration
        while True:
            remaining = self.declared_expiration_time - get_dht_time()
            if remaining <= 0:
                return
            await asyncio.sleep(max(remaining / 2, 0.5))
            with contextlib.suppress(Exception):
                await self.key_manager.declare_averager(key, self.peer_id, self.declared_expiration_time)

    async def _search_until_deadline(self) -> Optional[GroupInfo]:
        while get_dht_time() < self.declared_expiration_time:
            if self.assembled_group is not None:
                return self.assembled_group  # a full group assembled around us
            leader = await self._find_next_leader()
            if self.assembled_group is not None:
                return self.assembled_group
            if leader is not None:
                group = await self._request_join_group(leader)
                if group is not None:
                    return group
                continue
            remaining = self.declared_expiration_time - get_dht_time()
            if remaining > 0:
                # pacing sleep, interrupted the instant a full group assembles
                # around us — the data path must start at fill time, not when the
                # declared window runs out
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(
                        self._group_assembled.wait(),
                        timeout=min(remaining, self._poll_floor + self._poll_policy.delay(0)),
                    )
        # the group may have assembled (full-group path) during the final sleep
        if self.assembled_group is not None:
            return self.assembled_group
        # our deadline arrived: we lead whoever joined us (if enough), else give up
        if len(self.current_followers) + 1 >= self.min_group_size:
            return self._leader_assemble_group()
        await self.key_manager.update_key_on_not_enough_peers()
        return None

    async def _find_next_leader(self) -> Optional[PeerID]:
        """The declared averager with the earliest expiration strictly before ours
        (ties broken by peer id) that we haven't already tried this round."""
        try:
            candidates = await self.key_manager.get_averagers(self.key_manager.current_key)
        except Exception as e:
            logger.debug(f"could not fetch potential leaders: {e!r}")
            return None
        now = get_dht_time()
        best: Optional[Tuple[DHTExpiration, PeerID]] = None
        for peer_id, expiration in candidates:
            if peer_id == self.peer_id:
                continue
            self._note_others_observed()
            if peer_id in self._tried_leaders:
                continue
            if expiration <= now or expiration >= self.declared_expiration_time:
                continue  # stale, or they should be joining us instead
            if best is None or (expiration, peer_id) < best:
                best = (expiration, peer_id)
        return best[1] if best is not None else None

    async def _request_join_group(self, leader: PeerID) -> Optional[GroupInfo]:
        """Stream rpc_join_group to a (chain of) leader(s); follows suggested_leader
        redirects (reference matchmaking.py:178-252)."""
        visited_chain: set = set()
        current: Optional[PeerID] = leader
        while current is not None and current not in visited_chain and get_dht_time() < self.declared_expiration_time:
            visited_chain.add(current)
            self._tried_leaders.add(current)  # lint: single-writer — one matchmaking cycle per averager
            group = None
            suggested = None
            try:
                group, suggested = await self._request_join_one(current)
            except (P2PHandlerError, ConnectionError, asyncio.TimeoutError, OSError) as e:
                logger.debug(f"join request to {current} failed: {e!r}")
            if group is not None:
                return group
            current = suggested
        return None

    async def _request_join_one(self, leader: PeerID):
        stream = None
        self._join_in_progress = True
        try:
            stub = self.get_stub(leader)
            request = averaging_pb2.JoinRequest(
                group_key=self.key_manager.current_key.encode(),
                expiration=self.declared_expiration_time,
                gather=self.data_for_gather,
                client_mode=self.client_mode,
                schema_hash=self.schema_hash,
            )
            stream = stub.rpc_join_group(request).__aiter__()
            first = await asyncio.wait_for(anext_safe(stream), timeout=self.request_timeout)
            if not isinstance(first, averaging_pb2.MessageFromLeader):
                return None, None
            if first.code == averaging_pb2.GROUP_DISBANDED:
                return None, PeerID(first.suggested_leader) if first.suggested_leader else None
            if first.code != averaging_pb2.ACCEPTED:
                logger.debug(f"{leader} rejected us: {averaging_pb2.MessageCode.Name(first.code)}")
                return None, None

            # accepted: we are now a follower — disband our own would-be group
            self.current_leader = leader
            if self.current_followers:
                self._disband_followers(suggested_leader=leader)
            # the leader must answer by (its expiration ≤ ours) + grace
            deadline = self.declared_expiration_time - get_dht_time() + self.request_timeout * 2
            second = await asyncio.wait_for(anext_safe(stream), timeout=max(deadline, self.request_timeout))
            if not isinstance(second, averaging_pb2.MessageFromLeader):
                return None, None
            if second.code == averaging_pb2.BEGIN_ALLREDUCE:
                group = GroupInfo(
                    group_id=second.group_id,
                    peer_ids=tuple(PeerID(pid) for pid in second.ordered_peer_ids),
                    gathered=tuple(second.gathered),
                )
                if self.peer_id not in group:
                    raise MatchmakingException(f"leader {leader} assembled a group without us")
                await self.key_manager.update_key_on_group_assembled(group)
                return group, None
            if second.code == averaging_pb2.GROUP_DISBANDED:
                return None, PeerID(second.suggested_leader) if second.suggested_leader else None
            return None, None
        finally:
            self._join_in_progress = False
            self.current_leader = None
            if stream is not None:
                with contextlib.suppress(Exception):
                    await stream.aclose()

    # ------------------------------------------------------------------ leader side

    async def rpc_join_group(
        self, request: averaging_pb2.JoinRequest, context: P2PContext
    ) -> AsyncIterator[averaging_pb2.MessageFromLeader]:
        """Serve a follower's join request: ACCEPTED now, BEGIN_ALLREDUCE /
        GROUP_DISBANDED later (reference matchmaking.py:262-332)."""
        reject = self._check_join_request(request, context)
        if reject is not None:
            yield reject
            return
        outbox: asyncio.Queue = asyncio.Queue()
        self._note_others_observed()
        self.current_followers[context.remote_id] = (request, outbox)  # lint: single-writer — each handler owns its follower key
        try:
            yield averaging_pb2.MessageFromLeader(code=averaging_pb2.ACCEPTED)
            if (
                self.target_group_size is not None
                and len(self.current_followers) + 1 >= self.target_group_size
                and self.current_leader is None
                and not self._join_in_progress  # split-brain guard: we may be mid-join
                and self.assembled_group is None
            ):
                self._leader_assemble_group()  # group is full: begin early
            timeout = self.declared_expiration_time - get_dht_time() + self.request_timeout * 2
            try:
                message = await asyncio.wait_for(outbox.get(), timeout=max(timeout, self.request_timeout))
            except asyncio.TimeoutError:
                message = averaging_pb2.MessageFromLeader(code=averaging_pb2.GROUP_DISBANDED)
            yield message
        finally:
            self.current_followers.pop(context.remote_id, None)

    def _check_join_request(
        self, request: averaging_pb2.JoinRequest, context: P2PContext
    ) -> Optional[averaging_pb2.MessageFromLeader]:
        """The nine rejection reasons (reference matchmaking.py:334-369)."""
        code = None
        suggested = b""
        now = get_dht_time()
        if not self.looking_for_group or self.assembled_group is not None:
            code = averaging_pb2.REJECT_NOT_LOOKING_FOR_GROUP
        elif self.client_mode:
            code = averaging_pb2.REJECT_REQUEST_TO_CLIENT
        elif request.group_key != self.key_manager.current_key.encode():
            code = averaging_pb2.REJECT_WRONG_GROUP_KEY
        elif request.schema_hash != self.schema_hash:
            code = averaging_pb2.PROTOCOL_VIOLATION
        elif self.current_leader is not None:
            code = averaging_pb2.GROUP_DISBANDED
            suggested = self.current_leader.to_bytes()
        elif request.expiration <= now:
            code = averaging_pb2.REJECT_EXPIRED
        elif request.expiration < self.declared_expiration_time:
            # their deadline is earlier: they should lead, not follow
            code = averaging_pb2.REJECT_WRONG_TIME
        elif context.remote_id == self.peer_id or context.remote_id in self.current_followers:
            code = averaging_pb2.REJECT_DUPLICATE_PEER_ID
        elif self.target_group_size is not None and len(self.current_followers) + 1 >= self.target_group_size:
            code = averaging_pb2.REJECT_GROUP_IS_FULL
        if code is None:
            return None
        return averaging_pb2.MessageFromLeader(code=code, suggested_leader=suggested)

    def _leader_assemble_group(self) -> GroupInfo:
        """Assemble self + current followers into a group and notify everyone
        (reference matchmaking.py:371-406)."""
        group_id = os.urandom(16)
        members = [self.peer_id, *self.current_followers.keys()]
        rng = random.Random(group_id)
        rng.shuffle(members)
        gathered = []
        for member in members:
            if member == self.peer_id:
                gathered.append(self.data_for_gather)
            else:
                gathered.append(self.current_followers[member][0].gather)
        group = GroupInfo(group_id, tuple(members), tuple(gathered))
        self.assembled_group = group
        self._group_assembled.set()  # wake the leader's search loop immediately
        message = averaging_pb2.MessageFromLeader(
            code=averaging_pb2.BEGIN_ALLREDUCE,
            group_id=group_id,
            ordered_peer_ids=[pid.to_bytes() for pid in members],
            gathered=list(gathered),
        )
        for _request, outbox in self.current_followers.values():
            outbox.put_nowait(message)
        spawn(self.key_manager.update_key_on_group_assembled(group), name="matchmaking.update_key_on_group_assembled")
        logger.debug(f"assembled group of {len(members)} (leader={self.peer_id})")
        return group

    def _disband_followers(self, suggested_leader: Optional[PeerID]) -> None:
        message = averaging_pb2.MessageFromLeader(
            code=averaging_pb2.GROUP_DISBANDED,
            suggested_leader=suggested_leader.to_bytes() if suggested_leader else b"",
        )
        for _request, outbox in self.current_followers.values():
            outbox.put_nowait(message)
