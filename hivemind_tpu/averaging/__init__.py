from hivemind_tpu.averaging.allreduce import AllReduceRunner, AveragingMode
from hivemind_tpu.averaging.averager import DecentralizedAverager
from hivemind_tpu.averaging.control import AveragingStage, StepControl
from hivemind_tpu.averaging.group_info import GroupInfo
from hivemind_tpu.averaging.ici import MeshAverager
from hivemind_tpu.averaging.key_manager import GroupKeyManager
from hivemind_tpu.averaging.load_balancing import load_balance_peers
from hivemind_tpu.averaging.matchmaking import Matchmaking, MatchmakingException
from hivemind_tpu.averaging.partition import (
    AllreduceException,
    TensorPartContainer,
    TensorPartReducer,
)
from hivemind_tpu.averaging.slice import SliceAverager
from hivemind_tpu.averaging.state_sync import (
    DigestMismatch,
    ManifestMismatch,
    StaleDonor,
    StateAssembly,
    StateDownloadResult,
    StateSyncError,
    StateUnavailable,
    build_state_manifest,
    download_state_verified,
    payload_digest,
)
