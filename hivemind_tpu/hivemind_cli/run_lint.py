"""Console entry point for ``hivemind-lint`` (ISSUE 16).

The suite itself lives in ``tools/lint`` — deliberately outside the installed
package, next to the allowlists and fixtures it reads, so linting the repo
never imports (or depends on importing) jax or the runtime. This wrapper just
puts ``tools/`` on ``sys.path`` and delegates; it exists so pyproject.toml can
register a ``hivemind-lint`` script.

Keep this module import-light: it must work in environments where the heavy
runtime deps are absent.
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[2]


def main() -> int:
    tools_dir = _REPO_ROOT / "tools"
    if not (tools_dir / "lint" / "engine.py").is_file():
        print(
            "hivemind-lint: tools/lint not found — the lint suite only runs from a "
            "source checkout (it reads allowlists and fixtures next to the code)",
            file=sys.stderr,
        )
        return 2
    if str(tools_dir) not in sys.path:
        sys.path.insert(0, str(tools_dir))
    from lint.cli import main as lint_main

    return lint_main()


if __name__ == "__main__":
    raise SystemExit(main())
