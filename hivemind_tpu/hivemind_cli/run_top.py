"""``hivemind-top``: a live terminal dashboard over the swarm's telemetry
(ISSUE 8 tentpole). One screen, refreshed in place, answering the operator's
standing questions without Prometheus or Perfetto:

- **per-peer vitals** — epoch, samples/s (frame-to-frame delta), event-loop
  lag and stall count, tripped breakers, snapshot age (peers whose snapshot
  age exceeds 3x the publish interval are flagged ``STALE``);
- **straggler table** — per-peer straggler scores merged across every peer's
  round ledger: which partner was slowest, how often, and how many excess
  seconds it cost the swarm;
- **recent alerts** — watchdog stalls (with the blocking frame), recovery
  emergencies, slow spans, degraded rounds;
- **serving board** (``--serving``, ISSUE 9) — per-expert QPS (frame-to-frame
  request delta), p95 latency and sheds merged across every peer's serving
  section, per-peer saturation (queue depth, runtime utilization, decode
  session occupancy), degraded client-side scorecards, and the slowest-request
  exemplars with their queue/assembly/compute/serialize decomposition;
- **device board** (``--device``, ISSUE 19) — per-peer jit compiles (count,
  storms, compile-seconds), HBM residency (live/peak bytes, buffer count),
  host<->device transfer totals, and the comm/compute overlap efficiency from
  the step timeline, plus the swarm's hottest compile sites. Recompile storms
  and suspected HBM leaks surface as alerts.

Everything renders from the DHT-published snapshots (`--key` must match the
swarm's ``TelemetryPublisher`` key), so the dashboard is a pure *reader*: it
joins the DHT, polls, and draws — it cannot perturb the run it watches.

Run it::

    hivemind-top --initial_peers /ip4/.../tcp/.../p2p/... --key myrun_telemetry

``--frames 1 --no-ansi`` renders one plain frame and exits (scripts, tests).
"""

from __future__ import annotations

import argparse
import time
from typing import Any, Dict, List, Optional, Tuple

from hivemind_tpu.telemetry.monitor import DEFAULT_PUBLISH_INTERVAL, STALE_AFTER_FACTOR
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CLEAR = "\x1b[2J\x1b[H"
_BOLD, _RED, _YELLOW, _DIM, _RESET = "\x1b[1m", "\x1b[31m", "\x1b[33m", "\x1b[2m", "\x1b[0m"


def _metric_total(snapshot: Dict[str, Any], name: str, field: str = "count") -> Optional[float]:
    """Sum of one metric family's series in a peer snapshot (gauges/counters sum
    their values; histograms sum ``field`` — 'count' or 'sum')."""
    family = (snapshot.get("metrics") or {}).get(name)
    if not isinstance(family, dict):
        return None
    total = 0.0
    for value in (family.get("series") or {}).values():
        if isinstance(value, dict):
            total += float(value.get(field, 0.0))
        else:
            total += float(value)
    return total


def _loop_lag_ms(snapshot: Dict[str, Any]) -> Optional[float]:
    count = _metric_total(snapshot, "hivemind_event_loop_lag_seconds", "count")
    total = _metric_total(snapshot, "hivemind_event_loop_lag_seconds", "sum")
    if not count:
        return None
    return (total or 0.0) / count * 1e3


def render_frame(
    records: Dict[str, Dict[str, Any]],
    *,
    publish_interval: float = DEFAULT_PUBLISH_INTERVAL,
    prev_samples: Optional[Dict[str, Tuple[float, float]]] = None,
    now: Optional[float] = None,
    ansi: bool = True,
) -> Tuple[str, Dict[str, Tuple[float, float]]]:
    """One dashboard frame from the swarm's snapshots. Pure: no DHT, no IO.

    ``prev_samples`` maps peer -> (samples_gauge, frame_time) from the previous
    frame; returns the updated map so the caller can thread it through for the
    samples/s column. Plain text with ``ansi=False`` (tests, piping)."""
    now = now if now is not None else time.time()
    bold = _BOLD if ansi else ""
    red = _RED if ansi else ""
    yellow = _YELLOW if ansi else ""
    dim = _DIM if ansi else ""
    reset = _RESET if ansi else ""
    samples_state: Dict[str, Tuple[float, float]] = {}
    stale_after = STALE_AFTER_FACTOR * publish_interval

    lines: List[str] = []
    lines.append(
        f"{bold}hivemind-top{reset} — {len(records)} peer(s), "
        f"{time.strftime('%H:%M:%S', time.localtime(now))} "
        f"{dim}(snapshot age > {stale_after:.0f}s = STALE){reset}"
    )
    header = (
        f"{'peer':<18} {'age':>5} {'epoch':>6} {'smp/s':>8} {'lag ms':>7} "
        f"{'stalls':>6} {'brk':>4} {'rounds':>6}  flags"
    )
    lines.append(bold + header + reset)

    alerts: List[str] = []
    straggler_board: Dict[str, Dict[str, float]] = {}
    link_tiers: Dict[str, str] = {}  # victim -> last-reported wire tier (ISSUE 11)

    def _render_peer(peer: str, snapshot: Dict[str, Any]) -> None:
        age = max(now - float(snapshot.get("time", now)), 0.0)
        epoch = _metric_total(snapshot, "hivemind_optim_local_epoch")
        samples = _metric_total(snapshot, "hivemind_optim_local_samples_accumulated")
        rate = None
        if samples is not None:
            samples_state[peer] = (samples, now)
            if prev_samples and peer in prev_samples:
                prev_value, prev_time = prev_samples[peer]
                if now > prev_time:
                    # accumulators reset each epoch: a negative delta is an
                    # epoch boundary, not negative throughput
                    rate = max(samples - prev_value, 0.0) / (now - prev_time)
        lag_ms = _loop_lag_ms(snapshot)
        watchdog = snapshot.get("watchdog") or {}
        stalls = int(watchdog.get("stalls", _metric_total(snapshot, "hivemind_event_loop_stalls_total") or 0))
        breakers = snapshot.get("breakers") or {}
        num_tripped = sum(int(b.get("num_tripped", 0)) for b in breakers.values() if isinstance(b, dict))
        ledger = snapshot.get("ledger") or {}
        rounds = len(ledger.get("records") or ())

        flags: List[str] = []
        if age > stale_after:
            flags.append(f"{red}STALE{reset}")
        if stalls:
            flags.append(f"{red}LOOP-STALLED{reset}")
        if num_tripped:
            flags.append(f"{yellow}BREAKERS{reset}")
        if snapshot.get("slow_spans"):
            flags.append(f"{yellow}SLOW-SPANS{reset}")
        if snapshot.get("truncated"):
            flags.append(f"{dim}truncated{reset}")

        lines.append(
            f"{peer[:18]:<18} {age:>4.0f}s "
            f"{(f'{epoch:.0f}' if epoch is not None else '-'):>6} "
            f"{(f'{rate:.1f}' if rate is not None else '-'):>8} "
            f"{(f'{lag_ms:.2f}' if lag_ms is not None else '-'):>7} "
            f"{stalls:>6} {num_tripped:>4} {rounds:>6}  {' '.join(flags)}"
        )

        for victim, score in (ledger.get("stragglers") or {}).items():
            board = straggler_board.setdefault(
                str(victim), {"rounds_slowest": 0, "excess_s": 0.0, "reporters": 0}
            )
            board["rounds_slowest"] += int(score.get("rounds_slowest", 0))
            board["excess_s"] = round(board["excess_s"] + float(score.get("excess_s", 0.0)), 3)
            board["reporters"] += 1

        # per-link negotiated wire tiers (records are oldest→newest: latest wins)
        # and demote/promote decisions from the adaptive codec policy
        for record in ledger.get("records") or ():
            codecs = record.get("link_codecs") if isinstance(record, dict) else None
            if isinstance(codecs, dict):
                for victim, tier in codecs.items():
                    link_tiers[str(victim)] = str(tier)
        for event in ledger.get("codec_events") or ():
            if isinstance(event, dict):
                alerts.append(
                    f"{yellow}codec{reset} {peer[:16]}: {event.get('action')} "
                    f"{str(event.get('peer'))[:16]} -> {event.get('tier') or 'default'}"
                )

        if stalls and watchdog.get("last_stall"):
            last = watchdog["last_stall"]
            alerts.append(
                f"{red}stall{reset} {peer[:16]}: loop blocked "
                f"{last.get('blocked_s_at_capture', '?')}s at {last.get('frame', '')}"
                if "frame" in last
                else f"{red}stall{reset} {peer[:16]}: {stalls} event-loop stall(s), "
                f"max lag {watchdog.get('max_lag_s', '?')}s"
            )
        for span in (snapshot.get("slow_spans") or ())[:2]:
            alerts.append(
                f"{yellow}slow{reset} {peer[:16]}: {span.get('name')} "
                f"{span.get('dur_ms')}ms {span.get('events', [])}"
            )
        for board_name, state in sorted(breakers.items()):
            if isinstance(state, dict) and state.get("num_tripped"):
                alerts.append(
                    f"{yellow}breaker{reset} {peer[:16]}: {board_name} open against {state.get('tripped')}"
                )
        for metric_name, what in (
            ("hivemind_optimizer_epoch_adopted_without_state_total", "epoch adopted WITHOUT state"),
            ("hivemind_state_sync_unverified_adoptions_total", "unverified state adoption"),
        ):
            value = _metric_total(snapshot, metric_name)
            if value:
                alerts.append(f"{red}recovery{reset} {peer[:16]}: {value:g} {what}")

    for peer, snapshot in sorted(records.items(), key=lambda kv: str(kv[0])):
        # snapshots are DHT-supplied: one malformed (buggy, version-skewed,
        # hostile) peer gets a flagged row, never a dead dashboard
        try:
            _render_peer(str(peer), snapshot if isinstance(snapshot, dict) else {})
        except Exception as e:
            logger.debug(f"malformed snapshot from {peer!r}: {e!r}")
            lines.append(f"{str(peer)[:18]:<18} {red}<malformed snapshot>{reset}")

    if straggler_board:
        lines.append("")
        lines.append(f"{bold}stragglers (merged from every peer's round ledger){reset}")
        ranked = sorted(
            straggler_board.items(),
            key=lambda kv: (-kv[1]["rounds_slowest"], -kv[1]["excess_s"]),
        )
        for victim, score in ranked[:8]:
            tier = link_tiers.get(victim)
            lines.append(
                f"  {victim[:18]:<18} slowest in {score['rounds_slowest']:>4} round(s), "
                f"+{score['excess_s']:.3f}s excess, reported by {score['reporters']} peer(s)"
                + (f", link @{tier}" if tier else "")
            )

    if alerts:
        lines.append("")
        lines.append(f"{bold}recent alerts{reset}")
        lines.extend(f"  {alert}" for alert in alerts[-12:])

    text = "\n".join(lines)
    if ansi:
        text = _CLEAR + text
    return text, samples_state


def render_serving_board(
    records: Dict[str, Dict[str, Any]],
    *,
    prev_requests: Optional[Dict[Tuple[str, str], Tuple[float, float]]] = None,
    now: Optional[float] = None,
    ansi: bool = True,
) -> Tuple[str, Dict[Tuple[str, str], Tuple[float, float]]]:
    """The ``--serving`` board (ISSUE 9). Pure: no DHT, no IO. Parsing lives
    in ``telemetry.serving.collect_swarm_serving`` (shared with
    ``SwarmMonitor.render_serving_board``); only the formatting is here.

    ``prev_requests`` maps (peer, expert) -> (request_count, frame_time) from
    the previous frame; returned updated so the caller can thread it through
    for the QPS column (same pattern as ``prev_samples`` in render_frame)."""
    from hivemind_tpu.telemetry.serving import (
        collect_swarm_serving,
        format_saturation_parts,
        format_scorecard_line,
        format_slowest_line,
    )

    now = now if now is not None else time.time()
    bold = _BOLD if ansi else ""
    red = _RED if ansi else ""
    reset = _RESET if ansi else ""
    data = collect_swarm_serving(records)
    request_state: Dict[Tuple[str, str], Tuple[float, float]] = {}

    lines: List[str] = [f"{bold}serving board{reset} — per-expert requests / QPS / p95 / sheds"]
    header = f"{'expert':<24} {'peer':<14} {'req':>7} {'qps':>6} {'p95 ms':>8} {'shed':>5}"
    lines.append(bold + header + reset)
    rows: List[str] = []
    for peer, uid, stats in data["experts"]:
        requests = stats["requests"]
        request_state[(peer, uid)] = (requests, now)
        qps = None
        if prev_requests and (peer, uid) in prev_requests:
            prev_count, prev_time = prev_requests[(peer, uid)]
            if now > prev_time:
                qps = max(requests - prev_count, 0.0) / (now - prev_time)
        p95 = stats["p95_s"]
        sheds = stats["sheds"]
        # pad BEFORE colorizing: escape codes inside a width spec eat the
        # padding and misalign exactly the rows the operator cares about
        shed_field = f"{sheds:>5}"
        rows.append(
            f"{uid[:24]:<24} {peer[:14]:<14} {requests:>7.0f} "
            f"{(f'{qps:.1f}' if qps is not None else '-'):>6} "
            f"{(f'{p95 * 1e3:.1f}' if p95 is not None else '-'):>8} "
            + (f"{red}{shed_field}{reset}" if sheds else shed_field)
        )
    malformed_rows = [
        f"{peer[:24]:<24} {red}<malformed serving section>{reset}"
        for peer in data["malformed"]
    ]

    saturation_rows = [
        f"  {peer[:16]:<16} {', '.join(format_saturation_parts(entry, red=red, reset=reset))}"
        for peer, entry in data["saturation"]
    ]

    if not rows and not malformed_rows and not saturation_rows:
        lines.append("  (no serving traffic reported by any peer)")
    lines.extend(rows[:20])
    lines.extend(malformed_rows)  # never capped away: a broken peer must show
    if saturation_rows:
        lines.append(f"{bold}saturation{reset}")
        lines.extend(saturation_rows)
    if data["degraded_scorecards"]:
        lines.append(f"{bold}degraded scorecards (client view){reset}")
        lines.extend(
            "  " + format_scorecard_line(peer, uid, card)
            for peer, uid, card in data["degraded_scorecards"][:8]
        )
    if data["slowest"]:
        lines.append(f"{bold}slowest requests (queue/assembly/compute/serialize){reset}")
        lines.extend(
            "  " + format_slowest_line(total_s, peer, record)
            for total_s, peer, record in data["slowest"][:5]
        )
    return "\n".join(lines), request_state


def _mib(nbytes: Any) -> str:
    try:
        return f"{float(nbytes) / 2**20:.1f}"
    except (TypeError, ValueError):
        return "-"


def render_device_board(records: Dict[str, Dict[str, Any]], *, ansi: bool = True) -> str:
    """The ``--device`` board (ISSUE 19). Pure: no DHT, no IO. Renders each
    peer's ``device`` snapshot section — live DHT snapshots and ``--from-spool``
    replays emit the same shape, so dead peers render like live ones."""
    bold = _BOLD if ansi else ""
    red = _RED if ansi else ""
    reset = _RESET if ansi else ""

    lines: List[str] = [f"{bold}device board{reset} — jit compiles / HBM / transfers / overlap"]
    header = (
        f"{'peer':<18} {'compiles':>8} {'storms':>6} {'jit s':>7} {'HBM MiB':>8} "
        f"{'peak MiB':>9} {'bufs':>5} {'h2d MiB':>8} {'d2h MiB':>8} {'ovl %':>6}"
    )
    lines.append(bold + header + reset)
    rows: List[str] = []
    alerts: List[str] = []
    site_board: Dict[str, List[float]] = {}  # site -> [count, seconds]

    for peer, snapshot in sorted(records.items(), key=lambda kv: str(kv[0])):
        device = snapshot.get("device") if isinstance(snapshot, dict) else None
        if not isinstance(device, dict) or not device:
            continue
        # snapshots are DHT/spool-supplied: a malformed device section gets a
        # flagged row, never a dead board (same contract as render_frame)
        try:
            compiles = device.get("compiles") or {}
            total = int(compiles.get("total") or 0)
            storms = int(compiles.get("storms") or 0)
            seconds = float(compiles.get("seconds") or 0.0)
            memory = device.get("memory") or {}
            peak = max(
                (int(entry.get("peak_bytes") or 0) for entry in (memory.get("devices") or {}).values()),
                default=None,
            )
            transfers = device.get("transfer_bytes") or {}
            overlap = device.get("overlap") or {}
            mean_overlap = overlap.get("mean")

            storm_field = f"{storms:>6}"
            rows.append(
                f"{str(peer)[:18]:<18} {total:>8} "
                + (f"{red}{storm_field}{reset}" if storms else storm_field)
                + f" {seconds:>7.2f} {_mib(memory.get('total_bytes')):>8} "
                f"{(_mib(peak) if peak is not None else '-'):>9} "
                f"{(memory.get('buffers') if memory.get('buffers') is not None else '-'):>5} "
                f"{_mib(transfers.get('host_to_device')):>8} "
                f"{_mib(transfers.get('device_to_host')):>8} "
                f"{(f'{mean_overlap * 100:.1f}' if mean_overlap is not None else '-'):>6}"
            )
            for site, stats in (compiles.get("sites") or {}).items():
                entry = site_board.setdefault(str(site), [0, 0.0])
                entry[0] += int((stats or {}).get("count") or 0)
                entry[1] += float((stats or {}).get("seconds") or 0.0)
            if storms:
                last = compiles.get("last") or {}
                alerts.append(
                    f"{red}recompile-storm{reset} {str(peer)[:16]}: {storms} storm(s), "
                    f"last compile at {last.get('site', '?')}"
                )
            if device.get("leaks_suspected"):
                alerts.append(
                    f"{red}hbm-leak{reset} {str(peer)[:16]}: "
                    f"{device['leaks_suspected']} suspected leak episode(s)"
                )
        except Exception as e:
            logger.debug(f"malformed device section from {peer!r}: {e!r}")
            rows.append(f"{str(peer)[:18]:<18} {red}<malformed device section>{reset}")

    if not rows:
        lines.append("  (no device telemetry reported by any peer)")
    lines.extend(rows[:20])
    if site_board:
        ranked = sorted(site_board.items(), key=lambda kv: (-kv[1][0], kv[0]))
        lines.append(f"{bold}hot compile sites (merged across peers){reset}")
        lines.extend(
            f"  {site[:40]:<40} x{int(count):>4}  {seconds:>7.2f}s"
            for site, (count, seconds) in ranked[:6]
        )
    if alerts:
        lines.append(f"{bold}device alerts{reset}")
        lines.extend(f"  {alert}" for alert in alerts[-8:])
    return "\n".join(lines)


def main() -> None:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("--initial_peers", nargs="*", default=[],
                        help="multiaddrs of swarm members to read telemetry from")
    parser.add_argument("--key", default=None,
                        help="the swarm's telemetry DHT key (default: hivemind_telemetry)")
    parser.add_argument("--interval", type=float, default=5.0, help="refresh period, seconds")
    parser.add_argument("--publish_interval", type=float, default=DEFAULT_PUBLISH_INTERVAL,
                        help="the swarm's TelemetryPublisher cadence; snapshots older "
                             f"than {STALE_AFTER_FACTOR:g}x this are flagged STALE")
    parser.add_argument("--frames", type=int, default=0,
                        help="render this many frames then exit (0 = run until ^C)")
    parser.add_argument("--no-ansi", action="store_true", dest="no_ansi",
                        help="plain text frames, no screen clearing (piping / CI)")
    parser.add_argument("--serving", action="store_true",
                        help="append the serving board: per-expert QPS/p95/sheds, "
                             "saturation, scorecards, slowest-request exemplars")
    parser.add_argument("--device", action="store_true",
                        help="append the device board: jit compiles/storms, HBM "
                             "live/peak bytes, host<->device transfer totals, "
                             "comm/compute overlap efficiency")
    parser.add_argument("--from-spool", nargs="+", default=None, dest="from_spool",
                        metavar="DIR",
                        help="replay mode for dead swarms: render one frame from "
                             "black-box spool directories (no DHT) and exit")
    args = parser.parse_args()

    if args.from_spool:
        # post-mortem replay (ISSUE 17): the dashboard over spools a dead
        # swarm left behind — a pure reader of the on-disk frames
        from pathlib import Path

        from hivemind_tpu.hivemind_cli.run_blackbox import load_spools, spool_snapshot

        spools = load_spools([Path(d) for d in args.from_spool])
        records = {peer: spool_snapshot(spool) for peer, spool in spools.items()}
        newest = max(
            (snapshot.get("time", 0.0) for snapshot in records.values()), default=0.0
        )
        frame, _ = render_frame(
            records,
            publish_interval=args.publish_interval,
            now=newest or None,
            ansi=not args.no_ansi,
        )
        # post-mortems are one frame with no space pressure: always show the
        # victim's device state (last compiles / HBM at death) when spooled
        if args.device or any(
            isinstance(s, dict) and s.get("device") for s in records.values()
        ):
            frame = f"{frame}\n\n{render_device_board(records, ansi=not args.no_ansi)}"
        print(frame, flush=True)
        return

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.telemetry.monitor import DEFAULT_TELEMETRY_KEY, fetch_swarm_telemetry

    key = args.key or DEFAULT_TELEMETRY_KEY
    dht = DHT(initial_peers=args.initial_peers, start=True)
    prev_samples: Dict[str, Tuple[float, float]] = {}
    prev_requests: Dict[Tuple[str, str], Tuple[float, float]] = {}
    rendered = 0
    try:
        while True:
            try:
                records = fetch_swarm_telemetry(dht, key)
            except Exception as e:
                logger.warning(f"telemetry fetch failed: {e!r}")
                records = {}
            frame, prev_samples = render_frame(
                records,
                publish_interval=args.publish_interval,
                prev_samples=prev_samples,
                ansi=not args.no_ansi,
            )
            if args.serving:
                board, prev_requests = render_serving_board(
                    records, prev_requests=prev_requests, ansi=not args.no_ansi
                )
                frame = f"{frame}\n\n{board}"
            if args.device:
                frame = f"{frame}\n\n{render_device_board(records, ansi=not args.no_ansi)}"
            print(frame, flush=True)
            rendered += 1
            if args.frames and rendered >= args.frames:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        dht.shutdown()


if __name__ == "__main__":
    main()
