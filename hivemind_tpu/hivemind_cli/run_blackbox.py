"""``hivemind-blackbox``: cross-peer post-mortem over black-box spools
(ISSUE 17 tentpole).

Each peer's :class:`~hivemind_tpu.telemetry.blackbox.BlackBox` leaves a
crash-durable spool directory behind; this tool reads N of them and rebuilds
what the swarm was doing when it died:

- **merge** — one cross-peer timeline: frames joined on trace id, per-peer
  wall-anchor skew corrected so a child span can never start before the
  remote parent that caused it (the spool headers' anchor/drift estimates
  bound the residual);
- **chrome export** (``--format chrome``) — the merged spans as Chrome
  trace-event JSON, one pid row per peer; opens directly in Perfetto;
- **post-mortem** (``--victim``) — the victim's final ledger round and its
  last in-flight span (a ``span_start`` frame with no matching finish: the
  operation the peer died inside), which the churn soak's
  ``postmortem_reconstructed`` verdict requires;
- **--last N** — focus every output on the final N seconds before the
  victim's (or the swarm's) last recorded frame.

Run it::

    hivemind-blackbox /tmp/run/blackbox/peer* --victim <peer_id> --last 30
    hivemind-blackbox /tmp/run/blackbox/peer* --format chrome --out dead_swarm.json

``hivemind-top --from-spool`` renders the same spools as a dashboard frame.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from hivemind_tpu.telemetry.blackbox import read_spool
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# skew refinement passes: each pass propagates causality constraints one
# cross-peer hop further; real swarm graphs settle in two or three
_SKEW_PASSES = 4


def load_spools(directories: List[Path]) -> Dict[str, Dict[str, Any]]:
    """Read each spool dir into ``{peer: {"frames", "stats", "header"}}``.
    The peer name comes from the newest segment header (falling back to the
    directory name for headerless/empty spools)."""
    spools: Dict[str, Dict[str, Any]] = {}
    for directory in directories:
        frames, stats = read_spool(directory)
        header: Optional[Dict[str, Any]] = None
        for frame in frames:
            if frame["k"] == "header":
                header = frame["d"]
        peer = str((header or {}).get("peer") or Path(directory).name)
        spools[peer] = {"frames": frames, "stats": stats, "header": header}
    return spools


def _span_frames(frames: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [f for f in frames if f["k"] in ("span", "span_start") and isinstance(f["d"], dict)]


def estimate_skew(spools: Dict[str, Dict[str, Any]]) -> Dict[str, float]:
    """Per-peer clock offsets (seconds to ADD to a peer's timestamps) from
    causality: a span whose parent lives on another peer cannot start before
    that parent did — cross-peer RPC propagation guarantees the ordering, so
    any negative child-minus-parent gap measures wall-anchor skew. Best
    effort: peers with no cross-peer spans keep offset 0."""
    # newest observation per span id wins (span frames repeat: start + finish)
    owner: Dict[str, Tuple[str, float]] = {}
    for peer, spool in spools.items():
        for frame in _span_frames(spool["frames"]):
            data = frame["d"]
            if "span" in data and "start" in data:
                owner[data["span"]] = (peer, float(data["start"]))
    offsets = {peer: 0.0 for peer in spools}
    for _ in range(_SKEW_PASSES):
        moved = False
        for peer, spool in spools.items():
            for frame in _span_frames(spool["frames"]):
                data = frame["d"]
                parent = data.get("parent")
                if parent is None or "start" not in data:
                    continue
                parent_owner = owner.get(parent)
                if parent_owner is None or parent_owner[0] == peer:
                    continue
                parent_peer, parent_start = parent_owner
                gap = (float(data["start"]) + offsets[peer]) - (
                    parent_start + offsets[parent_peer]
                )
                if gap < 0:
                    offsets[peer] = round(offsets[peer] - gap, 6)
                    moved = True
        if not moved:
            break
    return offsets


def merge_timeline(
    spools: Dict[str, Dict[str, Any]],
    offsets: Optional[Dict[str, float]] = None,
    last_s: Optional[float] = None,
    victim: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """All peers' frames as one time-sorted list of ``{"t", "peer", "k",
    "d"}`` with skew-corrected timestamps. ``last_s`` keeps only the final
    window, anchored at the victim's last frame when given (the moment of
    death), else the swarm-wide newest frame."""
    offsets = offsets if offsets is not None else estimate_skew(spools)
    merged: List[Dict[str, Any]] = []
    for peer, spool in spools.items():
        shift = offsets.get(peer, 0.0)
        for frame in spool["frames"]:
            merged.append(
                {"t": round(float(frame["t"]) + shift, 6), "peer": peer,
                 "k": frame["k"], "d": frame["d"]}
            )
    merged.sort(key=lambda f: f["t"])
    if last_s is not None and merged:
        if victim is not None:
            victim_times = [f["t"] for f in merged if f["peer"] == victim]
            horizon = max(victim_times) if victim_times else merged[-1]["t"]
        else:
            horizon = merged[-1]["t"]
        merged = [f for f in merged if horizon - last_s <= f["t"] <= horizon]
    return merged


def reconstruct_final_round(
    frames: List[Dict[str, Any]], stats: Optional[Dict[str, int]] = None
) -> Dict[str, Any]:
    """One dead peer's last moments from its spool: the final ledger round
    (the newest copy wins — rounds re-emitted by late-exchange retro-
    attribution supersede earlier ones), the last FINISHED span, and the last
    IN-FLIGHT span (started, never finished: the operation it died inside)."""
    final_round: Optional[Dict[str, Any]] = None
    last_epoch: Optional[Dict[str, Any]] = None
    finished: Dict[str, Dict[str, Any]] = {}
    starts: List[Tuple[float, Dict[str, Any]]] = []
    last_finished: Optional[Dict[str, Any]] = None
    device = _aggregate_device_frames(frames)
    for frame in frames:
        kind, data = frame["k"], frame["d"]
        if kind == "ledger_round":
            if final_round is None or data.get("round", 0) >= final_round.get("round", 0):
                final_round = data
        elif kind == "ledger_epoch":
            last_epoch = data
        elif kind == "span":
            finished[data.get("span", "")] = data
            last_finished = data
        elif kind == "span_start":
            starts.append((float(frame["t"]), data))
    in_flight = [data for _t, data in starts if data.get("span") not in finished]
    out: Dict[str, Any] = {
        "reconstructed": final_round is not None and bool(in_flight or last_finished),
        "final_round": final_round,
        "last_span": last_finished,
        "last_in_flight": in_flight[-1] if in_flight else None,
        "open_spans": len(in_flight),
    }
    if last_epoch is not None:
        out["last_epoch"] = last_epoch
    if device:
        # ISSUE 19: the victim's last device-side state — its final compile
        # and HBM sample are part of "what was it doing when it died"
        out["device"] = device
    if stats is not None:
        out["reader_stats"] = dict(stats)
    return out


def _aggregate_device_frames(frames: List[Dict[str, Any]]) -> Dict[str, Any]:
    """``device`` frames rolled into one snapshot-shaped section: per-site
    compile counts (recomputed by replay), the newest memory sample, storm /
    leak counts, and the last overlap record. Empty dict when the spool holds
    no device telemetry (pre-ISSUE-19 spools stay readable)."""
    # sites carry {"count": ...} dicts — the SAME shape as the live
    # device_snapshot(), so hivemind-top's device board renders either
    sites: Dict[str, Dict[str, int]] = {}
    out: Dict[str, Any] = {}
    storms = leaks = 0
    last_compile = last_memory = None
    ratios: List[float] = []
    for frame in frames:
        if frame["k"] != "device" or not isinstance(frame["d"], dict):
            continue
        data = frame["d"]
        kind = data.get("kind")
        if kind == "compile":
            # each frame carries the site's running count: the last one wins
            sites[str(data.get("site"))] = {"count": int(data.get("count", 0))}
            last_compile = data
        elif kind == "storm":
            storms += 1
        elif kind == "memory":
            last_memory = data
        elif kind == "leak":
            leaks += 1
        elif kind == "overlap":
            ratios.append(float(data.get("overlap_ratio", 0.0)))
    if sites:
        out["compiles"] = {
            "total": sum(site["count"] for site in sites.values()),
            "sites": sites,
            "storms": storms,
            "last": last_compile,
        }
        out["last_compile"] = last_compile
    if last_memory is not None:
        out["memory"] = {k: v for k, v in last_memory.items() if k != "kind"}
    if leaks:
        out["leaks_suspected"] = leaks
    if ratios:
        out["overlap"] = {
            "rounds": len(ratios),
            "last": ratios[-1],
            "mean": round(sum(ratios) / len(ratios), 4),
        }
    return out


def render_spool_chrome_trace(merged: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merged span frames as Chrome trace-event JSON (Perfetto): one pid row
    per peer, finished spans as complete events, still-open spans as instants
    flagged ``in_flight`` — on a dead peer's row, the instant at the end IS
    the crash site. Comm/compute spans land on fixed named lanes per peer
    (ISSUE 19, mirroring ``tracing.export_chrome_trace``) so the overlap the
    StepTimeline scores is visible as two stacked rows."""
    from hivemind_tpu.telemetry.device import span_lane

    lane_tids = {"compute": 1, "comm": 2}
    peers: Dict[str, int] = {}
    lanes_used: set = set()
    events: List[Dict[str, Any]] = []
    finished_ids = {
        f["d"].get("span") for f in merged if f["k"] == "span" and isinstance(f["d"], dict)
    }
    for frame in merged:
        if frame["k"] not in ("span", "span_start") or not isinstance(frame["d"], dict):
            continue
        data = frame["d"]
        pid = peers.get(frame["peer"])
        if pid is None:
            pid = peers[frame["peer"]] = len(peers) + 1
        args = {k: v for k, v in (data.get("attrs") or {}).items()}
        args["trace_id"] = data.get("trace")
        args["span_id"] = data.get("span")
        if data.get("parent"):
            args["parent_id"] = data["parent"]
        lane = span_lane(str(data.get("name") or ""))
        if lane is not None:
            tid = lane_tids[lane]
            args["lane"] = lane
            lanes_used.add((pid, lane))
        else:
            tid = 3
        if frame["k"] == "span":
            events.append(
                {"name": data.get("name"), "cat": "span", "ph": "X",
                 "ts": round(float(data.get("start", frame["t"])) * 1e6, 3),
                 "dur": round(max(float(data.get("dur_s", 0.0)) * 1e6, 0.001), 3),
                 "pid": pid, "tid": tid, "args": args}
            )
        elif data.get("span") not in finished_ids:
            args["in_flight"] = True
            events.append(
                {"name": data.get("name"), "cat": "span", "ph": "i", "s": "p",
                 "ts": round(float(data.get("start", frame["t"])) * 1e6, 3),
                 "pid": pid, "tid": tid, "args": args}
            )
    for peer, pid in peers.items():
        events.append(
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"peer {peer}"}}
        )
    for pid, lane in sorted(lanes_used):
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid,
             "tid": lane_tids[lane], "args": {"name": lane}}
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def spool_snapshot(spool: Dict[str, Any]) -> Dict[str, Any]:
    """One peer's spool rendered as the snapshot shape ``hivemind-top``'s
    render_frame consumes — the bridge behind ``hivemind-top --from-spool``
    (a dashboard over a dead swarm). Straggler scores are recomputed from the
    spooled round records, so attribution survives the crash too."""
    frames = spool["frames"]
    snapshot: Dict[str, Any] = {}
    rounds: Dict[Any, Dict[str, Any]] = {}
    stragglers: Dict[str, Dict[str, float]] = {}
    slow: List[Dict[str, Any]] = []
    last_t = 0.0
    for frame in frames:
        kind, data = frame["k"], frame["d"]
        last_t = max(last_t, float(frame["t"]))
        if kind == "metrics" and isinstance(data, dict):
            snapshot["metrics"] = data.get("metrics") or {}
        elif kind == "ledger_round" and isinstance(data, dict):
            rounds[data.get("round")] = data  # newest re-emission wins
        elif kind == "span" and isinstance(data, dict) and "dur_s" in data:
            slow.append(data)
    for record in rounds.values():
        slowest = record.get("slowest_peer")
        if not slowest:
            continue
        score = stragglers.setdefault(
            str(slowest), {"rounds_slowest": 0, "excess_s": 0.0, "total_s": 0.0}
        )
        score["rounds_slowest"] += 1
        durations = sorted(
            (float(e["dur_s"]) for e in record.get("exchanges") or () if "dur_s" in e),
            reverse=True,
        )
        if len(durations) > 1:
            median = durations[len(durations) // 2]
            score["excess_s"] = round(
                score["excess_s"] + max(0.0, durations[0] - median), 6
            )
    slow.sort(key=lambda d: -float(d.get("dur_s", 0.0)))
    snapshot["time"] = last_t
    ledger: Dict[str, Any] = {}
    if rounds:
        ledger["records"] = [
            {k: v for k, v in record.items() if k != "exchanges"}
            for _key, record in sorted(rounds.items(), key=lambda kv: kv[1].get("round", 0))
        ]
    if stragglers:
        ledger["stragglers"] = stragglers
    if ledger:
        snapshot["ledger"] = ledger
    if slow:
        snapshot["slow_spans"] = [
            {"name": d.get("name"), "dur_ms": round(float(d["dur_s"]) * 1e3, 3),
             "events": [e[1] for e in d.get("events") or ()]}
            for d in slow[:3]
        ]
    device = _aggregate_device_frames(frames)
    if device:
        # same shape as the live snapshot's device section — hivemind-top's
        # device board renders a dead peer exactly like a live one
        snapshot["device"] = device
    return snapshot


def _text_report(
    spools: Dict[str, Dict[str, Any]],
    offsets: Dict[str, float],
    merged: List[Dict[str, Any]],
    victim: Optional[str],
) -> str:
    lines = [f"merged {len(merged)} frame(s) from {len(spools)} spool(s)"]
    for peer, spool in sorted(spools.items()):
        stats = spool["stats"]
        clock = (spool["header"] or {}).get("clock", "?")
        lines.append(
            f"  {peer[:24]:<24} {stats['frames']:>6} frames / {stats['segments']} segment(s), "
            f"clock={clock}, skew={offsets.get(peer, 0.0):+.3f}s"
            + (f", torn_tail={stats['torn_tail']}" if stats["torn_tail"] else "")
            + (f", corrupt={stats['corrupt']}" if stats["corrupt"] else "")
        )
    targets = [victim] if victim else sorted(spools)
    for peer in targets:
        if peer not in spools:
            lines.append(f"  victim {peer!r}: no such spool")
            continue
        post = reconstruct_final_round(spools[peer]["frames"], spools[peer]["stats"])
        final_round = post["final_round"] or {}
        lines.append(f"post-mortem {peer}:")
        lines.append(
            f"  final round: #{final_round.get('round', '?')} "
            f"group_size={final_round.get('group_size')} total={final_round.get('total_s')}s "
            f"slowest={final_round.get('slowest_peer')}"
            if post["final_round"]
            else "  final round: <none spooled>"
        )
        in_flight = post["last_in_flight"]
        if in_flight is not None:
            lines.append(
                f"  last in-flight span: {in_flight.get('name')} "
                f"(trace {in_flight.get('trace')}, started {in_flight.get('start')}) "
                f"— died inside this operation"
            )
        elif post["last_span"] is not None:
            lines.append(f"  last finished span: {post['last_span'].get('name')}")
        device = post.get("device") or {}
        compiles = device.get("compiles")
        if compiles:
            last_compile = device.get("last_compile") or {}
            lines.append(
                f"  device: {compiles.get('total', 0)} compile(s), "
                f"{compiles.get('storms', 0)} storm(s); last compile at site "
                f"{last_compile.get('site')!r}"
            )
        memory = device.get("memory")
        if memory:
            lines.append(
                f"  device memory at death: {memory.get('total_bytes', 0)} live bytes "
                f"across {memory.get('buffers', 0)} buffer(s)"
                + (f", leaks suspected: {device['leaks_suspected']}"
                   if device.get("leaks_suspected") else "")
            )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("spools", nargs="+", type=Path,
                        help="black-box spool directories, one per peer")
    parser.add_argument("--victim", default=None,
                        help="focus the post-mortem (and --last window) on this peer")
    parser.add_argument("--last", type=float, default=None, metavar="N",
                        help="keep only the final N seconds before the victim's "
                             "(or swarm's) last recorded frame")
    parser.add_argument("--format", choices=("text", "json", "chrome"), default="text",
                        help="text post-mortem, merged-timeline JSON, or Chrome "
                             "trace-event JSON for Perfetto")
    parser.add_argument("--out", type=Path, default=None,
                        help="write the report here instead of stdout")
    args = parser.parse_args(argv)

    missing = [str(d) for d in args.spools if not Path(d).is_dir()]
    if missing:
        parser.error(f"not a spool directory: {', '.join(missing)}")
    spools = load_spools(args.spools)
    offsets = estimate_skew(spools)
    merged = merge_timeline(spools, offsets, last_s=args.last, victim=args.victim)

    if args.format == "chrome":
        report = json.dumps(render_spool_chrome_trace(merged))
    elif args.format == "json":
        victims = [args.victim] if args.victim else sorted(spools)
        report = json.dumps(
            {
                "peers": {
                    peer: {"stats": spool["stats"], "header": spool["header"],
                           "skew_s": offsets.get(peer, 0.0)}
                    for peer, spool in spools.items()
                },
                "postmortem": {
                    peer: reconstruct_final_round(spools[peer]["frames"])
                    for peer in victims if peer in spools
                },
                "timeline": merged,
            },
            default=str,
        )
    else:
        report = _text_report(spools, offsets, merged, args.victim)

    if args.out is not None:
        args.out.write_text(report + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
