"""Run a MoE expert server (capability parity: reference
hivemind/hivemind_cli/run_server.py)."""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

from hivemind_tpu.moe import Server
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def main():
    parser = argparse.ArgumentParser(description="Run a hivemind_tpu MoE expert server")
    parser.add_argument("--num_experts", type=int, default=None)
    parser.add_argument("--expert_uids", nargs="*", default=None, help="explicit expert uids")
    parser.add_argument("--expert_pattern", default=None, help="e.g. 'ffn.[0:16].[0:16]'")
    parser.add_argument("--expert_cls", default="ffn", help="registered expert class")
    parser.add_argument("--hidden_dim", type=int, default=1024)
    parser.add_argument("--expert_kwargs", default=None,
                        help="JSON dict forwarded to the expert class, e.g. "
                             "'{\"num_kv_heads\": 2}' for GQA llama_block")
    parser.add_argument("--decode_max_len", type=int, default=256,
                        help="KV-cache decode session capacity (prompt + generated "
                             "tokens) per client session")
    parser.add_argument("--decode_max_sessions", type=int, default=64,
                        help="LRU cap on concurrent KV-cache decode sessions "
                             "(occupancy/evictions are gauged — see "
                             "docs/observability.md 'Serving')")
    parser.add_argument("--max_queue_size", type=int, default=1024,
                        help="bounded task-pool queue: submits past this many "
                             "waiting tasks are SHED with ServerOverloadedError "
                             "(counted in hivemind_moe_shed_total) instead of "
                             "queueing unboundedly toward client timeouts")
    parser.add_argument("--activation_compression", default="float16",
                        help="wire dtype for expert activations/grads on the "
                             "serving RPC path (float16 halves wire bytes; "
                             "'none' = bit-identical fp32). Published in expert "
                             "info + DHT declarations so clients negotiate the "
                             "same codec for requests; see docs/benchmarks.md")
    parser.add_argument("--client_rate", type=float, default=None,
                        help="fair-share admission (ISSUE 13): per-client token "
                             "budget in samples/s — a hot client past its bucket "
                             "is shed (typed ClientOverBudgetError, counted in "
                             "hivemind_moe_admission_shed_total) while other "
                             "clients keep flowing. Default: off")
    parser.add_argument("--client_burst", type=float, default=None,
                        help="token-bucket burst ceiling (default 2s of --client_rate)")
    parser.add_argument("--replica_slots", type=int, default=0,
                        help="acquire up to this many hot experts from other "
                             "servers (rpc_replica_state transfer, then served + "
                             "declared here as extra replicas)")
    parser.add_argument("--replicate_hot_experts", action="store_true",
                        help="advertise this server's hot experts (ServingLedger "
                             "QPS/occupancy thresholds) under replica_wanted.* so "
                             "servers with --replica_slots pick them up")
    parser.add_argument("--replication_watch_grids", nargs="*", default=None,
                        help="grid roots to scan for replica_wanted adverts "
                             "(default: the roots of this server's own experts)")
    parser.add_argument("--custom_module_path", default=None,
                        help="path to a .py file whose @register_expert_class "
                             "decorators run before the server starts (capability "
                             "parity: reference custom_experts.py add_custom_models)")
    parser.add_argument("--max_batch_size", type=int, default=4096)
    parser.add_argument("--initial_peers", nargs="*", default=[])
    parser.add_argument("--checkpoint_dir", default=None)
    parser.add_argument("--llama_checkpoint", default=None,
                        help="serve a real (sharded) HF-layout Llama checkpoint: "
                             "decoder layers load into llama_block backends "
                             "(BASELINE config #5 Petals-style block server)")
    parser.add_argument("--llama_layers", default=None,
                        help="'start:stop' layer range of --llama_checkpoint to "
                             "serve (default: HBM-budgeted from the start, or all "
                             "when the platform reports no memory limit)")
    parser.add_argument("--llama_uid_prefix", default="llama.")
    parser.add_argument("--mesh_devices", type=int, default=0,
                        help="serve each block MESH-SHARDED over this many local "
                             "devices (params + KV caches as NamedSharding arrays; "
                             "0 = single-device serving). The HBM plan uses the "
                             "probe block's MEASURED per-device residency, so "
                             "blocks one chip cannot hold fit when they shard")
    parser.add_argument("--weight_quantization", choices=["int8"], default=None,
                        help="serve blocks int8 weight-only via the blockwise "
                             "codec (4x less resident HBM; inference-only)")
    parser.add_argument("--decode_sessions_budget", type=int, default=8,
                        help="concurrent decode sessions the HBM plan reserves "
                             "KV-cache space for")
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--max_connections", type=int, default=0,
                        help="connection-manager high water for the DHT peer "
                             "(0 = unlimited; bounds fds at swarm scale)")
    parser.add_argument("--increase_file_limit", action="store_true",
                        help="raise RLIMIT_NOFILE for many concurrent connections")
    parser.add_argument("--metrics-port", "--metrics_port", type=int, default=None,
                        dest="metrics_port",
                        help="serve Prometheus text exposition at "
                             "http://<metrics_host>:PORT/metrics (0 = auto-pick)")
    parser.add_argument("--metrics_host", default="127.0.0.1",
                        help="bind host of the metrics endpoint (0.0.0.0 for "
                             "remote scrapers)")
    parser.add_argument("--telemetry_key", default=None,
                        help="publish this server's telemetry snapshot to the DHT "
                             "under this key every --telemetry_interval seconds")
    parser.add_argument("--telemetry_interval", type=float, default=30.0)
    parser.add_argument("--blackbox_dir", default=None,
                        help="crash-durable flight-recorder spool directory: "
                             "finished spans, round/serving ledger records and "
                             "metric snapshots are appended as msgpack frames "
                             "readable post-mortem with hivemind-blackbox (see "
                             "docs/observability.md 'Black-box flight recorder')")
    parser.add_argument("--no_device_telemetry", action="store_false", dest="device_telemetry",
                        help="disable device-side observability (jit compile tracking, "
                             "HBM/leak sampling on the watchdog tick, transfer counters; "
                             "docs/observability.md 'Device telemetry'); on by default")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)

    if args.increase_file_limit:
        from hivemind_tpu.utils.limits import increase_file_limit

        increase_file_limit()

    if args.custom_module_path:
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location("hivemind_custom_experts", args.custom_module_path)
        if spec is None or spec.loader is None:
            raise RuntimeError(f"cannot load {args.custom_module_path}")
        module = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = module  # classes' __module__ must resolve (pickling etc.)
        spec.loader.exec_module(module)  # runs the @register_expert_class decorators
        logger.info(f"loaded custom expert module {args.custom_module_path}")

    import optax

    if args.llama_checkpoint:
        server = _serve_llama_checkpoint(args)
        _run_forever(server, _start_telemetry(args, server.dht))
        return
    if args.mesh_devices:
        raise SystemExit(
            "--mesh_devices is only supported with --llama_checkpoint serving; "
            "the registry-expert path would silently ignore it"
        )

    from hivemind_tpu.dht import DHT

    # construct the DHT here so --max_connections reaches its transport
    dht = DHT(initial_peers=args.initial_peers, start=True,
              max_connections=args.max_connections)
    server = Server.create(
        num_experts=args.num_experts,
        expert_uids=args.expert_uids,
        expert_pattern=args.expert_pattern,
        expert_cls=args.expert_cls,
        hidden_dim=args.hidden_dim,
        expert_kwargs=json.loads(args.expert_kwargs) if args.expert_kwargs else None,
        max_batch_size=args.max_batch_size,
        dht=dht,
        checkpoint_dir=Path(args.checkpoint_dir) if args.checkpoint_dir else None,
        decode_max_len=args.decode_max_len,
        decode_max_sessions=args.decode_max_sessions,
        max_queue_size=args.max_queue_size,
        activation_compression=args.activation_compression,
        client_rate=args.client_rate,
        client_burst=args.client_burst,
        replica_slots=args.replica_slots,
        replicate_hot_experts=args.replicate_hot_experts,
        replication_watch_grids=args.replication_watch_grids,
        optim_factory=lambda: optax.adam(args.learning_rate),
        start=True,
    )
    _run_forever(server, _start_telemetry(args, dht))


def _serve_llama_checkpoint(args) -> Server:
    """BASELINE config #5: serve a real checkpoint's decoder layers, choosing how
    many fit this chip when no explicit range is given."""
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe.server.llama_loader import (
        LlamaCheckpointConfig,
        decode_cache_bytes,
        device_hbm_bytes,
        load_llama_blocks,
        plan_block_capacity,
    )

    mesh = None
    if args.mesh_devices:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if args.mesh_devices < 1:
            raise ValueError(f"--mesh_devices must be >= 1, got {args.mesh_devices}")
        devices = jax.local_devices()[: args.mesh_devices]
        if len(devices) < args.mesh_devices:
            raise RuntimeError(
                f"--mesh_devices {args.mesh_devices} but only {len(devices)} local devices"
            )
        mesh = Mesh(np.array(devices).reshape(len(devices)), ("tp",))

    config = LlamaCheckpointConfig.load(args.llama_checkpoint)
    if args.llama_layers:
        start, _, stop = args.llama_layers.partition(":")
        layers = range(int(start or 0), int(stop or config.num_hidden_layers))
    else:
        layers = range(config.num_hidden_layers)
        hbm = device_hbm_bytes()
        if hbm is not None:
            # measure one real block, then plan with KV-cache headroom
            probe, _ = load_llama_blocks(
                args.llama_checkpoint, layers=[0], uid_prefix="_probe.",
                weight_quantization=args.weight_quantization, mesh=mesh,
            )
            probe_backend = next(iter(probe.values()))
            # mesh serving: plan from the MEASURED per-device residency, not an
            # assumed 1/mesh fraction — kernels whose last dim does not divide
            # the mesh REPLICATE (leaf_spec), and only the probe knows how much
            block_bytes = (
                probe_backend.param_bytes_per_device() if mesh is not None
                else probe_backend.param_bytes()
            )
            del probe, probe_backend  # release before the real load fills the plan
            fit = plan_block_capacity(
                block_bytes,
                hbm_bytes=hbm,
                decode_sessions=args.decode_sessions_budget,
                # conservative: budget FULL per-session caches on every chip
                # (cache sharding is also divisibility-dependent)
                cache_bytes_per_session_block=decode_cache_bytes(
                    config, batch=1, max_len=args.decode_max_len
                ),
            )
            layers = range(min(fit, config.num_hidden_layers))
            logger.info(
                f"HBM plan: {block_bytes / 1e6:.0f} MB/block resident per chip "
                f"({'mesh of ' + str(args.mesh_devices) if mesh is not None else 'single device'}), "
                f"{hbm / 1e9:.1f} GB/chip → serving {len(layers)} of "
                f"{config.num_hidden_layers} layers"
            )
    backends, _config = load_llama_blocks(
        args.llama_checkpoint,
        layers=layers,
        uid_prefix=args.llama_uid_prefix,
        weight_quantization=args.weight_quantization,
        max_batch_size=args.max_batch_size,
        mesh=mesh,
    )
    dht = DHT(initial_peers=args.initial_peers, start=True,
              max_connections=args.max_connections)
    server = Server(
        dht, backends, decode_max_len=args.decode_max_len,
        # the HBM plan reserved KV space for exactly this many sessions: cap the
        # session manager to it so the reservation is real, not advisory
        decode_max_sessions=args.decode_sessions_budget,
        max_queue_size=args.max_queue_size,
        activation_compression=args.activation_compression,
        client_rate=args.client_rate,
        client_burst=args.client_burst,
    )
    server.run_in_background(await_ready=True)
    return server


def _start_telemetry(args, dht):
    """Optional metrics endpoint + DHT snapshot publisher (docs/observability.md);
    returns the components to shut down, or an empty tuple."""
    from hivemind_tpu.telemetry import ensure_watchdog
    from hivemind_tpu.utils.loop import get_loop_runner

    # server + DHT already armed the loop watchdog; stay loud if it is disabled
    if ensure_watchdog(get_loop_runner().loop) is None:
        logger.warning("event-loop watchdog disabled (HIVEMIND_WATCHDOG=0): stalls will be silent")
    components = []
    if getattr(args, "device_telemetry", True):
        import types

        from hivemind_tpu.telemetry.device import arm_device_telemetry, disarm_device_telemetry

        arm_device_telemetry()
        components.append(types.SimpleNamespace(shutdown=disarm_device_telemetry))
    if getattr(args, "blackbox_dir", None):
        import types

        from hivemind_tpu.telemetry.blackbox import arm_blackbox, disarm_blackbox

        arm_blackbox(args.blackbox_dir, peer=str(dht.peer_id))
        logger.info(f"black-box recorder armed: spooling to {args.blackbox_dir}")
        # disarm (not just close) at shutdown so the global slot is freed for
        # whatever arms next in this process
        components.append(types.SimpleNamespace(shutdown=disarm_blackbox))
    if args.metrics_port is not None:
        from hivemind_tpu.telemetry import MetricsExporter

        components.append(MetricsExporter(port=args.metrics_port, host=args.metrics_host))
    if args.telemetry_key:
        from hivemind_tpu.telemetry import TelemetryPublisher

        components.append(
            TelemetryPublisher(dht, args.telemetry_key, interval=args.telemetry_interval)
        )
    return tuple(components)


def _run_forever(server: Server, telemetry=()) -> None:
    for maddr in server.dht.get_visible_maddrs():
        logger.info(f"listening: {maddr}")
    logger.info(f"serving {len(server.backends)} experts: {sorted(server.backends)[:8]}…")
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        logger.info("shutting down")
        for component in telemetry:
            component.shutdown()
        server.shutdown()
        server.dht.shutdown()


if __name__ == "__main__":
    main()
