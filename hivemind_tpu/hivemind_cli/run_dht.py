"""Run a standalone DHT node (capability parity: reference
hivemind/hivemind_cli/run_dht.py:27-74 — the bootstrap/health-monitor entrypoint)."""

from __future__ import annotations

import argparse
import time

from hivemind_tpu.dht import DHT
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)


def main():
    parser = argparse.ArgumentParser(description="Run a hivemind_tpu DHT bootstrap node")
    parser.add_argument("--initial_peers", nargs="*", default=[], help="multiaddrs of existing peers")
    parser.add_argument("--listen_host", default="0.0.0.0")
    parser.add_argument("--listen_port", type=int, default=0)
    parser.add_argument("--announce_host", default=None, help="externally visible host")
    parser.add_argument("--identity_path", default=None, help="persistent identity file")
    parser.add_argument("--refresh_period", type=float, default=30.0, help="health report interval")
    parser.add_argument("--max_connections", type=int, default=0,
                        help="connection-manager high water (0 = unlimited): idle "
                             "LRU connections close past it, bounding fds at scale")
    parser.add_argument("--metrics-port", "--metrics_port", type=int, default=None,
                        dest="metrics_port",
                        help="serve Prometheus text exposition at "
                             "http://<metrics_host>:PORT/metrics (0 = auto-pick)")
    parser.add_argument("--metrics_host", default="127.0.0.1",
                        help="bind host of the metrics endpoint (0.0.0.0 for "
                             "remote scrapers)")
    parser.add_argument("--telemetry_key", default=None,
                        help="publish this peer's telemetry snapshot to the DHT "
                             "under this key every --refresh_period seconds "
                             "(see docs/observability.md)")
    parser.add_argument("--blackbox_dir", default=None,
                        help="crash-durable flight-recorder spool directory: "
                             "finished spans, ledger records and metric "
                             "snapshots are appended as msgpack frames readable "
                             "post-mortem with hivemind-blackbox (see "
                             "docs/observability.md 'Black-box flight recorder')")
    parser.add_argument("--no_device_telemetry", action="store_false", dest="device_telemetry",
                        help="disable device-side observability (jit compile tracking, "
                             "HBM/leak sampling; docs/observability.md 'Device telemetry'); "
                             "on by default — a DHT-only peer that never touches jax "
                             "pays nothing (the sampler is a no-op without a backend)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)

    dht = DHT(
        initial_peers=args.initial_peers,
        start=True,
        listen_host=args.listen_host,
        listen_port=args.listen_port,
        announce_host=args.announce_host,
        identity_path=args.identity_path,
        max_connections=args.max_connections,
    )
    for maddr in dht.get_visible_maddrs():
        logger.info(f"listening: {maddr}")
    logger.info(f"to join this swarm: --initial_peers {dht.get_visible_maddrs()[0]}")

    blackbox = None
    if args.blackbox_dir:
        from hivemind_tpu.telemetry.blackbox import arm_blackbox

        blackbox = arm_blackbox(args.blackbox_dir, peer=str(dht.peer_id))
        logger.info(f"black-box recorder armed: spooling to {args.blackbox_dir}")

    if args.device_telemetry:
        from hivemind_tpu.telemetry.device import arm_device_telemetry

        arm_device_telemetry()

    # the DHT armed the event-loop watchdog on its loop; asserting here keeps
    # the CLI loud if the kill switch (HIVEMIND_WATCHDOG=0) disabled it
    from hivemind_tpu.telemetry import ensure_watchdog
    from hivemind_tpu.utils.loop import get_loop_runner

    if ensure_watchdog(get_loop_runner().loop) is None:
        logger.warning("event-loop watchdog disabled (HIVEMIND_WATCHDOG=0): stalls will be silent")

    exporter = publisher = None
    if args.metrics_port is not None:
        from hivemind_tpu.telemetry import MetricsExporter

        exporter = MetricsExporter(port=args.metrics_port, host=args.metrics_host)
    if args.telemetry_key:
        from hivemind_tpu.telemetry import TelemetryPublisher

        publisher = TelemetryPublisher(dht, args.telemetry_key, interval=args.refresh_period)

    try:
        while True:
            time.sleep(args.refresh_period)
            # health heartbeat (reference run_dht.py:14-24): table/storage sizes + a live get
            node = dht.node
            table_size = len(node.protocol.routing_table)
            storage_size = len(node.protocol.storage)
            t0 = time.perf_counter()
            dht.get(f"heartbeat_{dht.peer_id}")
            latency = (time.perf_counter() - t0) * 1000
            logger.info(
                f"health: {table_size} peers in routing table, {storage_size} keys stored, "
                f"get latency {latency:.1f}ms"
            )
    except KeyboardInterrupt:
        logger.info("shutting down")
        if publisher is not None:
            publisher.shutdown()
        if exporter is not None:
            exporter.shutdown()
        if blackbox is not None:
            from hivemind_tpu.telemetry.blackbox import disarm_blackbox

            disarm_blackbox()
        if args.device_telemetry:
            from hivemind_tpu.telemetry.device import disarm_device_telemetry

            disarm_device_telemetry()
        dht.shutdown()


if __name__ == "__main__":
    main()
