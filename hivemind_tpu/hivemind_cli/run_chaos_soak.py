"""Chaos soak (ISSUE 3 acceptance; churn phase ISSUE 7): a small in-process
swarm trains under a seeded fault schedule covering every named injection
point, then the faults stop and the soak asserts the swarm LIVED through it:

- every peer's optimizer step count (and epoch) keeps advancing,
- the MoE client keeps getting expert responses after the faults stop,
- every circuit breaker tripped during the storm returns to closed,
- every named injection point actually saw traffic,
- the round ledger NAMED at least one straggler during the chaos-delay phase
  (ISSUE 8: injected slowness must be attributable, not just survivable), and
  the event-loop watchdog counted zero stalls once the faults were disarmed,
- with ``--churn``: peers are crash-killed on a seeded schedule (their DHT
  yanked mid-round, no shutdown, state declarations left dangling) and
  restarted with a local checkpoint directory — the verdict then requires
  ``state_recovered: true`` (every restarted peer back at the tracker's global
  epoch via digest-verified state) and ``digest_failures_adopted: 0`` (chaos
  corrupted payloads on ``state.download.*``, and not one unverified tensor
  was ever adopted).

Run it::

    python -m hivemind_tpu.hivemind_cli.run_chaos_soak --peers 4 --duration 60
    python -m hivemind_tpu.hivemind_cli.run_chaos_soak --peers 4 --duration 60 --churn

or programmatically via :func:`run_soak` (the chaos-marked tests use a short
configuration of the same function). The schedule is deterministic per seed —
a failing soak replays exactly with the same ``--seed``.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from hivemind_tpu.hivemind_cli.run_blackbox import reconstruct_final_round
from hivemind_tpu.resilience import CHAOS, INJECTION_POINTS, reset_all_boards
from hivemind_tpu.telemetry import REGISTRY
from hivemind_tpu.telemetry.blackbox import BlackBox, read_spool
from hivemind_tpu.telemetry.device import (
    arm_device_telemetry,
    device_snapshot,
    disarm_device_telemetry,
)
from hivemind_tpu.telemetry.ledger import LEDGER
from hivemind_tpu.telemetry.tracing import RECORDER, thread_current_span
from hivemind_tpu.telemetry.watchdog import watchdog_summary
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# faults are proportionate, not apocalyptic: the paper's claim is surviving an
# UNRELIABLE swarm, not a dead one — each point sees regular drops/delays/aborts
DEFAULT_SCHEDULE = (
    ("p2p.unary.send", "drop", dict(prob=0.04)),
    ("p2p.unary.recv", "delay", dict(prob=0.05, delay=0.15)),
    ("p2p.stream.send", "delay", dict(prob=0.03, delay=0.1)),
    ("p2p.stream.recv", "drop", dict(prob=0.01)),
    ("dht.rpc_ping", "drop", dict(prob=0.1)),
    ("dht.rpc_store", "drop", dict(prob=0.15)),
    ("dht.rpc_find", "drop", dict(prob=0.15)),
    ("allreduce.setup", "abort", dict(prob=0.05)),
    ("allreduce.load", "delay", dict(prob=0.05, delay=0.25)),
    ("allreduce.reduce", "abort", dict(prob=0.02)),
    ("moe.forward", "drop", dict(prob=0.25)),
    ("moe.backward", "drop", dict(prob=0.25)),
    # the recovery path under fire (ISSUE 7): corrupted donor payloads must be
    # caught by digest verification, dropped streams must resume via failover
    ("state.download.send", "corrupt_payload", dict(prob=0.2)),
    ("state.download.recv", "drop", dict(prob=0.1)),
)


def arm_default_schedule(seed: int) -> None:
    CHAOS.clear()
    CHAOS.reseed(seed)
    for point, action, kwargs in DEFAULT_SCHEDULE:
        CHAOS.add_rule(point, action, **kwargs)


def _toy_problem(seed: int = 0):
    import numpy as np

    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    true_w = rng.randn(8).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    from hivemind_tpu.utils.profiling import tracked_jit

    @tracked_jit(site="chaos_soak.loss_and_grad")
    def loss_and_grad(params, x, y):
        return jax.value_and_grad(lambda p: jnp.mean((x @ p["w"] - y) ** 2))(params)

    return features, targets, loss_and_grad


def run_soak(
    n_peers: int = 4,
    duration: float = 60.0,
    seed: int = 0,
    chaos_fraction: float = 0.6,
    include_moe: bool = True,
    spec: Optional[str] = None,
    churn: bool = False,
    churn_kills: Optional[int] = None,
    checkpoint_root: Optional[str] = None,
    blackbox_root: Optional[str] = None,
) -> dict:
    """Run the soak; returns a JSON-able report with an ``ok`` verdict.

    With ``churn=True``, ``churn_kills`` peers (default ``max(1, n_peers // 3)``;
    never peer 0, which anchors the DHT bootstrap and the download prober) are
    crash-killed on a seeded schedule inside the chaos window and restarted a few
    seconds later with the same local checkpoint directory.

    Every peer writes a black-box spool under ``blackbox_root`` (ISSUE 17;
    defaults to a tempdir when churn is on). A churn kill abandons the
    victim's spool exactly as a kill-9 would — active segment unpublished,
    torn tail and all — and the verdict then also requires
    ``postmortem_reconstructed``: the victim's final round and its last
    in-flight span rebuilt from that spool by the ``hivemind-blackbox``
    machinery.
    """
    import random as random_module

    import numpy as np
    import optax

    import jax.numpy as jnp

    from hivemind_tpu.averaging.state_sync import (
        _STATE_SYNC_DIGEST_FAILURES,
        _STATE_SYNC_UNVERIFIED,
    )
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.optim import Optimizer

    report: Dict[str, object] = {
        "n_peers": n_peers, "duration": duration, "seed": seed, "churn": churn, "errors": [],
    }
    reset_all_boards()
    # arm the flight recorder for THIS soak: a fresh ring means every chaos
    # span event found at verdict time was injected by this run (ISSUE 4: the
    # chaos engine and the tracer must provably connect)
    RECORDER.clear()
    # same for the round ledger (ISSUE 8): every record + straggler attribution
    # found at verdict time was produced under this soak's rounds
    LEDGER.clear()
    # device-side observability (ISSUE 19): compile/memory events spool into
    # every peer's black box, so a victim's corpse carries its last device state
    arm_device_telemetry()

    def _total_watchdog_stalls() -> float:
        metric = REGISTRY.get("hivemind_event_loop_stalls_total")
        return sum(child.value for _key, child in metric.series()) if metric is not None else 0.0
    digest_failures_before = _STATE_SYNC_DIGEST_FAILURES.value(site="download")
    unverified_before = _STATE_SYNC_UNVERIFIED.value()
    # the soak's recovery window is short: expert breakers must be probeable
    # within it (the production default is restored in the outer finally)
    original_expert_recovery = EXPERT_BREAKERS._kwargs["recovery_time"]
    EXPERT_BREAKERS.reconfigure(recovery_time=4.0)

    # ------------------------------------------------------------ swarm
    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts: List[DHT] = [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(n_peers - 1)]

    checkpoint_dir_ctx = None
    if churn and checkpoint_root is None:
        checkpoint_dir_ctx = tempfile.TemporaryDirectory(prefix="chaos_soak_ckpt_")
        checkpoint_root = checkpoint_dir_ctx.name
    blackbox_dir_ctx = None
    if churn and blackbox_root is None:
        blackbox_dir_ctx = tempfile.TemporaryDirectory(prefix="chaos_soak_blackbox_")
        blackbox_root = blackbox_dir_ctx.name

    server = None
    moe_stats = {"ok_during": 0, "ok_after": 0, "calls": 0}
    stop_event = threading.Event()
    chaos_off_event = threading.Event()
    errors: List[str] = []
    step_counts: Dict[int, int] = {index: 0 for index in range(n_peers)}
    epochs: Dict[int, int] = {index: 0 for index in range(n_peers)}

    class _TrainerSlot:
        def __init__(self, index: int, dht: DHT, restarts: int = 0):
            self.index = index
            self.dht = dht
            self.kill = threading.Event()  # crash simulation: NO clean shutdown
            self.opt = None
            self.thread: Optional[threading.Thread] = None
            self.restarts = restarts
            self.box: Optional[BlackBox] = None
            self.spool_dir: Optional[str] = None
            if blackbox_root is not None:
                # one spool per peer INCARNATION: a restart writes a fresh
                # directory, so the dead incarnation's spool stays exactly as
                # the crash left it (the post-mortem's evidence)
                suffix = f"-r{restarts}" if restarts else ""
                self.spool_dir = f"{blackbox_root}/peer{index}{suffix}"
                self.box = BlackBox(
                    self.spool_dir, peer=f"peer{index}", peer_filter=str(dht.peer_id)
                )

    slots: Dict[int, _TrainerSlot] = {index: _TrainerSlot(index, dht) for index, dht in enumerate(dhts)}
    dead_peer_ids: List[str] = []  # breakers for these ids legitimately stay open
    retired_threads: List[threading.Thread] = []  # crash-killed trainers, still joined at exit
    victim_spools: List[Dict[str, object]] = []  # abandoned spool dirs, one per kill

    features, targets, loss_and_grad = _toy_problem(seed)

    def run_trainer(slot: _TrainerSlot) -> None:
        try:
            opt = Optimizer(
                dht=slot.dht, run_id="chaos_soak", target_batch_size=64,
                params={"w": jnp.zeros(8, jnp.float32)}, optimizer=optax.sgd(0.2),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=20,
                average_state_every=1, target_group_size=2, verbose=False,
                load_state_timeout=15,
                checkpoint_dir=(
                    f"{checkpoint_root}/peer{slot.index}" if checkpoint_root is not None else None
                ),
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            slot.opt = opt
            rng_local = np.random.RandomState(slot.index + 101 * slot.restarts)
            while not stop_event.is_set() and not slot.kill.is_set():
                batch = rng_local.choice(len(features), 16)
                _loss, grads = loss_and_grad(opt.params, features[batch], targets[batch])
                opt.step(grads)
                step_counts[slot.index] += 1
                epochs[slot.index] = opt.local_epoch
                time.sleep(0.25)
            if slot.kill.is_set():
                return  # kill -9 semantics: no opt.shutdown(), declarations left dangling
            opt.shutdown()
        except Exception as e:
            if slot.kill.is_set():
                return  # expected: the DHT was yanked out from under a live step
            errors.append(f"trainer {slot.index}: {e!r}")

    def run_moe_client(client_dht: DHT, expert_uids) -> None:
        from hivemind_tpu.moe import RemoteExpert, get_experts
        from hivemind_tpu.moe.client.call_many import RemoteCallMany

        try:
            infos = get_experts(client_dht, list(expert_uids))
            experts = [RemoteExpert(info, client_dht.node.p2p) for info in infos if info is not None]
            if not experts:
                errors.append("moe client: no experts resolved")
                return
            x = np.random.RandomState(seed).randn(2, 16).astype(np.float32)
            while not stop_event.is_set():
                moe_stats["calls"] += 1
                try:
                    rcm = RemoteCallMany([experts], k_min=0, forward_timeout=10.0)
                    outputs, alive = rcm._forward_np(x)
                    if np.asarray(alive).any():
                        key = "ok_after" if chaos_off_event.is_set() else "ok_during"
                        moe_stats[key] += 1
                        grad = np.ones_like(outputs)
                        rcm._backward_np(x, grad, alive)
                except Exception as e:
                    logger.debug(f"moe soak call failed: {e!r}")
                time.sleep(0.5)
        except Exception as e:
            errors.append(f"moe client: {e!r}")

    def run_pinger() -> None:
        """Steady-state swarms barely ping (it is a bootstrap/staleness RPC): a
        light probe loop keeps the dht.rpc_ping injection point exercised."""

        async def ping_one_neighbor(_dht, node):
            contacts = list(node.protocol.routing_table.iter_nodes())
            if contacts:
                await node.protocol.call_ping(contacts[0][1].peer_id)

        while not stop_event.is_set():
            for slot in slots.values():
                if slot.kill.is_set():
                    continue
                try:
                    slot.dht.run_coroutine(ping_one_neighbor)
                except Exception as e:
                    logger.debug(f"soak pinger: {e!r}")
            time.sleep(1.0)

    def run_downloader() -> None:
        """Periodic verified state downloads keep the state.download.* injection
        points exercised even before any peer falls behind: the prober pulls the
        trainers' shared state exactly the way a joining peer would."""
        from hivemind_tpu.averaging.averager import DecentralizedAverager

        async def _probe(_dht, _node):
            p2p = await _dht.replicate_p2p()
            return await DecentralizedAverager._download_verified_async(
                _dht, p2p, "chaos_soak_state", exclude_peer_id=_dht.peer_id, timeout=6.0
            )

        while not stop_event.is_set():
            slot = slots[0]  # never churn-killed: its DHT outlives the soak
            try:
                slot.dht.run_coroutine(_probe)
            except Exception as e:
                logger.debug(f"soak downloader: {e!r}")
            for _ in range(4):
                if stop_event.is_set():
                    return
                time.sleep(0.5)

    def _spawn_joined_dht(rng) -> Optional[DHT]:
        """A fresh DHT that actually JOINED the swarm: with chaos dropping DHT
        RPCs, a single bootstrap attempt can fail silently and leave the node
        isolated forever (empty routing table) — a rebooted machine would retry
        its bootstrap too, so the churn restart does."""

        async def _table_size(_dht, node):
            return len(list(node.protocol.routing_table.iter_nodes()))

        for _attempt in range(6):
            candidate = None
            try:
                # construction itself throws when chaos eats the bootstrap pings
                candidate = DHT(initial_peers=maddrs, start=True)
                if candidate.run_coroutine(_table_size) > 0:
                    return candidate
            except Exception as e:
                logger.debug(f"churn bootstrap attempt failed: {e!r}")
            if candidate is not None:
                candidate.shutdown()
            if stop_event.wait(rng.uniform(0.5, 1.5)):
                return None
        return None

    def run_churn(chaos_window: float) -> None:
        """Seeded kill/restart schedule: each kill yanks the victim's DHT with no
        shutdown (mid-round, possibly mid-download for its downloaders), then
        restarts the peer on a fresh DHT with the same checkpoint directory."""
        rng = random_module.Random(seed + 0xC0FFEE)
        kills = churn_kills if churn_kills is not None else max(1, n_peers // 3)
        kill_times = sorted(rng.uniform(0.25, 0.7) * chaos_window for _ in range(kills))
        start = time.monotonic()
        # peer 0 anchors the DHT bootstrap + download prober; the last peer's DHT
        # is the MoE client's transport — killing it would orphan the client's
        # RemoteExperts for the rest of the soak and fail moe_recovered
        last_victim = n_peers - 1 if include_moe else n_peers
        victims = [index for index in range(1, last_victim)]
        if not victims:
            errors.append("churn: no eligible victims (need more peers for this configuration)")
            return
        for kill_time in kill_times:
            delay = start + kill_time - time.monotonic()
            if delay > 0:
                if stop_event.wait(delay):
                    return
            candidates = [i for i in victims if not slots[i].kill.is_set()]
            # quorum counts LIVE slots (restarted peers are alive again) — the
            # cumulative dead_peer_ids list exists for breaker bookkeeping only
            live = sum(1 for slot in slots.values() if not slot.kill.is_set())
            if len(candidates) < 1 or live <= 2:
                continue  # keep a quorum able to form groups
            index = rng.choice(candidates)
            slot = slots[index]
            # die MID-OPERATION when possible: wait (bounded) until the victim's
            # trainer thread has a span open, so the abandoned spool holds a
            # span_start with no finish — the post-mortem's "died inside this
            # operation" evidence (a real crash overwhelmingly lands mid-step;
            # the 0.25 s inter-step sleep is the only quiet window)
            mid_span_deadline = time.monotonic() + 5.0
            while (
                time.monotonic() < mid_span_deadline
                and not stop_event.is_set()
                and (slot.thread is None or thread_current_span(slot.thread.ident) is None)
            ):
                time.sleep(0.05)
            logger.warning(f"churn: crash-killing trainer {index}")
            slot.kill.set()
            victim_peer_id = None
            try:
                victim_peer_id = str(slot.dht.peer_id)  # unreadable once shut down
                dead_peer_ids.append(victim_peer_id)
                slot.dht.shutdown()  # the "power cord": transport dies instantly
            except Exception as e:
                logger.debug(f"churn kill {index}: {e!r}")
            if slot.box is not None:
                # kill-9 the spool too: unsubscribe without publishing — the
                # .open segment stays on disk exactly as the dead peer left it
                slot.box.abandon()
                victim_spools.append(
                    {"index": index, "dir": slot.spool_dir, "peer_id": victim_peer_id}
                )
            if stop_event.wait(rng.uniform(2.0, 4.0)):
                return
            logger.warning(f"churn: restarting trainer {index}")
            try:
                new_dht = _spawn_joined_dht(rng)
            except Exception as e:
                errors.append(f"churn restart {index}: {e!r}")
                continue
            if new_dht is None:
                if not stop_event.is_set():
                    errors.append(f"churn restart {index}: could not rejoin the swarm")
                continue
            new_slot = _TrainerSlot(index, new_dht, restarts=slot.restarts + 1)
            if slot.thread is not None:
                retired_threads.append(slot.thread)
            slots[index] = new_slot
            new_slot.thread = threading.Thread(target=run_trainer, args=(new_slot,))
            new_slot.thread.start()

    threads: List[threading.Thread] = []
    try:
        try:
            if include_moe:
                from hivemind_tpu.moe import Server

                expert_uids = ("soak_expert.0", "soak_expert.1")
                server = Server.create(
                    expert_uids=list(expert_uids), expert_cls="ffn", hidden_dim=16,
                    dht=dhts[0], start=True, max_batch_size=64,
                    optim_factory=lambda: optax.sgd(1e-3),
                )
                time.sleep(1.0)  # let the experts land in the DHT
                threads.append(threading.Thread(target=run_moe_client, args=(dhts[-1], expert_uids)))

            threads.append(threading.Thread(target=run_pinger))
            threads.append(threading.Thread(target=run_downloader))
            for slot in slots.values():
                slot.thread = threading.Thread(target=run_trainer, args=(slot,))
            trainer_threads_initial = [slots[index].thread for index in range(n_peers)]
            for thread in threads + trainer_threads_initial:
                thread.start()

            # phase 1: faults armed (and, with --churn, peers dying)
            if spec:
                CHAOS.configure(spec, seed=seed)
            else:
                arm_default_schedule(seed)
            chaos_window = duration * chaos_fraction
            churn_thread = None
            if churn:
                churn_thread = threading.Thread(target=run_churn, args=(chaos_window,))
                churn_thread.start()
            time.sleep(chaos_window)
            steps_at_chaos_end = dict(step_counts)
            report["chaos_stats"] = CHAOS.stats()
            points_exercised = {rule.point for rule in CHAOS.rules if rule.calls > 0}
            # count injected faults visible in the trace NOW, before the
            # recovery phase's spans can evict the chaos-era ones from the ring
            chaos_span_events = sum(
                sum(1 for _t, name, _a in span.events or () if name.startswith("chaos."))
                for span in RECORDER.snapshot()
            )
            report["chaos_span_events"] = chaos_span_events
            # ledger verdict inputs, read NOW while every record is chaos-era
            # (ISSUE 8): the chaos-delay schedule must have produced at least
            # one straggler attribution — a partner named slowest in a record
            # AND actually slow. The slowness floor keeps the check from being
            # vacuous: every round with a remote exchange names SOME slowest
            # peer, so bare existence would pass even with no delay rule armed.
            # 0.1 s is the smallest delay in DEFAULT_SCHEDULE, ~2x a healthy
            # toy-round exchange on this swarm.
            chaos_ledger_records = LEDGER.records()
            report["ledger_rounds_under_chaos"] = len(chaos_ledger_records)
            straggler_floor_s = 0.1
            report["straggler_attributions_under_chaos"] = sum(
                1 for record in chaos_ledger_records
                if record.get("slowest_peer")
                and float(record.get("slowest_s", 0.0)) >= straggler_floor_s
            )
            CHAOS.clear()
            chaos_off_event.set()
            # the disarmed-phase watchdog baseline: any stall counted from here
            # on happened with NO faults armed — a real bug, not injected noise
            stalls_at_disarm = _total_watchdog_stalls()
            logger.warning("chaos window over: faults disarmed, watching recovery")

            # phase 2: recovery. The base window is fixed; with churn, a BOUNDED
            # extra wait runs only while a restarted peer still lags the swarm —
            # on a loaded 1-core CI box, averaging rounds stretch to their full
            # timeouts and a fixed window flakes on liveness the peer is already
            # in the middle of demonstrating
            time.sleep(duration - chaos_window)
            if churn_thread is not None:
                churn_thread.join(timeout=60)

            def _swarm_global_epoch() -> int:
                best = 0
                for slot in slots.values():
                    if slot.opt is not None and not slot.kill.is_set():
                        try:
                            best = max(best, slot.opt.tracker.global_epoch)
                        except Exception:
                            continue
                return best

            def _lagging_restarts() -> List[int]:
                # the SAME swarm-wide view the verdict uses — a restarted peer's
                # own tracker can lag the survivors' by an epoch under load, and
                # waiting on the wrong view flakes the verdict
                global_now = _swarm_global_epoch()
                return [
                    index for index, slot in slots.items()
                    if slot.restarts > 0
                    and (slot.opt is None or slot.opt.local_epoch < global_now - 1)
                ]

            if churn:
                extra_deadline = time.monotonic() + max(30.0, duration - chaos_window)
                while time.monotonic() < extra_deadline and _lagging_restarts():
                    time.sleep(1.0)

            # final swarm view BEFORE teardown: the restarted peers' verdict is
            # measured against the tracker's global epoch, not a local guess
            final_global_epoch = _swarm_global_epoch()
        finally:
            stop_event.set()
            live_threads = [slot.thread for slot in slots.values() if slot.thread is not None]
            for thread in threads + live_threads + retired_threads:
                thread.join(timeout=60)
            if server is not None:
                server.shutdown()
            for slot in slots.values():
                if slot.box is not None:
                    slot.box.close()  # survivors publish cleanly; victims were abandoned
                if not slot.kill.is_set():
                    slot.dht.shutdown()

        # ------------------------------------------------------------ verdict
        tripped = {}
        for index, slot in slots.items():
            if slot.kill.is_set():
                continue
            try:
                blacklist = slot.dht.node.blacklist
            except Exception:
                continue
            open_keys = [str(key) for key in blacklist.tripped_keys()]
            # a breaker held open against a peer we crash-killed (and whose old
            # identity never came back) is the breaker WORKING, not a failure
            open_keys = [key for key in open_keys if key not in dead_peer_ids]
            tripped[f"dht_blacklist[{index}]"] = open_keys
        tripped["moe_expert"] = [str(key) for key in EXPERT_BREAKERS.tripped_keys()]

        total_injections = sum(report.get("chaos_stats", {}).values())
        missed_points = sorted(
            point for point in INJECTION_POINTS
            if point not in points_exercised
            and (include_moe or not point.startswith("moe."))
        )
        steps_after_chaos = {
            index: step_counts[index] - steps_at_chaos_end.get(index, 0) for index in step_counts
        }

        restarted = {index: slot for index, slot in slots.items() if slot.restarts > 0}
        restart_report = {}
        for index, slot in restarted.items():
            # read the LIVE optimizer, not the per-step snapshot: a peer deep in
            # a slow averaging round has advanced past its last-reported epoch
            local_epoch = slot.opt.local_epoch if slot.opt is not None else 0
            restart_report[index] = {
                "restarts": slot.restarts,
                "final_epoch": local_epoch,
                "global_epoch": final_global_epoch,
                # one-epoch grace is inherent to the protocol: a peer at
                # global-1 transitions itself on its next ready step
                "recovered": local_epoch >= final_global_epoch - 1 and local_epoch > 0,
            }
        digest_failures = _STATE_SYNC_DIGEST_FAILURES.value(site="download") - digest_failures_before
        digest_failures_adopted = _STATE_SYNC_UNVERIFIED.value() - unverified_before
        stalls_while_disarmed = _total_watchdog_stalls() - stalls_at_disarm
        report["watchdog"] = watchdog_summary()
        report["watchdog_stalls_while_disarmed"] = stalls_while_disarmed
        report["ledger_summary"] = LEDGER.summary()
        report["device"] = device_snapshot()

        # post-mortem (ISSUE 17): every kill -9'd victim left an unpublished
        # ``.open`` spool behind; rebuild its final round from the corpse with
        # the same reader hivemind-blackbox uses. Reconstruction must name the
        # span the victim died inside — a spool that only shows cleanly
        # finished work means the recorder was not crash-durable.
        postmortems: Dict[str, Dict[str, object]] = {}
        for entry in victim_spools:
            spool_dir = str(entry["dir"])
            try:
                frames, spool_stats = read_spool(spool_dir)
                post = reconstruct_final_round(frames, spool_stats)
            except Exception as exc:  # a corrupt corpse is a finding, not a crash
                postmortems[spool_dir] = {"error": repr(exc), "reconstructed": False}
                continue
            final_round = post.get("final_round") or {}
            in_flight = post.get("last_in_flight") or {}
            device_frames = sum(1 for frame in frames if frame.get("k") == "device")
            postmortems[spool_dir] = {
                "peer": f"peer{entry['index']}",
                "frames": spool_stats.get("frames", 0),
                "device_frames": device_frames,
                "torn_tail": spool_stats.get("torn_tail", 0),
                "corrupt": spool_stats.get("corrupt", 0),
                "final_round": final_round.get("round"),
                "final_round_slowest": final_round.get("slowest_peer"),
                "last_in_flight_span": in_flight.get("name"),
                "open_spans": post.get("open_spans", 0),
                "reconstructed": bool(post.get("reconstructed"))
                and in_flight.get("name") is not None,
            }
        report["postmortems"] = postmortems
        if blackbox_root is not None:
            report["blackbox_root"] = blackbox_root

        report.update(
            steps=dict(step_counts),
            steps_after_chaos=steps_after_chaos,
            epochs=dict(epochs),
            moe=dict(moe_stats),
            breakers_still_tripped={name: keys for name, keys in tripped.items() if keys},
            missed_points=missed_points,
            total_injections=total_injections,
            digest_failures=digest_failures,
            digest_failures_adopted=digest_failures_adopted,
            restarts=restart_report,
            state_recovered=all(entry["recovered"] for entry in restart_report.values()),
            errors=errors,
        )

        checks = {
            "steps_advanced": all(count > 0 for count in step_counts.values()),
            "steps_advanced_after_chaos": all(count > 0 for count in steps_after_chaos.values()),
            "breakers_recovered": not report["breakers_still_tripped"],
            "every_point_exercised": not missed_points,
            "faults_injected": total_injections >= 10,
            # the loop between the chaos engine and the flight recorder: at
            # least one injected fault must be visible as a span event
            "chaos_visible_in_trace": report.get("chaos_span_events", 0) >= 1,
            # corrupted payloads may be REJECTED (digest_failures > 0 is
            # expected under the corrupt_payload rule) but never ADOPTED
            "digest_failures_adopted_zero": digest_failures_adopted == 0,
            # attribution verdict (ISSUE 8): the chaos-delay phase must have
            # NAMED a slow partner in the round ledger...
            "straggler_attributed": report["straggler_attributions_under_chaos"] >= 1,
            # ...and a healthy, undisturbed swarm must not stall its loops —
            # a disarmed-phase stall is a real blocking bug the faults masked
            "watchdog_stalls_zero_disarmed": stalls_while_disarmed == 0,
            "no_thread_errors": not errors,
        }
        if include_moe:
            checks["moe_recovered"] = moe_stats["ok_after"] > 0
        if churn:
            checks["peers_restarted"] = bool(restart_report)
            checks["state_recovered"] = bool(report["state_recovered"]) and bool(restart_report)
            # the flight-recorder loop closed: at least one victim's final
            # round AND its dying in-flight span came back out of the spool
            checks["postmortem_reconstructed"] = bool(postmortems) and any(
                entry.get("reconstructed") for entry in postmortems.values()
            )
            # device telemetry is crash-durable too (ISSUE 19): at least one
            # victim's corpse must carry compile/memory frames
            checks["device_frames_in_victim_spool"] = bool(postmortems) and any(
                entry.get("device_frames", 0) > 0 for entry in postmortems.values()
            )
        report["checks"] = checks
        report["ok"] = all(checks.values())
        return report
    finally:
        # ALWAYS disarm and restore, even when setup or teardown raised: armed
        # chaos rules or a 4 s expert recovery window leaking past run_soak
        # would silently distort everything that runs later in the process
        CHAOS.clear()
        EXPERT_BREAKERS.reconfigure(recovery_time=original_expert_recovery)
        reset_all_boards()
        disarm_device_telemetry()
        if checkpoint_dir_ctx is not None:
            checkpoint_dir_ctx.cleanup()
        if blackbox_dir_ctx is not None:
            blackbox_dir_ctx.cleanup()


def run_serving_churn(
    duration: float = 45.0,
    seed: int = 0,
    n_experts: int = 2,
    stall_fraction: float = 0.25,
    kill_fraction: float = 0.45,
    restart_fraction: float = 0.7,
) -> dict:
    """Serving-churn soak (ISSUE 13): two servers replicate the same expert
    grid; mid-traffic one replica is first STALLED (its runtime suspended — the
    straggler that makes hedges fire) and then crash-killed (its DHT yanked, no
    shutdown), later restarted under a fresh identity. The verdict requires:

    - ``hedges_fired >= 1`` — the stall was hedged around, not waited out,
    - ``client_failures == 0`` — replica death is never client-visible
      (failover + hedging absorb it),
    - ``breakers_recovered`` — after the restart, no breaker is left open
      except against the dead identity (which never comes back),
    - ``post_restart_ok > 0`` and the resolved replica set includes the
      restarted server.
    """
    import numpy as np
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.telemetry.serving import SCORECARDS

    report: Dict[str, object] = {"duration": duration, "seed": seed, "mode": "serving_churn"}
    reset_all_boards()
    SCORECARDS.clear()
    original_recovery = EXPERT_BREAKERS._kwargs["recovery_time"]
    EXPERT_BREAKERS.reconfigure(recovery_time=3.0)
    uids = [f"srv_churn.{i}" for i in range(n_experts)]

    def hedge_counts() -> Dict[str, float]:
        metric = REGISTRY.get("hivemind_moe_hedge_total")
        if metric is None:
            return {}
        return {",".join(key): child.value for key, child in metric.series()}

    def failover_total() -> float:
        metric = REGISTRY.get("hivemind_moe_replica_failover_total")
        return sum(child.value for _k, child in metric.series()) if metric is not None else 0.0

    hedges_before = hedge_counts()
    failovers_before = failover_total()

    dht_a = DHT(start=True)
    maddrs = [str(m) for m in dht_a.get_visible_maddrs()]
    server_a = Server.create(
        expert_uids=uids, expert_cls="ffn", hidden_dim=16, dht=dht_a, start=True,
        max_batch_size=64, optim_factory=lambda: optax.sgd(1e-3),
    )
    dht_b = DHT(initial_peers=maddrs, start=True)
    server_b = Server.create(
        expert_uids=uids, expert_cls="ffn", hidden_dim=16, dht=dht_b, start=True,
        max_batch_size=64, optim_factory=lambda: optax.sgd(1e-3),
    )
    client_dht = DHT(initial_peers=maddrs, start=True)
    dead_peer_ids: List[str] = []

    stop_event = threading.Event()
    stats = {"ok": 0, "failures": 0, "post_restart_ok": 0}
    phase = {"name": "warm"}
    errors: List[str] = []

    def run_traffic() -> None:
        import numpy as _np

        try:
            infos = None
            for _attempt in range(30):
                infos = get_experts(client_dht, uids)
                if all(i is not None and len(i.replica_set) == 2 for i in infos):
                    break
                time.sleep(0.5)
            if infos is None or any(i is None for i in infos):
                errors.append("serving churn: experts never resolved")
                return
            experts = [RemoteExpert(info, client_dht.node.p2p) for info in infos]
            x = _np.random.RandomState(seed).randn(2, 16).astype(_np.float32)
            while not stop_event.is_set():
                for expert in experts:
                    try:
                        expert.forward_np(x)
                        stats["ok"] += 1
                        if phase["name"] == "restarted":
                            stats["post_restart_ok"] += 1
                    except Exception as e:
                        stats["failures"] += 1
                        errors.append(f"client-visible failure in {phase['name']}: {e!r}")
                time.sleep(0.05)
        except Exception as e:
            errors.append(f"traffic thread: {e!r}")

    traffic = threading.Thread(target=run_traffic)
    traffic.start()
    restarted_server = restarted_dht = None
    # placeholders until the victim is chosen at stall time (an early failure
    # cleans up one pair and leaves the other dangling, like the crash it is)
    survivor_server, survivor_dht = server_a, dht_a
    try:
        time.sleep(duration * stall_fraction)
        # the client's routing turns deterministic once scorecards warm
        # (measured replicas sort by mean latency), so by now traffic has
        # concentrated on ONE replica — the victim must be THAT replica, or
        # the stall lands on a server nobody dials and no hedge can fire
        def replica_requests(peer_b58: str) -> int:
            total = 0
            for uid in uids:
                card = SCORECARDS.card(uid) or {}
                entry = (card.get("replicas") or {}).get(peer_b58)
                if entry:
                    total += int(entry.get("requests", 0))
            return total

        victim_is_b = replica_requests(str(dht_b.peer_id)) >= replica_requests(str(dht_a.peer_id))
        victim_server, victim_dht = (server_b, dht_b) if victim_is_b else (server_a, dht_a)
        survivor_server, survivor_dht = (server_a, dht_a) if victim_is_b else (server_b, dht_b)
        victim_name = "B" if victim_is_b else "A"

        # phase 1: the victim becomes a straggler — its runtime stops draining,
        # so in-flight requests hang past p95 and the client must hedge
        phase["name"] = "stalled"
        logger.warning(f"serving churn: stalling replica {victim_name}'s runtime (hedge bait)")

        async def _stall():
            victim_server.runtime._task.cancel()

        victim_server._runner.run_coroutine(_stall(), return_future=True).result(5)
        time.sleep(duration * (kill_fraction - stall_fraction))

        # phase 2: crash-kill the victim (transport yanked, no clean shutdown —
        # its declarations dangle in the DHT like a real dead process's)
        phase["name"] = "killed"
        logger.warning(f"serving churn: crash-killing replica {victim_name}")
        dead_peer_ids.append(str(victim_dht.peer_id))
        victim_dht.shutdown()
        time.sleep(duration * (restart_fraction - kill_fraction))

        # phase 3: restart under a fresh identity; it re-declares the same uids
        phase["name"] = "restarting"
        logger.warning(f"serving churn: restarting replica {victim_name}")
        restarted_dht = DHT(initial_peers=maddrs, start=True)
        restarted_server = Server.create(
            expert_uids=uids, expert_cls="ffn", hidden_dim=16, dht=restarted_dht,
            start=True, max_batch_size=64, optim_factory=lambda: optax.sgd(1e-3),
        )
        time.sleep(2.0)
        phase["name"] = "restarted"
        time.sleep(max(duration * (1.0 - restart_fraction) - 2.0, 5.0))

        infos = get_experts(client_dht, uids)
        live_peers = {
            replica.peer_id.to_base58()
            for info in infos if info is not None
            for replica in info.replica_set
        }
        report["resolved_replicas"] = sorted(live_peers)
        restarted_visible = str(restarted_dht.peer_id) in live_peers
    finally:
        stop_event.set()
        traffic.join(timeout=30)

        hedges_after = hedge_counts()
        hedges_fired = hedges_after.get("fired", 0) - hedges_before.get("fired", 0)
        tripped = [
            str(key) for key in EXPERT_BREAKERS.tripped_keys()
            if not any(dead in str(key) for dead in dead_peer_ids)
        ]

        for component in (survivor_server, restarted_server):
            if component is not None:
                component.shutdown()
        for component in (survivor_dht, restarted_dht, client_dht):
            if component is not None:
                component.shutdown()
        EXPERT_BREAKERS.reconfigure(recovery_time=original_recovery)
        reset_all_boards()

    report.update(
        traffic=dict(stats),
        hedges_fired=hedges_fired,
        hedge_outcomes={k: hedges_after.get(k, 0) - hedges_before.get(k, 0) for k in hedges_after},
        replica_failovers=failover_total() - failovers_before,
        breakers_still_tripped=tripped,
        dead_peer_ids=dead_peer_ids,
        errors=errors,
    )
    checks = {
        "traffic_flowed": stats["ok"] > 0,
        "hedge_fired": hedges_fired >= 1,
        "zero_client_visible_failures": stats["failures"] == 0,
        "post_restart_ok": stats["post_restart_ok"] > 0,
        "restarted_replica_visible": bool(restarted_visible),
        "breakers_recovered": not tripped,
    }
    report["checks"] = checks
    report["ok"] = all(checks.values())
    return report


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--peers", type=int, default=4)
    parser.add_argument("--duration", type=float, default=60.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--chaos-fraction", type=float, default=0.6,
                        help="fraction of the soak spent with faults armed")
    parser.add_argument("--no-moe", action="store_true", help="skip the MoE server/client pair")
    parser.add_argument("--churn", action="store_true",
                        help="crash-kill and restart peers on a seeded schedule (ISSUE 7); "
                             "the verdict then requires state_recovered and zero unverified adoptions")
    parser.add_argument("--churn-kills", type=int, default=None,
                        help="how many kill/restart cycles (default: peers // 3, min 1)")
    parser.add_argument("--checkpoint-root", default=None,
                        help="directory for per-peer crash-safe checkpoints (default: a tempdir)")
    parser.add_argument("--blackbox-root", default=None,
                        help="directory for per-peer black-box spools (default: a tempdir under "
                             "--churn; pass a path to keep victim spools for hivemind-blackbox)")
    parser.add_argument("--spec", default=None,
                        help="HIVEMIND_CHAOS-grammar schedule overriding the default")
    parser.add_argument("--serving", action="store_true",
                        help="serving-churn phase (ISSUE 13): two replicas of one "
                             "expert grid, one stalled then crash-killed then "
                             "restarted mid-traffic; verdict requires >=1 hedge "
                             "fired, zero client-visible failures, breakers "
                             "recovered after the restart")
    args = parser.parse_args()
    if args.serving:
        report = run_serving_churn(duration=args.duration, seed=args.seed)
        print(json.dumps(report, indent=2, default=str))
        sys.exit(0 if report["ok"] else 1)
    report = run_soak(
        n_peers=args.peers, duration=args.duration, seed=args.seed,
        chaos_fraction=args.chaos_fraction, include_moe=not args.no_moe, spec=args.spec,
        churn=args.churn, churn_kills=args.churn_kills, checkpoint_root=args.checkpoint_root,
        blackbox_root=args.blackbox_root,
    )
    print(json.dumps(report, indent=2, default=str))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
