"""Chaos soak (ISSUE 3 acceptance): a small in-process swarm trains under a
seeded fault schedule covering every named injection point, then the faults
stop and the soak asserts the swarm LIVED through it:

- every peer's optimizer step count (and epoch) keeps advancing,
- the MoE client keeps getting expert responses after the faults stop,
- every circuit breaker tripped during the storm returns to closed,
- every named injection point actually saw traffic.

Run it::

    python -m hivemind_tpu.hivemind_cli.run_chaos_soak --peers 4 --duration 60

or programmatically via :func:`run_soak` (the chaos-marked tests use a short
configuration of the same function). The schedule is deterministic per seed —
a failing soak replays exactly with the same ``--seed``.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional

from hivemind_tpu.resilience import CHAOS, INJECTION_POINTS, reset_all_boards
from hivemind_tpu.telemetry.tracing import RECORDER
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# faults are proportionate, not apocalyptic: the paper's claim is surviving an
# UNRELIABLE swarm, not a dead one — each point sees regular drops/delays/aborts
DEFAULT_SCHEDULE = (
    ("p2p.unary.send", "drop", dict(prob=0.04)),
    ("p2p.unary.recv", "delay", dict(prob=0.05, delay=0.15)),
    ("p2p.stream.send", "delay", dict(prob=0.03, delay=0.1)),
    ("p2p.stream.recv", "drop", dict(prob=0.01)),
    ("dht.rpc_ping", "drop", dict(prob=0.1)),
    ("dht.rpc_store", "drop", dict(prob=0.15)),
    ("dht.rpc_find", "drop", dict(prob=0.15)),
    ("allreduce.setup", "abort", dict(prob=0.05)),
    ("allreduce.load", "delay", dict(prob=0.05, delay=0.25)),
    ("allreduce.reduce", "abort", dict(prob=0.02)),
    ("moe.forward", "drop", dict(prob=0.25)),
    ("moe.backward", "drop", dict(prob=0.25)),
)


def arm_default_schedule(seed: int) -> None:
    CHAOS.clear()
    CHAOS.reseed(seed)
    for point, action, kwargs in DEFAULT_SCHEDULE:
        CHAOS.add_rule(point, action, **kwargs)


def _toy_problem(seed: int = 0):
    import numpy as np

    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    true_w = rng.randn(8).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    @jax.jit
    def loss_and_grad(params, x, y):
        return jax.value_and_grad(lambda p: jnp.mean((x @ p["w"] - y) ** 2))(params)

    return features, targets, loss_and_grad


def run_soak(
    n_peers: int = 4,
    duration: float = 60.0,
    seed: int = 0,
    chaos_fraction: float = 0.6,
    include_moe: bool = True,
    spec: Optional[str] = None,
) -> dict:
    """Run the soak; returns a JSON-able report with an ``ok`` verdict."""
    import numpy as np
    import optax

    import jax.numpy as jnp

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.optim import Optimizer

    report: Dict[str, object] = {
        "n_peers": n_peers, "duration": duration, "seed": seed, "errors": [],
    }
    reset_all_boards()
    # arm the flight recorder for THIS soak: a fresh ring means every chaos
    # span event found at verdict time was injected by this run (ISSUE 4: the
    # chaos engine and the tracer must provably connect)
    RECORDER.clear()
    # the soak's recovery window is short: expert breakers must be probeable
    # within it (the production default is restored in the outer finally)
    original_expert_recovery = EXPERT_BREAKERS._kwargs["recovery_time"]
    EXPERT_BREAKERS.reconfigure(recovery_time=4.0)

    # ------------------------------------------------------------ swarm
    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts: List[DHT] = [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(n_peers - 1)]

    server = None
    moe_stats = {"ok_during": 0, "ok_after": 0, "calls": 0}
    stop_event = threading.Event()
    chaos_off_event = threading.Event()
    errors: List[str] = []
    step_counts: Dict[int, int] = {index: 0 for index in range(n_peers)}
    epochs: Dict[int, int] = {index: 0 for index in range(n_peers)}

    features, targets, loss_and_grad = _toy_problem(seed)

    def run_trainer(index: int, dht: DHT) -> None:
        try:
            opt = Optimizer(
                dht=dht, run_id="chaos_soak", target_batch_size=64,
                params={"w": jnp.zeros(8, jnp.float32)}, optimizer=optax.sgd(0.2),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=20,
                average_state_every=1, target_group_size=2, verbose=False,
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            rng_local = np.random.RandomState(index)
            while not stop_event.is_set():
                batch = rng_local.choice(len(features), 16)
                _loss, grads = loss_and_grad(opt.params, features[batch], targets[batch])
                opt.step(grads)
                step_counts[index] += 1
                epochs[index] = opt.local_epoch
                time.sleep(0.25)
            opt.shutdown()
        except Exception as e:
            errors.append(f"trainer {index}: {e!r}")

    def run_moe_client(client_dht: DHT, expert_uids) -> None:
        from hivemind_tpu.moe import RemoteExpert, get_experts
        from hivemind_tpu.moe.client.call_many import RemoteCallMany

        try:
            infos = get_experts(client_dht, list(expert_uids))
            experts = [RemoteExpert(info, client_dht.node.p2p) for info in infos if info is not None]
            if not experts:
                errors.append("moe client: no experts resolved")
                return
            x = np.random.RandomState(seed).randn(2, 16).astype(np.float32)
            while not stop_event.is_set():
                moe_stats["calls"] += 1
                try:
                    rcm = RemoteCallMany([experts], k_min=0, forward_timeout=10.0)
                    outputs, alive = rcm._forward_np(x)
                    if np.asarray(alive).any():
                        key = "ok_after" if chaos_off_event.is_set() else "ok_during"
                        moe_stats[key] += 1
                        grad = np.ones_like(outputs)
                        rcm._backward_np(x, grad, alive)
                except Exception as e:
                    logger.debug(f"moe soak call failed: {e!r}")
                time.sleep(0.5)
        except Exception as e:
            errors.append(f"moe client: {e!r}")

    def run_pinger() -> None:
        """Steady-state swarms barely ping (it is a bootstrap/staleness RPC): a
        light probe loop keeps the dht.rpc_ping injection point exercised."""

        async def ping_one_neighbor(_dht, node):
            contacts = list(node.protocol.routing_table.iter_nodes())
            if contacts:
                await node.protocol.call_ping(contacts[0][1].peer_id)

        while not stop_event.is_set():
            for dht in dhts:
                try:
                    dht.run_coroutine(ping_one_neighbor)
                except Exception as e:
                    logger.debug(f"soak pinger: {e!r}")
            time.sleep(1.0)

    threads: List[threading.Thread] = []
    try:
        try:
            if include_moe:
                from hivemind_tpu.moe import Server

                expert_uids = ("soak_expert.0", "soak_expert.1")
                server = Server.create(
                    expert_uids=list(expert_uids), expert_cls="ffn", hidden_dim=16,
                    dht=dhts[0], start=True, max_batch_size=64,
                    optim_factory=lambda: optax.sgd(1e-3),
                )
                time.sleep(1.0)  # let the experts land in the DHT
                threads.append(threading.Thread(target=run_moe_client, args=(dhts[-1], expert_uids)))

            threads.append(threading.Thread(target=run_pinger))
            threads.extend(
                threading.Thread(target=run_trainer, args=(index, dht))
                for index, dht in enumerate(dhts)
            )
            for thread in threads:
                thread.start()

            # phase 1: faults armed
            if spec:
                CHAOS.configure(spec, seed=seed)
            else:
                arm_default_schedule(seed)
            chaos_window = duration * chaos_fraction
            time.sleep(chaos_window)
            steps_at_chaos_end = dict(step_counts)
            report["chaos_stats"] = CHAOS.stats()
            points_exercised = {rule.point for rule in CHAOS.rules if rule.calls > 0}
            # count injected faults visible in the trace NOW, before the
            # recovery phase's spans can evict the chaos-era ones from the ring
            chaos_span_events = sum(
                sum(1 for _t, name, _a in span.events or () if name.startswith("chaos."))
                for span in RECORDER.snapshot()
            )
            report["chaos_span_events"] = chaos_span_events
            CHAOS.clear()
            chaos_off_event.set()
            logger.warning("chaos window over: faults disarmed, watching recovery")

            # phase 2: recovery
            time.sleep(duration - chaos_window)
        finally:
            stop_event.set()
            for thread in threads:
                thread.join(timeout=60)
            if server is not None:
                server.shutdown()
            for dht in dhts:
                dht.shutdown()

        # ------------------------------------------------------------ verdict
        tripped = {}
        for index, dht in enumerate(dhts):
            try:
                blacklist = dht.node.blacklist
            except Exception:
                continue
            tripped[f"dht_blacklist[{index}]"] = [str(key) for key in blacklist.tripped_keys()]
        tripped["moe_expert"] = [str(key) for key in EXPERT_BREAKERS.tripped_keys()]

        total_injections = sum(report.get("chaos_stats", {}).values())
        missed_points = sorted(
            point for point in INJECTION_POINTS
            if point not in points_exercised
            and (include_moe or not point.startswith("moe."))
        )
        steps_after_chaos = {
            index: step_counts[index] - steps_at_chaos_end.get(index, 0) for index in step_counts
        }

        report.update(
            steps=dict(step_counts),
            steps_after_chaos=steps_after_chaos,
            epochs=dict(epochs),
            moe=dict(moe_stats),
            breakers_still_tripped={name: keys for name, keys in tripped.items() if keys},
            missed_points=missed_points,
            total_injections=total_injections,
            errors=errors,
        )

        checks = {
            "steps_advanced": all(count > 0 for count in step_counts.values()),
            "steps_advanced_after_chaos": all(count > 0 for count in steps_after_chaos.values()),
            "breakers_recovered": not report["breakers_still_tripped"],
            "every_point_exercised": not missed_points,
            "faults_injected": total_injections >= 10,
            # the loop between the chaos engine and the flight recorder: at
            # least one injected fault must be visible as a span event
            "chaos_visible_in_trace": report.get("chaos_span_events", 0) >= 1,
            "no_thread_errors": not errors,
        }
        if include_moe:
            checks["moe_recovered"] = moe_stats["ok_after"] > 0
        report["checks"] = checks
        report["ok"] = all(checks.values())
        return report
    finally:
        # ALWAYS disarm and restore, even when setup or teardown raised: armed
        # chaos rules or a 4 s expert recovery window leaking past run_soak
        # would silently distort everything that runs later in the process
        CHAOS.clear()
        EXPERT_BREAKERS.reconfigure(recovery_time=original_expert_recovery)
        reset_all_boards()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--peers", type=int, default=4)
    parser.add_argument("--duration", type=float, default=60.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--chaos-fraction", type=float, default=0.6,
                        help="fraction of the soak spent with faults armed")
    parser.add_argument("--no-moe", action="store_true", help="skip the MoE server/client pair")
    parser.add_argument("--spec", default=None,
                        help="HIVEMIND_CHAOS-grammar schedule overriding the default")
    args = parser.parse_args()
    report = run_soak(
        n_peers=args.peers, duration=args.duration, seed=args.seed,
        chaos_fraction=args.chaos_fraction, include_moe=not args.no_moe, spec=args.spec,
    )
    print(json.dumps(report, indent=2, default=str))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
