"""Run the native relay daemon AND advertise it in the swarm's DHT — the complete
relay-operator story for zero-config auto-relay (reference role: public peers
with relay enabled, p2p_daemon.py use_relay; here the relay is the C++ daemon
`hivemind_tpu/native/relay_daemon.cpp` and discovery rides `p2p/autorelay.py`).

    python -m hivemind_tpu.hivemind_cli.run_relay \
        --initial_peers /ip4/…/tcp/…/p2p/Qm… \
        --relay_port 34000 --announce_host 203.0.113.7

NATed peers then find this relay via `AutoRelay.create(p2p, dht)` with zero
relay configuration."""

from __future__ import annotations

import argparse
import subprocess
import time
from pathlib import Path

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

NATIVE_DIR = Path(__file__).parent.parent / "native"


def main():
    parser = argparse.ArgumentParser(description="Run + advertise a relay daemon")
    parser.add_argument("--initial_peers", nargs="*", default=[],
                        help="DHT bootstrap addrs (empty: starts a fresh swarm)")
    parser.add_argument("--relay_port", type=int, default=0, help="0 = ephemeral")
    parser.add_argument("--announce_host", default=None,
                        help="the relay endpoint advertised to the swarm (REQUIRED "
                             "for real deployments; defaults to loopback for local "
                             "testing only)")
    parser.add_argument("--identity_path", default="relay.key",
                        help="persistent relay Ed25519 identity file")
    parser.add_argument("--advertise_period", type=float, default=300.0,
                        help="re-advertise at this period (records expire at 2x)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)

    if args.announce_host is None:
        args.announce_host = "127.0.0.1"
        logger.warning(
            "no --announce_host given: advertising LOOPBACK (127.0.0.1) — fine for "
            "local testing, useless to any peer on another machine"
        )

    from hivemind_tpu.p2p.native_transport import build_daemon_binary, read_daemon_banner

    # the shared helper serializes concurrent makes with an flock and treats a
    # missing toolchain as an error message (an operator CLI raises on it)
    binary, error = build_daemon_binary()
    if binary is None:
        raise RuntimeError(f"relay daemon unavailable under {NATIVE_DIR}: {error}")

    daemon = subprocess.Popen(
        [str(binary), str(args.relay_port), args.identity_path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    # a current daemon emits exactly two startup lines in one flush; the bounded
    # read guards a STALE prebuilt binary from before the two-line protocol —
    # hanging forever there would be worse than erroring. Anything unexpected is
    # an error: a crypto-capable relay advertised WITHOUT its identity would
    # silently downgrade every NATed peer to unpinned registration.
    banner = read_daemon_banner(daemon, timeout=10.0)
    if banner is None:
        returncode = daemon.poll()
        stderr_tail = ""
        if returncode is not None:  # died before announcing (e.g. port bound)
            stderr_tail = daemon.stderr.read()[-500:]
        daemon.kill()
        daemon.wait()
        raise RuntimeError(
            "relay daemon did not announce its two startup lines within 10s"
            + (f" (rc={returncode}): {stderr_tail}" if returncode is not None
               else " — a stale binary predates the protocol; rebuild (make -C native)")
        )
    first_line, identity_line = banner
    try:
        port = int(first_line.rsplit(" ", 1)[-1])
    except ValueError:
        daemon.kill()
        raise RuntimeError(f"unexpected relay daemon output: {first_line!r}") from None
    if identity_line.startswith("relay identity "):
        pubkey_hex = identity_line.rsplit(" ", 1)[-1]
        logger.info(f"relay daemon up on port {port} (identity {pubkey_hex[:16]}…)")
    elif identity_line == "relay encryption unavailable":
        pubkey_hex = ""
        logger.warning(
            f"relay daemon up on port {port} WITHOUT an identity (no libcrypto) — "
            f"peers cannot pin it and will refuse encrypted-control registration"
        )
    else:
        daemon.kill()
        raise RuntimeError(f"unexpected relay daemon output: {identity_line!r}")

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.p2p.autorelay import advertise_relay

    dht = DHT(initial_peers=args.initial_peers, start=True)
    for maddr in dht.get_visible_maddrs():
        logger.info(f"swarm members can bootstrap via: --initial_peers {maddr}")

    try:
        while True:
            if daemon.poll() is not None:
                raise RuntimeError(f"relay daemon exited with rc={daemon.returncode}")
            ok = advertise_relay(
                dht, args.announce_host, port, pubkey_hex, ttl=args.advertise_period * 2
            )
            logger.info(
                f"advertised {args.announce_host}:{port} in the DHT (stored={ok}); "
                f"next refresh in {args.advertise_period:.0f}s"
            )
            time.sleep(args.advertise_period)
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        daemon.kill()
        daemon.wait()
        dht.shutdown()


if __name__ == "__main__":
    main()
