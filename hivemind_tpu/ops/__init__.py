from hivemind_tpu.ops.quantization import (
    BLOCKWISE_BLOCK_SIZE,
    blockwise_dequantize,
    blockwise_quantize,
    quantile_quantize,
    uniform_quantize,
)
