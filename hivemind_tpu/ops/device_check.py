"""On-device validation of the Pallas kernels — the chip-trust gate.

The kernels are fully covered in interpret mode by the CPU test suite, but Mosaic
compilation on a real TPU is a different code path (tiling, VMEM budgets, dtype
rules). `validate_on_device()` runs the same parity checks ON THE CURRENT DEFAULT
DEVICE and returns a structured report; `bench.py` calls it whenever the chip
answers and embeds the report in the round artifact, so "flash attention is the
default" is a *measured* claim, not an interpret-mode extrapolation (VERDICT r2
item 2). It is also exposed as `tests/test_device_tpu.py` for manual runs on TPU.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


def _max_rel_err(a, b) -> float:
    a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
    return float(np.abs(a - b).max() / (np.abs(b).max() + 1e-9))


def validate_on_device(seq: int = 512, tol: float = 2e-2) -> Dict[str, Any]:
    """Run flash fwd/bwd parity and the blockwise-int8 round-trip on the default
    backend. ``tol`` is loose because the plain path computes in the input dtype
    while the kernels accumulate fp32 (on chip the inputs are bf16-cast by models;
    here we feed fp32, so observed errors should be far below ``tol``).

    Returns ``{"ok": bool, "backend": str, "checks": {name: max_rel_err},
    "errors": {name: str}}`` — a failed check records its exception instead of
    aborting the rest.
    """
    from hivemind_tpu.ops.pallas_attention import flash_attention
    from hivemind_tpu.parallel.ring_attention import plain_attention

    report: Dict[str, Any] = {
        "backend": jax.default_backend(),
        "checks": {},
        "errors": {},
    }
    interpret = jax.default_backend() != "tpu"
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(1, seq, 4, 64).astype(np.float32)) for _ in range(3)
    )
    w = jnp.asarray(np.cos(np.arange(64)), jnp.float32)

    for causal in (False, True):
        name = f"flash_fwd_{'causal' if causal else 'bidir'}"
        try:
            fused = flash_attention(q, k, v, causal, interpret)
            exact = plain_attention(q, k, v, causal=causal)
            report["checks"][name] = _max_rel_err(fused, exact)
        except Exception as e:
            report["errors"][name] = repr(e)[:500]

        name = f"flash_bwd_{'causal' if causal else 'bidir'}"
        try:
            loss_fused = lambda q, k, v: (flash_attention(q, k, v, causal, interpret) * w).sum()
            loss_exact = lambda q, k, v: (plain_attention(q, k, v, causal=causal) * w).sum()
            gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
            ge = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
            report["checks"][name] = max(_max_rel_err(a, b) for a, b in zip(gf, ge))
        except Exception as e:
            report["errors"][name] = repr(e)[:500]

    try:
        from hivemind_tpu.ops.pallas_quantization import (
            blockwise_dequantize_auto, blockwise_quantize_auto,
        )

        x = jnp.asarray(rng.randn(1 << 20).astype(np.float32))  # 1M elems, 256 blocks
        quantized, absmax = blockwise_quantize_auto(x)
        restored = blockwise_dequantize_auto(quantized, absmax)
        # int8 blockwise: error bound is absmax/127 per block
        bound = float(jnp.max(jnp.abs(x)) / 127.0) * 1.01
        err = float(jnp.max(jnp.abs(restored - x)))
        report["checks"]["blockwise_int8_roundtrip"] = err
        if err > bound:
            report["errors"]["blockwise_int8_roundtrip"] = (
                f"round-trip error {err:.3g} exceeds absmax/127 bound {bound:.3g}"
            )
    except Exception as e:
        report["errors"]["blockwise_int8_roundtrip"] = repr(e)[:500]

    attention_ok = all(
        report["checks"].get(n, float("inf")) < tol
        for n in ("flash_fwd_bidir", "flash_fwd_causal", "flash_bwd_bidir", "flash_bwd_causal")
    )
    report["attention_ok"] = attention_ok and not any(
        n.startswith("flash") for n in report["errors"]
    )
    report["ok"] = report["attention_ok"] and "blockwise_int8_roundtrip" not in report["errors"]
    return report
