"""Jitted quantization math — the device-side half of the compression codecs.

The reference's 8-bit path is a C++/CUDA bitsandbytes kernel
(hivemind/compression/quantization.py:130-201); here the equivalents are jax
functions that XLA fuses/tiles for TPU (a Pallas kernel would only matter for
enormous tensors; XLA's fusion already saturates HBM bandwidth for these shapes).
All functions also run under the CPU backend for host-side use.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

BLOCKWISE_BLOCK_SIZE = 4096  # parity with the reference's bitsandbytes blocksize
UNIFORM_NUM_BUCKETS = 256
UNIFORM_RANGE_IN_SIGMAS = 6.0


@partial(jax.jit, static_argnames=("block_size",))
def blockwise_quantize(flat: jax.Array, block_size: int = BLOCKWISE_BLOCK_SIZE):
    """Per-block absmax int8 quantization of a flat (padded) array.

    :returns: (int8 codes [n_blocks, block_size], fp32 absmax [n_blocks])
    Deviation from the reference: bitsandbytes uses a dynamic-tree codebook; linear
    absmax int8 has comparable error for gradient averaging and maps directly onto
    vectorized TPU ops.
    """
    blocks = flat.reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(absmax > 0, 127.0 / absmax, 0.0)
    codes = jnp.clip(jnp.round(blocks * scale[:, None]), -127, 127).astype(jnp.int8)
    return codes, absmax.astype(jnp.float32)


@partial(jax.jit, static_argnames=("block_size",))
def blockwise_dequantize(codes: jax.Array, absmax: jax.Array, block_size: int = BLOCKWISE_BLOCK_SIZE):
    scale = absmax / 127.0
    return (codes.astype(jnp.float32) * scale[:, None]).reshape(-1)


@jax.jit
def uniform_quantize(flat: jax.Array):
    """Uniform 8-bit quantization over [mean - 6σ, mean + 6σ] with a bucket-mean
    codebook (parity: reference quantization.py:60-74,88-93).

    :returns: (uint8 codes, fp32 codebook [256])
    """
    flat32 = flat.astype(jnp.float32)
    mean, std = jnp.mean(flat32), jnp.std(flat32) + 1e-11
    lo = mean - UNIFORM_RANGE_IN_SIGMAS * std
    hi = mean + UNIFORM_RANGE_IN_SIGMAS * std
    scale = (UNIFORM_NUM_BUCKETS - 1) / (hi - lo)
    codes = jnp.clip(jnp.round((flat32 - lo) * scale), 0, UNIFORM_NUM_BUCKETS - 1).astype(jnp.uint8)
    # bucket-mean codebook: average of the elements that landed in each bucket;
    # empty buckets fall back to the bucket midpoint
    sums = jnp.zeros(UNIFORM_NUM_BUCKETS, jnp.float32).at[codes].add(flat32)
    counts = jnp.zeros(UNIFORM_NUM_BUCKETS, jnp.float32).at[codes].add(1.0)
    midpoints = lo + (jnp.arange(UNIFORM_NUM_BUCKETS, dtype=jnp.float32) + 0.5) / scale
    codebook = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), midpoints)
    return codes, codebook


QUANTILE_SAMPLE_SIZE = 1 << 20  # codebook estimation sample for large tensors


def hash_sample_indices(size: int, count: int) -> np.ndarray:
    """``count`` layout-independent sample indices into a flat array of
    ``size`` via a multiplicative-hash sequence (Knuth's 2654435761): unlike
    strided sampling, the indices share no period with any channel layout, so
    structured tensors cannot alias the sample onto a single column. THE shared
    sampler for every host-side codec statistic (quantile codebooks here,
    uniform8 codebooks in compression/quantization.py) — one formula, so the
    'deterministic, reproducible wire bytes' guarantee cannot drift apart."""
    indices = (
        np.arange(count, dtype=np.uint64) * np.uint64(2654435761)
    ) % np.uint64(size)
    return indices.astype(np.int64, copy=False)


@jax.jit
def _quantile_codebook(flat32: jax.Array) -> jax.Array:
    quantiles = jnp.linspace(0.5 / UNIFORM_NUM_BUCKETS, 1 - 0.5 / UNIFORM_NUM_BUCKETS, UNIFORM_NUM_BUCKETS)
    return jnp.quantile(flat32, quantiles)


@jax.jit
def _quantile_encode(flat32: jax.Array, codebook: jax.Array):
    edges = (codebook[1:] + codebook[:-1]) / 2
    return jnp.searchsorted(edges, flat32).astype(jnp.uint8)


@jax.jit
def _quantile_sample(flat32: jax.Array) -> jax.Array:
    """Exactly 2^20 layout-independent samples via a multiplicative-hash index
    sequence (Knuth's 2654435761): unlike strided sampling, the indices share no
    period with any channel layout, so structured tensors (e.g. [N, 3] or [N, 4]
    with per-channel scales) cannot alias the sample onto a single column."""
    indices = (
        jnp.arange(QUANTILE_SAMPLE_SIZE, dtype=jnp.uint32) * jnp.uint32(2654435761)
    ) % jnp.uint32(flat32.size)
    return jnp.take(flat32, indices.astype(jnp.int32))


def quantile_quantize(flat: jax.Array):
    """Quantile 8-bit quantization: the codebook is the 256 empirical quantiles.

    Large tensors estimate the codebook from a hash-sampled 2^20-element subset
    instead of sorting everything: 4096 samples per bucket keeps the boundary
    estimates well within one bucket width (measured: identical round-trip error
    on 10M gaussian elements). This replaces the reference's thread-pool
    quantile-of-quantiles approximation (quantization.py:77-122) — same idea,
    sampling instead of parallel chunking.

    The whole codec runs in NUMPY on the host: its output feeds wire
    serialization (host bytes) anyway, and XLA:CPU executes the gather-heavy
    sample/quantile/searchsorted steps as scalar loops — the numpy path measured
    ~5x faster at 10M elements (846 → ~170 ms) with identical error. The jitted
    helpers above remain for callers that want the math on-device.

    :returns: (uint8 codes, fp32 codebook [256])
    """
    flat32 = np.asarray(flat, dtype=np.float32).reshape(-1)
    if flat32.size == 0:
        return np.zeros(0, np.uint8), np.zeros(UNIFORM_NUM_BUCKETS, np.float32)
    if flat32.size > QUANTILE_SAMPLE_SIZE:
        sample = np.sort(flat32[hash_sample_indices(flat32.size, QUANTILE_SAMPLE_SIZE)])
    else:
        sample = np.sort(flat32)
    # evenly spaced order statistics of the sorted sample = empirical quantiles
    positions = np.linspace(
        0.5 / UNIFORM_NUM_BUCKETS, 1 - 0.5 / UNIFORM_NUM_BUCKETS, UNIFORM_NUM_BUCKETS
    ) * (sample.size - 1)
    codebook = sample[np.round(positions).astype(np.int64)].astype(np.float32)
    edges = (codebook[1:] + codebook[:-1]) / 2
    return _encode_against_edges(flat32, edges), codebook


_ENCODE_GRID = 1 << 16


def _encode_against_edges(flat32: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Exact bucket assignment ~4x faster than ``np.searchsorted`` on the full
    array: a uniform grid LUT resolves every element whose grid bin lies wholly
    inside one bucket (~99% of them); only elements in bins that straddle a
    bucket edge fall back to a real binary search, so results are bit-identical
    to ``np.searchsorted(edges, flat32)``."""
    # grid arithmetic runs in float64 so the per-element binning is consistent
    # with the grid boundaries for ANY float32 data (with a float32 grid, data
    # like N(1e4, 1) makes ulp(lo) comparable to the grid step and bins disagree
    # with grid_starts — codes then silently differ from searchsorted's)
    lo, hi = float(edges[0]), float(edges[-1])
    span = hi - lo
    if not span > 0:  # degenerate codebook (constant tensor): no grid to build
        return np.searchsorted(edges, flat32).astype(np.uint8)
    scale = (_ENCODE_GRID - 2) / span
    grid_starts = lo + np.arange(_ENCODE_GRID + 1, dtype=np.float64) / scale
    lut = np.searchsorted(edges, grid_starts).astype(np.uint8)
    safe = lut[:-1] == lut[1:]
    bins = np.clip(
        ((flat32.astype(np.float64) - lo) * scale).astype(np.int64), 0, _ENCODE_GRID - 1
    )
    codes = lut[bins]
    unsafe = ~safe[bins]
    codes[unsafe] = np.searchsorted(edges, flat32[unsafe]).astype(np.uint8)
    return codes


def dequantize_with_codebook(codes: np.ndarray, codebook: np.ndarray) -> np.ndarray:
    """Host-side lookup decode (cheap gather; no jit needed)."""
    return codebook[codes.astype(np.int64)]


def pad_to_block(flat: np.ndarray, block_size: int = BLOCKWISE_BLOCK_SIZE) -> tuple:
    """Pad a flat array to a multiple of block_size; returns (padded, original_size)."""
    remainder = flat.size % block_size
    if remainder == 0:
        return flat, flat.size
    padded = np.concatenate([flat, np.zeros(block_size - remainder, dtype=flat.dtype)])
    return padded, flat.size
