"""Pallas TPU kernels for blockwise int8 quantization — the device-side equivalent of
the reference's bitsandbytes CUDA kernels (hivemind/compression/quantization.py:130-201).

Layout: the flat tensor is viewed as [n_blocks, BLOCK_SIZE=4096] and the kernel
processes ROWS_PER_STEP=32 quantization blocks per grid step, so the int8 store tile
is exactly the TPU minimum (32, 128)-aligned shape (32, 4096) — one VMEM round trip
computes absmax, scales, rounds, and casts without materializing fp32 intermediates
in HBM. On non-TPU backends the same kernels run in Pallas interpret mode (used by
the CPU test suite); the fused-jnp path in ops/quantization.py remains the fast
host-side implementation and the dispatch helpers below pick per backend."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

ROWS_PER_STEP = 32  # int8 min sublane count: full tiles for the int8 store


def _quantize_kernel(x_ref, codes_ref, absmax_ref):
    x = x_ref[:]
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, 127.0 / absmax, 0.0)
    codes_ref[:] = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
    absmax_ref[:] = absmax


def _dequantize_kernel(codes_ref, absmax_ref, out_ref):
    scale = absmax_ref[:] / 127.0
    out_ref[:] = codes_ref[:].astype(jnp.float32) * scale


def _pad_rows(blocks: jax.Array) -> jax.Array:
    n = blocks.shape[0]
    remainder = n % ROWS_PER_STEP
    if remainder:
        blocks = jnp.pad(blocks, ((0, ROWS_PER_STEP - remainder), (0, 0)))
    return blocks


@partial(jax.jit, static_argnames=("block_size", "interpret"))
def pallas_blockwise_quantize(flat: jax.Array, block_size: int = 4096, interpret: bool = False):
    """Per-block absmax int8 quantization as one fused Pallas kernel.

    :returns: (int8 codes [n_blocks, block_size], fp32 absmax [n_blocks])
    """
    blocks = flat.astype(jnp.float32).reshape(-1, block_size)
    n_blocks = blocks.shape[0]
    padded = _pad_rows(blocks)
    rows = padded.shape[0]
    codes, absmax = pl.pallas_call(
        _quantize_kernel,
        grid=(rows // ROWS_PER_STEP,),
        in_specs=[pl.BlockSpec((ROWS_PER_STEP, block_size), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((ROWS_PER_STEP, block_size), lambda i: (i, 0)),
            pl.BlockSpec((ROWS_PER_STEP, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block_size), jnp.int8),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(padded)
    return codes[:n_blocks], absmax[:n_blocks, 0]


@partial(jax.jit, static_argnames=("block_size", "interpret"))
def pallas_blockwise_dequantize(
    codes: jax.Array, absmax: jax.Array, block_size: int = 4096, interpret: bool = False
):
    n_blocks = codes.shape[0]
    padded_codes = _pad_rows(codes)
    padded_absmax = _pad_rows(absmax.reshape(-1, 1))
    rows = padded_codes.shape[0]
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(rows // ROWS_PER_STEP,),
        in_specs=[
            pl.BlockSpec((ROWS_PER_STEP, block_size), lambda i: (i, 0)),
            pl.BlockSpec((ROWS_PER_STEP, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_STEP, block_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, block_size), jnp.float32),
        interpret=interpret,
    )(padded_codes, padded_absmax)
    return out[:n_blocks].reshape(-1)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def blockwise_quantize_auto(flat, block_size: int = 4096):
    """Backend dispatch: fused Pallas kernel on TPU, fused-jnp on host (interpret
    mode exists for correctness testing, not speed)."""
    if _on_tpu():
        return pallas_blockwise_quantize(flat, block_size=block_size)
    from hivemind_tpu.ops.quantization import blockwise_quantize

    return blockwise_quantize(flat, block_size=block_size)


def blockwise_dequantize_auto(codes, absmax, block_size: int = 4096):
    if _on_tpu():
        return pallas_blockwise_dequantize(codes, absmax, block_size=block_size)
    from hivemind_tpu.ops.quantization import blockwise_dequantize

    return blockwise_dequantize(codes, absmax, block_size=block_size)
