"""Pallas TPU flash-attention kernel — the fused hot op behind the serving path.

The reference has no attention kernel at all (its device math is plain torch ops;
SURVEY §2.0); attention here is the TPU-first capability layer's hot op: MoE
transformer/causal/llama experts and the flagship model all funnel through one
attention core (`parallel/ring_attention.plain_attention`). This kernel fuses the
whole softmax(QKᵀ)·V pipeline into VMEM-block passes with ONLINE softmax, so logits
never round-trip through HBM and VMEM stays O(BLOCK_Q·BLOCK_K) regardless of
sequence length.

Layout: grid = (batch·heads, seq/BLOCK_Q, seq/BLOCK_K) — the KV loop is the LAST
(fastest-varying) grid dimension, and the online-softmax carry (running row max,
row sum, output accumulator) lives in VMEM scratch that persists across those grid
steps; the carry is initialized on the first KV block and the normalized output is
written on the last. Only one (1, BLOCK_Q, d) query tile and one (1, BLOCK_K, d)
KV tile are resident per step. In causal mode, KV blocks entirely above the
diagonal skip their matmuls via `pl.when` (half the FLOPs of the naive sweep);
masking within straddling blocks matches `plain_attention` exactly.

Differentiation: `flash_attention` carries a `jax.custom_vjp` with FUSED backward
kernels (the standard two-pass scheme): the forward saves (out, lse) as O(seq)
residuals, then dQ comes from one kernel sweeping KV blocks per query block and
(dK, dV) from a second kernel sweeping query blocks per KV block — probabilities
are recomputed per tile from the saved log-sum-exp (`p = exp(s − lse)`, no max
carry needed), so score matrices never materialize in HBM in either direction.
On non-TPU backends the kernels run in interpret mode for the test suite;
`attention_auto` dispatches per backend."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128
# Row statistics (max / sum / lse / delta) are carried with a 128-wide minor dim:
# Mosaic requires the last two dims of every block to tile onto (8, 128) lanes,
# so a [BLOCK_Q] column vector is broadcast across _LANES and read back from
# lane 0 (the official TPU flash kernel stores l/m the same way,
# jax/experimental/pallas/ops/tpu/flash_attention.py MIN_BLOCK_SIZE).
_LANES = 128
_NEG_INF = -1e30  # large-but-finite: keeps fully-masked rows NaN-free


def _flash_kernel(
    q_ref, k_ref, v_ref, out_ref, lse_ref, max_ref, sum_ref, acc_ref, *, seq_len: int, causal: bool
):
    """One (query block, KV block) grid step; carry persists in scratch refs."""
    q_index, kv_index = pl.program_id(1), pl.program_id(2)
    num_kv = pl.num_programs(2)

    @pl.when(kv_index == 0)
    def _init():
        max_ref[:] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_start = kv_index * BLOCK_K
    # in causal mode, blocks entirely above the diagonal contribute nothing
    block_needed = (not causal) or (kv_start <= q_index * BLOCK_Q + BLOCK_Q - 1)

    @pl.when(block_needed)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [BLOCK_Q, d]
        k = k_ref[0].astype(jnp.float32)  # [BLOCK_K, d]
        v = v_ref[0].astype(jnp.float32)
        scale = q.shape[-1] ** -0.5
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BLOCK_Q, BLOCK_K]
        # rank-2 iotas: Mosaic rejects rank-1 lax.iota (pallas_guide: common pitfalls)
        kv_positions = kv_start + jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 1)
        mask = kv_positions < seq_len  # guard the tail-padding block
        if causal:
            q_positions = q_index * BLOCK_Q + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 0
            )
            mask &= kv_positions <= q_positions
        scores = jnp.where(mask, scores, _NEG_INF)

        row_max = max_ref[:, 0]
        block_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[:, None])
        acc_ref[:] = acc_ref[:] * correction[:, None] + jax.lax.dot_general(
            probs, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        new_sum = sum_ref[:, 0] * correction + jnp.sum(probs, axis=-1)
        sum_ref[:] = jnp.broadcast_to(new_sum[:, None], sum_ref.shape)
        max_ref[:] = jnp.broadcast_to(new_max[:, None], max_ref.shape)

    @pl.when(kv_index == num_kv - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(sum_ref[:, 0], 1e-30)[:, None]
        out_ref[0] = out.astype(out_ref.dtype)
        # log-sum-exp per query row: what ring attention needs to merge softmax
        # statistics across sequence shards without re-materializing the scores
        lse = max_ref[:, 0] + jnp.log(jnp.maximum(sum_ref[:, 0], 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


@partial(jax.jit, static_argnames=("causal", "interpret"))
def _flash_forward(q, k, v, causal: bool = False, interpret: bool = False):
    """q, k, v: [batch, seq, heads, head_dim] → context of the same shape."""
    batch, seq, heads, head_dim = q.shape

    def to_bh(x, block):  # [batch*heads, ceil(seq/block)*block, head_dim]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(batch * heads, seq, head_dim)
        pad = (-seq) % block
        return jnp.pad(x, ((0, 0), (0, pad), (0, 0))) if pad else x

    qb = to_bh(q, BLOCK_Q)
    kb, vb = to_bh(k, BLOCK_K), to_bh(v, BLOCK_K)
    out, lse = pl.pallas_call(
        partial(_flash_kernel, seq_len=seq, causal=causal),
        grid=(batch * heads, qb.shape[1] // BLOCK_Q, kb.shape[1] // BLOCK_K),
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, BLOCK_K, head_dim), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, BLOCK_K, head_dim), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_Q, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, BLOCK_Q, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, qb.shape[1], head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch * heads, qb.shape[1], _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, _LANES), jnp.float32),  # running row max
            pltpu.VMEM((BLOCK_Q, _LANES), jnp.float32),  # running row sum
            pltpu.VMEM((BLOCK_Q, head_dim), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qb, kb, vb)
    out = out[:, :seq].reshape(batch, heads, seq, head_dim)
    lse = lse[:, :seq, 0].reshape(batch, heads, seq)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def flash_attention_lse(q, k, v, causal: bool = False, interpret: bool = False):
    """Fused attention that ALSO returns the per-row log-sum-exp ([batch, heads,
    seq], fp32) — the statistic ring attention needs to merge shard outputs:
    ``merged = Σ_i out_i · exp(lse_i − logaddexp_i(lse))``. Forward-only (no
    custom_vjp): callers that differentiate wrap the whole construction (see
    `parallel.ring_attention.ring_flash_attention`)."""
    return _flash_forward(q, k, v, causal=causal, interpret=interpret)


def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *, kv_start, q_start, seq_len, causal):
    """Shared per-tile math of both backward kernels: recompute probabilities from
    the saved log-sum-exp and return (p, ds) for this (query, KV) tile pair."""
    q = q_ref[0].astype(jnp.float32)  # [BLOCK_Q, d]
    k = k_ref[0].astype(jnp.float32)  # [BLOCK_K, d]
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0]  # [BLOCK_Q] fp32 (lane 0 of the 128-wide carry)
    delta = delta_ref[0][:, 0]  # [BLOCK_Q] fp32, rowsum(dout * out)
    scale = q.shape[-1] ** -0.5
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    kv_positions = kv_start + jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 1)
    mask = kv_positions < seq_len  # tail-padding guard; masked p underflows to 0
    if causal:
        q_positions = q_start + jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 0)
        mask &= kv_positions <= q_positions
    scores = jnp.where(mask, scores, _NEG_INF)
    p = jnp.exp(scores - lse[:, None])  # exact probs: lse already holds the row max
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    return q, k, do, p, ds


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref, *, seq_len, causal
):
    """dQ pass: grid (batch·heads, q_blocks, kv_blocks) — for each query block,
    sweep KV blocks accumulating dQ = Σ dS·K in VMEM scratch."""
    q_index, kv_index = pl.program_id(1), pl.program_id(2)
    num_kv = pl.num_programs(2)

    @pl.when(kv_index == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    kv_start = kv_index * BLOCK_K
    block_needed = (not causal) or (kv_start <= q_index * BLOCK_Q + BLOCK_Q - 1)

    @pl.when(block_needed)
    def _accumulate():
        _q, k, _do, _p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            kv_start=kv_start, q_start=q_index * BLOCK_Q, seq_len=seq_len, causal=causal,
        )
        dq_acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kv_index == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref, *, seq_len, causal
):
    """dK/dV pass: grid (batch·heads, kv_blocks, q_blocks) — for each KV block,
    sweep query blocks accumulating dV = Σ Pᵀ·dO and dK = Σ dSᵀ·Q in scratch."""
    kv_index, q_index = pl.program_id(1), pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(q_index == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    kv_start = kv_index * BLOCK_K
    # blocks strictly above the diagonal see no probability mass in causal mode
    block_needed = (not causal) or (q_index * BLOCK_Q + BLOCK_Q - 1 >= kv_start)

    @pl.when(block_needed)
    def _accumulate():
        q, _k, do, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            kv_start=kv_start, q_start=q_index * BLOCK_Q, seq_len=seq_len, causal=causal,
        )
        dv_acc_ref[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_acc_ref[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(q_index == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


@partial(jax.jit, static_argnames=("causal", "interpret"))
def _flash_backward(q, k, v, out, lse, grad_out, causal: bool = False, interpret: bool = False):
    """Fused two-pass flash backward from the saved (out, lse) residuals."""
    batch, seq, heads, head_dim = q.shape

    def to_bh(x, block):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(batch * heads, seq, head_dim)
        pad = (-seq) % block
        return jnp.pad(x, ((0, 0), (0, pad), (0, 0))) if pad else x

    def from_bh(x):
        return jnp.transpose(x[:, :seq].reshape(batch, heads, seq, head_dim), (0, 2, 1, 3))

    qb, dob, outb = to_bh(q, BLOCK_Q), to_bh(grad_out, BLOCK_Q), to_bh(out, BLOCK_Q)
    kb, vb = to_bh(k, BLOCK_K), to_bh(v, BLOCK_K)
    padded_q = qb.shape[1]
    # delta_i = Σ_d dOut·Out — one elementwise reduce; padded rows are zero (dob
    # is zero-padded), so they contribute nothing to dK/dV in the sweep
    deltab = jnp.sum(dob.astype(jnp.float32) * outb.astype(jnp.float32), axis=-1)
    lseb = lse.reshape(batch * heads, seq)  # lse arrives as [batch, heads, seq]
    pad = padded_q - seq
    if pad:
        lseb = jnp.pad(lseb, ((0, 0), (0, pad)))
    # 128-lane broadcast of the row statistics (see _LANES)
    lseb = jnp.broadcast_to(lseb[:, :, None], (*lseb.shape, _LANES))
    deltab = jnp.broadcast_to(deltab[:, :, None], (*deltab.shape, _LANES))

    num_q, num_kv = padded_q // BLOCK_Q, kb.shape[1] // BLOCK_K
    q_spec = pl.BlockSpec((1, BLOCK_Q, head_dim), lambda bh, qi, ki: (bh, qi, 0))
    kv_spec = pl.BlockSpec((1, BLOCK_K, head_dim), lambda bh, qi, ki: (bh, ki, 0))
    row_spec = pl.BlockSpec((1, BLOCK_Q, _LANES), lambda bh, qi, ki: (bh, qi, 0))
    dq = pl.pallas_call(
        partial(_flash_bwd_dq_kernel, seq_len=seq, causal=causal),
        grid=(batch * heads, num_q, num_kv),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, BLOCK_Q, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, padded_q, head_dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((BLOCK_Q, head_dim), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lseb, deltab)
    # second pass: grid transposed — (bh, kv block, q block), q fastest-varying
    q_spec_t = pl.BlockSpec((1, BLOCK_Q, head_dim), lambda bh, ki, qi: (bh, qi, 0))
    kv_spec_t = pl.BlockSpec((1, BLOCK_K, head_dim), lambda bh, ki, qi: (bh, ki, 0))
    row_spec_t = pl.BlockSpec((1, BLOCK_Q, _LANES), lambda bh, ki, qi: (bh, qi, 0))
    dk, dv = pl.pallas_call(
        partial(_flash_bwd_dkv_kernel, seq_len=seq, causal=causal),
        grid=(batch * heads, num_kv, num_q),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t, row_spec_t],
        out_specs=[
            pl.BlockSpec((1, BLOCK_K, head_dim), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, BLOCK_K, head_dim), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, kb.shape[1], head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch * heads, kb.shape[1], head_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BLOCK_K, head_dim), jnp.float32),
            pltpu.VMEM((BLOCK_K, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, dob, lseb, deltab)
    return from_bh(dq), from_bh(dk), from_bh(dv)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False, interpret: bool = False):
    """Fused flash attention on [batch, seq, heads, head_dim] (full sequences; for
    padded batches use the mask-capable `plain_attention`). Backward is fused too
    (two-pass kernels from the saved log-sum-exp — see module docstring)."""
    return _flash_forward(q, k, v, causal=causal, interpret=interpret)[0]


def _flash_fwd(q, k, v, causal, interpret):
    out, lse = _flash_forward(q, k, v, causal=causal, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, interpret, residuals, grad_out):
    q, k, v, out, lse = residuals
    # lse back to [bh, seq] layout happens inside _flash_backward; reshape here
    # keeps residuals in the public [batch, seq, heads, dim] convention
    lse_bhs = lse  # [batch, heads, seq] as returned by _flash_forward
    return _flash_backward(
        q, k, v, out, lse_bhs, grad_out.astype(q.dtype), causal=causal, interpret=interpret
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _flash_enabled() -> bool:
    import os

    return os.environ.get("HIVEMIND_TPU_FLASH_ATTENTION", "1") == "1"


def _flash_forced() -> bool:
    """HIVEMIND_TPU_FORCE_FLASH=1 selects the flash kernels regardless of the
    CURRENT backend — for AOT workflows (jax.export platforms=["tpu"]) where the
    trace happens on a CPU host but the artifact targets a TPU."""
    import os

    return os.environ.get("HIVEMIND_TPU_FORCE_FLASH", "0") == "1"


def attention_auto(q, k, v, mask=None, causal: bool = False):
    """Backend dispatch for the attention core: fused Pallas kernel on TPU (full
    sequences; both directions are fused kernels — set
    HIVEMIND_TPU_FLASH_ATTENTION=0 to force the einsum core for A/B runs),
    reference einsum path elsewhere or when a padding mask is given."""
    # q_len != k_len (cached incremental decode) needs plain_attention's end-aligned
    # causal mask; the flash kernel assumes square self-attention
    if (
        mask is None
        and q.shape[1] == k.shape[1]
        and (jax.default_backend() == "tpu" or _flash_forced())
        and _flash_enabled()
    ):
        return flash_attention(q, k, v, causal)
    from hivemind_tpu.parallel.ring_attention import plain_attention

    return plain_attention(q, k, v, mask=mask, causal=causal)
