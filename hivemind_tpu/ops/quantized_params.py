"""Int8 weight-only parameter storage for serving (BASELINE config #5, the
Petals-style block server: reference-era Petals serves Llama blocks with 8-bit
weights; here the storage codec is this repo's own blockwise absmax int8 —
`ops/pallas_quantization.py` on TPU, the fused jnp path on host).

A parameter pytree is converted leaf-by-leaf: float leaves above a size threshold
become :class:`QuantizedTensor` (int8 codes + per-block fp32 absmax, a registered
pytree node, 4x smaller resident than fp32), tiny leaves (norm scales, biases)
stay exact. ``dequantize_tree`` runs INSIDE the consumer's jit, so XLA keeps the
int8 resident in HBM and materializes bf16/fp32 weights transiently per use —
resident model memory divides by ~4 while matmuls still run on the MXU in bf16.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.ops.pallas_quantization import (
    blockwise_dequantize_auto,
    blockwise_quantize_auto,
)

QUANT_BLOCK_SIZE = 4096
MIN_QUANT_SIZE = 4096  # leaves smaller than one block stay exact


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Blockwise-int8 weight: ``codes`` [n_blocks, block] int8 + ``absmax``
    [n_blocks] fp32, remembering the original shape/dtype/true size."""

    def __init__(self, codes, absmax, shape: Tuple[int, ...], dtype, size: int):
        self.codes, self.absmax = codes, absmax
        self.shape, self.dtype, self.size = tuple(shape), dtype, size

    def tree_flatten(self):
        return (self.codes, self.absmax), (self.shape, self.dtype, self.size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    @property
    def nbytes(self) -> int:
        return int(np.asarray(self.codes).nbytes + np.asarray(self.absmax).nbytes)

    def dequantize(self):
        flat = blockwise_dequantize_auto(self.codes, self.absmax, QUANT_BLOCK_SIZE)
        return flat[: self.size].reshape(self.shape).astype(self.dtype)

    def __repr__(self):
        return f"QuantizedTensor(shape={self.shape}, blocks={self.codes.shape[0]})"


def _is_quantized(leaf) -> bool:
    return isinstance(leaf, QuantizedTensor)


def quantize_params(params: Any, min_size: int = MIN_QUANT_SIZE) -> Any:
    """Float leaves with >= ``min_size`` elements become QuantizedTensor."""

    def convert(leaf):
        arr = jnp.asarray(leaf)
        # only float MATRICES quantize: 1-D leaves are norm scales/biases whose
        # exactness matters far more than their bytes (a 4096-wide RMSNorm scale
        # has size == one quant block, so a pure size test would catch it)
        if arr.ndim < 2 or arr.size < min_size or not jnp.issubdtype(arr.dtype, jnp.floating):
            return arr
        flat = arr.astype(jnp.float32).reshape(-1)
        pad = (-flat.size) % QUANT_BLOCK_SIZE
        if pad:
            flat = jnp.pad(flat, (0, pad))
        codes, absmax = blockwise_quantize_auto(flat, QUANT_BLOCK_SIZE)
        return QuantizedTensor(codes, absmax, arr.shape, arr.dtype, arr.size)

    return jax.tree_util.tree_map(convert, params)


def dequantize_tree(params: Any) -> Any:
    """Materialize a quantized tree back to dense weights (call INSIDE jit)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize() if _is_quantized(leaf) else leaf,
        params,
        is_leaf=_is_quantized,
    )


def tree_param_bytes(params: Any) -> int:
    """Resident bytes of a (possibly quantized) parameter tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=_is_quantized
    ):
        if _is_quantized(leaf):
            total += leaf.nbytes
        else:
            total += int(np.asarray(leaf).nbytes)
    return total
