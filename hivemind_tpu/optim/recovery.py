"""Crash-safe local state recovery (ISSUE 7 tentpole).

The swarm can rebuild a peer's state over the network, but a machine reboot
should cost a file read, not a multi-donor download: the Optimizer saves its
``state_dict`` into a :class:`LocalCheckpointStore` on an epoch cadence and
restores from it at startup. The restore order is

    local-verified checkpoint  →  swarm download  →  fresh initialization

where the swarm leg is the existing catch-up path (the restored local epoch is
still validated against the progress tracker — a stale checkpoint merely
shortens the download that follows).

Crash safety is mechanical, not probabilistic:

- **Atomic publication.** Every save writes to a temp file in the same
  directory, flushes + fsyncs it, computes a blake2b-16 digest of the file
  bytes, then atomically renames it into a digest-stamped name and fsyncs the
  directory. A ``kill -9`` at ANY instant leaves either the previous
  checkpoint set intact or the new one fully published — never a torn file
  under a valid name.
- **Verified restore.** ``load_latest`` re-digests each candidate file and
  compares against the digest in its name, walking from the newest epoch down:
  a corrupt or truncated file is rejected (counted under
  ``hivemind_state_sync_digest_failures_total{site="checkpoint"}``) and the
  previous checkpoint is used instead.
- **Bounded retention.** Only the newest ``keep_last`` checkpoints survive a
  save; stray temp files from interrupted saves are swept as well.

See docs/state_recovery.md for the full recovery state machine.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import re
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import trace as _tracing_span
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DIGEST_SIZE = 16  # matches state_sync.DIGEST_SIZE: one integrity currency repo-wide

_STATE_RESTORES = _TELEMETRY.counter(
    "hivemind_state_sync_restores_total",
    "state restores by source (local checkpoint / swarm download / fresh init)",
    ("source",),
)
_CHECKPOINT_DIGEST_FAILURES = _TELEMETRY.counter(
    "hivemind_state_sync_digest_failures_total",
    "state payloads rejected by digest verification",
    ("site",),
).labels(site="checkpoint")
_CHECKPOINT_SAVES = _TELEMETRY.counter(
    "hivemind_checkpoint_saves_total", "local checkpoints published atomically"
)

_CHECKPOINT_PATTERN = re.compile(
    r"^(?P<prefix>[\w.-]+)-e(?P<epoch>\d{12})-(?P<digest>[0-9a-f]{32})\.ckpt\.npz$"
)
_TMP_SUFFIX = ".tmp"


def _file_digest(path: Path) -> str:
    digest = hashlib.blake2b(digest_size=DIGEST_SIZE)
    with path.open("rb") as handle:
        for block in iter(lambda: handle.read(1 << 20), b""):
            digest.update(block)
    return digest.hexdigest()


class CheckpointError(Exception):
    """A checkpoint could not be written (restores never raise: they fall back)."""


class LocalCheckpointStore:
    """Digest-stamped, atomically-published checkpoints of an Optimizer's
    ``state_dict`` (epoch + tensors + optax step counters).

    :param directory: where checkpoints live (created if missing)
    :param prefix: filename prefix — one store directory can host several peers
        as long as their prefixes differ
    :param keep_last: newest checkpoints kept after every save (older pruned)
    """

    def __init__(self, directory, *, prefix: str = "state", keep_last: int = 3):
        assert keep_last >= 1, "retention must keep at least one checkpoint"
        assert _CHECKPOINT_PATTERN.match(f"{prefix}-e{0:012d}-{'0' * 32}.ckpt.npz"), (
            f"prefix {prefix!r} must be filename-safe ([\\w.-])"
        )
        self.directory = Path(directory)
        self.prefix = prefix
        self.keep_last = keep_last
        self.directory.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------------ save

    def save(self, state: Dict) -> Path:
        """Atomically publish one ``state_dict`` checkpoint; returns its path."""
        epoch = int(state["epoch"])
        tensors = state["tensors"]
        payload = {
            "epoch": np.asarray(epoch, dtype=np.int64),
            "opt_counts": np.asarray(list(state.get("opt_counts") or []), dtype=np.int64),
            "num_tensors": np.asarray(len(tensors), dtype=np.int64),
        }
        for index, tensor in enumerate(tensors):
            payload[f"tensor_{index:05d}"] = np.asarray(tensor)

        fd, tmp_name = tempfile.mkstemp(
            prefix=f".{self.prefix}-save-", suffix=_TMP_SUFFIX, dir=self.directory
        )
        tmp_path = Path(tmp_name)
        try:
            with os.fdopen(fd, "wb") as handle:
                np.savez(handle, **payload)
                handle.flush()
                os.fsync(handle.fileno())
            digest = _file_digest(tmp_path)
            final = self.directory / f"{self.prefix}-e{epoch:012d}-{digest}.ckpt.npz"
            os.replace(tmp_path, final)  # atomic on POSIX: old checkpoints untouched
            self._fsync_directory()
        except BaseException as e:
            with contextlib.suppress(OSError):
                tmp_path.unlink()
            raise CheckpointError(f"could not publish checkpoint at epoch {epoch}: {e!r}") from e
        _CHECKPOINT_SAVES.inc()
        self.prune()
        logger.debug(f"published checkpoint {final.name}")
        return final

    def _fsync_directory(self) -> None:
        # the rename itself must be durable, or a crash right after save() could
        # roll the directory back to a state where the new name never existed
        with contextlib.suppress(OSError):
            dir_fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

    # ------------------------------------------------------------------ load

    def checkpoints(self) -> List[Path]:
        """All well-named checkpoints, newest epoch first (NOT yet verified)."""
        found = []
        for path in self.directory.iterdir():
            match = _CHECKPOINT_PATTERN.match(path.name)
            if match is not None and match.group("prefix") == self.prefix:
                found.append((int(match.group("epoch")), path))
        found.sort(reverse=True)
        return [path for _epoch, path in found]

    def load_latest(self) -> Optional[Dict]:
        """The newest checkpoint whose file digest matches its name, as a
        ``state_dict``; corrupt/torn files are skipped (and counted), never
        adopted."""
        for path in self.checkpoints():
            expected = _CHECKPOINT_PATTERN.match(path.name).group("digest")
            try:
                actual = _file_digest(path)
                if actual != expected:
                    _CHECKPOINT_DIGEST_FAILURES.inc()
                    logger.warning(
                        f"checkpoint {path.name} failed digest verification; trying an older one"
                    )
                    continue
                return self._read(path)
            except Exception as e:
                logger.warning(f"checkpoint {path.name} unreadable ({e!r}); trying an older one")
        return None

    @staticmethod
    def _read(path: Path) -> Dict:
        with np.load(path) as archive:
            num_tensors = int(archive["num_tensors"])
            tensors = [archive[f"tensor_{index:05d}"] for index in range(num_tensors)]
            return {
                "epoch": int(archive["epoch"]),
                "tensors": tensors,
                "opt_counts": [int(count) for count in archive["opt_counts"]],
            }

    # ------------------------------------------------------------------ retention

    # temp files older than this are interrupted saves from a dead process; a
    # younger one may belong to a LIVE concurrent writer and must not be swept
    STALE_TMP_AGE_S = 600.0

    def prune(self) -> None:
        """Keep the newest ``keep_last`` checkpoints; sweep interrupted temp files
        (age-gated so a concurrent save's in-flight temp file is never touched)."""
        for stale in self.checkpoints()[self.keep_last:]:
            with contextlib.suppress(OSError):
                stale.unlink()
        cutoff = time.time() - self.STALE_TMP_AGE_S
        for path in self.directory.glob(f".{self.prefix}-save-*{_TMP_SUFFIX}"):
            with contextlib.suppress(OSError):
                if path.stat().st_mtime < cutoff:
                    path.unlink()


def restore_from_local(state_averager, store: Optional[LocalCheckpointStore]) -> Optional[int]:
    """The first leg of the recovery order: adopt the newest verified local
    checkpoint into ``state_averager``. Returns the restored epoch, or ``None``
    when no usable checkpoint exists (the caller falls through to the swarm /
    fresh legs). Counts ``hivemind_state_sync_restores_total{source=...}``."""
    if store is None:
        return None
    with _tracing_span("state_sync.restore_local"):
        state = store.load_latest()
        if state is None:
            # a store was configured but held nothing usable: this peer starts
            # fresh (the swarm leg may still catch it up later)
            _STATE_RESTORES.inc(source="fresh")
            return None
        try:
            state_averager.load_state_dict(state)
        except Exception as e:
            logger.warning(f"local checkpoint could not be adopted ({e!r}); starting fresh")
            _STATE_RESTORES.inc(source="fresh")
            return None
        _STATE_RESTORES.inc(source="local")
        logger.info(f"restored local checkpoint at epoch {state['epoch']}")
        return int(state["epoch"])
