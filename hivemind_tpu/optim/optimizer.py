"""hivemind_tpu.Optimizer — train collaboratively with an elastic swarm of unreliable
peers (capability parity: reference hivemind/optim/optimizer.py:32-790).

jax-first API: instead of wrapping a torch optimizer (loss.backward(); opt.step()),
the user's jitted step computes gradients and passes them in; ``step`` returns the
current parameter pytree:

    opt = Optimizer(dht=dht, run_id="run", params=params, optimizer=optax.adam(1e-3),
                    target_batch_size=4096, batch_size_per_step=32)
    loss, grads = jitted_loss_and_grad(opt.params, batch)
    params = opt.step(grads)

Semantics match the reference: progress is measured in virtual "epochs" of
``target_batch_size`` samples accumulated ACROSS the swarm; when the swarm reaches the
target, peers average their accumulated gradients (weighted by contribution), apply
one optax update each, and advance the epoch — equivalent to large-batch synchronous
training, invariant to swarm size (reference optimizer.py:63-69)."""

from __future__ import annotations

import contextlib
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Iterable, Optional

import numpy as np

from hivemind_tpu.averaging.control import AveragingStage, StepControl
from hivemind_tpu.compression import CompressionBase, Float16Compression, NoCompression
from hivemind_tpu.dht import DHT
from hivemind_tpu.optim.chronic import ChronicFailureTracking
from hivemind_tpu.optim.grad_averager import GradientAverager
from hivemind_tpu.optim.progress_tracker import ProgressTracker
from hivemind_tpu.optim.recovery import LocalCheckpointStore, restore_from_local
from hivemind_tpu.optim.state_averager import TrainingStateAverager
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.device import STEP_TIMELINE as _STEP_TIMELINE
from hivemind_tpu.telemetry.ledger import LEDGER as _LEDGER
from hivemind_tpu.telemetry.tracing import trace as _tracing_span
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

# ISSUE 7 satellite: a peer that cannot download state adopts the global epoch
# NUMBER while skipping the training that produced it — silently, this turns a
# flaky download path into quiet model divergence; counted so the monitor sees it
_EPOCH_ADOPTED_WITHOUT_STATE = _TELEMETRY.counter(
    "hivemind_optimizer_epoch_adopted_without_state_total",
    "epoch fast-forwards after a failed state download (epoch number adopted, state NOT)",
)


class Optimizer(ChronicFailureTracking):
    """See module docstring.

    :param run_id: unique swarm identifier; peers with the same run_id train together
    :param target_batch_size: global samples per virtual epoch
    :param batch_size_per_step: default samples per local step (overridable per call)
    :param use_local_updates: apply optax updates locally every step and average
        PARAMETERS periodically instead of gradients (asynchronous mode)
    :param average_state_every: average parameters/opt stats every N epochs
    :param auxiliary: no data/gradients of its own; assists group averaging only.
        If no gradient schema is provided, it is bootstrapped from the swarm
        (state download from a running gradient averager) — aux peers need zero
        model knowledge, matching the reference.
    :param delay_optimizer_step: Delayed Parameter Updates — ``step()`` returns as
        soon as the epoch transition is SCHEDULED; gradient averaging and the optax
        update run on a background thread while the caller computes the next batches
        on one-step-stale parameters (reference optimizer.py:87-88,131-132 +
        state_averager.py:478-574 background executor)
    :param delay_grad_averaging: alias that implies delay_optimizer_step (kept for
        reference API parity; the background task always overlaps both)
    :param delay_state_averaging: run the periodic state-averaging round on a
        background thread (reference optimizer.py:129-130). Independent of
        delay_optimizer_step — with ``use_local_updates`` this is the canonical
        local-SGD combination (pair with ``delta_rule_averaging`` so local steps
        taken during the round survive). In full DPU mode the whole transition is
        already backgrounded, so the flag adds nothing there.
    :param delta_rule_averaging: apply state-averaging results as deltas so optimizer
        steps running concurrently with the round survive (required for DPU/local
        updates; reference state_averager.py:73-74)
    :param checkpoint_dir: when set (non-auxiliary peers), keep crash-safe local
        checkpoints there: atomically-published, digest-stamped snapshots saved on
        an epoch cadence and restored at startup, so a machine reboot costs a file
        read instead of a swarm download (restore order: local-verified → swarm →
        fresh; docs/state_recovery.md)
    :param checkpoint_every: save every N epochs (default 1)
    :param checkpoint_keep_last: checkpoints retained after every save (default 3)
    :param blackbox_dir: when set, arm the process-wide black-box flight
        recorder spooling to this directory (crash-durable msgpack frames of
        finished spans, ledger records and metric snapshots; read post-mortem
        with ``hivemind-blackbox`` — docs/observability.md). Arming is
        idempotent per directory, so run_server and Optimizer can both pass it.
    """

    def __init__(
        self,
        *,
        dht: DHT,
        run_id: str,
        target_batch_size: int,
        params: Any = None,
        optimizer: Any = None,
        batch_size_per_step: Optional[int] = None,
        matchmaking_time: float = 5.0,
        averaging_timeout: float = 60.0,
        load_state_timeout: float = 60.0,
        average_state_every: int = 1,
        use_local_updates: bool = False,
        delay_optimizer_step: bool = False,
        delay_grad_averaging: bool = False,
        delay_state_averaging: bool = False,
        delta_rule_averaging: bool = False,
        client_mode: bool = False,
        auxiliary: bool = False,
        grad_compression: CompressionBase = Float16Compression(),
        state_averaging_compression: CompressionBase = Float16Compression(),
        target_group_size: Optional[int] = None,
        min_group_size: int = 2,
        grad_averager_factory=None,
        grad_averager_opts: Optional[dict] = None,
        state_averager_opts: Optional[dict] = None,
        tracker_opts: Optional[dict] = None,
        shutdown_timeout: float = 5.0,
        chronic_failure_threshold: int = 5,
        checkpoint_dir: Optional[Any] = None,
        checkpoint_every: int = 1,
        checkpoint_keep_last: int = 3,
        blackbox_dir: Optional[Any] = None,
        verbose: bool = False,
    ):
        assert not (client_mode and auxiliary), "a peer is either a client or an auxiliary, not both"
        assert auxiliary or (params is not None and optimizer is not None), (
            "non-auxiliary peers must provide params and an optax optimizer"
        )
        self.dht, self.run_id = dht, run_id
        self.target_batch_size = target_batch_size
        self.batch_size_per_step = batch_size_per_step
        self.matchmaking_time, self.averaging_timeout = matchmaking_time, averaging_timeout
        self.load_state_timeout = load_state_timeout
        self.average_state_every = average_state_every
        self.use_local_updates = use_local_updates
        self.delay_optimizer_step = delay_optimizer_step or delay_grad_averaging
        self.delay_grad_averaging = delay_grad_averaging
        self.delay_state_averaging = delay_state_averaging
        assert not (self.delay_optimizer_step and use_local_updates), (
            "delayed updates apply to collaborative (gradient-averaging) mode"
        )
        self.client_mode, self.auxiliary = client_mode, auxiliary
        self.shutdown_timeout = shutdown_timeout
        self.verbose = verbose
        self.scheduled_grads: Optional[StepControl] = None
        self._step_lock = threading.Lock()
        self._update_executor: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="hm_dpu")
            if (self.delay_optimizer_step or delay_state_averaging)
            else None
        )
        self._pending_update: Optional[Future] = None
        # chronic-degradation tracking: every epoch that ends without a successful
        # swarm averaging round counts; after `chronic_failure_threshold` in a row
        # the condition escalates to ERROR and matchmaking backs off exponentially
        # (a persistently failing swarm must not silently train local SGD forever)
        self.chronic_failure_threshold = chronic_failure_threshold
        self._consecutive_failed_rounds = 0
        if blackbox_dir is not None:
            # arm BEFORE the averagers spin up so their first rounds spool too;
            # idempotent per directory (see arm_blackbox)
            from hivemind_tpu.telemetry.blackbox import arm_blackbox

            arm_blackbox(blackbox_dir, peer=str(dht.peer_id))

        averager_common = dict(
            target_group_size=target_group_size,
            min_group_size=min_group_size,
            min_matchmaking_time=matchmaking_time,
            client_mode=client_mode,
            auxiliary=auxiliary,
        )
        self.state_averager: Optional[TrainingStateAverager] = None
        if not auxiliary:
            state_opts = dict(state_averager_opts or {})
            state_opts.setdefault("delta_rule_averaging", delta_rule_averaging)
            # local-updates peers take many optax steps per epoch, so their step
            # counters must never be rewound to the epoch number
            state_opts.setdefault("count_equals_epoch", not use_local_updates)
            self.state_averager = TrainingStateAverager(
                dht=dht,
                optimizer=optimizer,
                params=params,
                prefix=f"{run_id}_state",
                start=True,
                compression=state_averaging_compression,
                state_compression=state_averaging_compression,
                **averager_common,
                **state_opts,
            )
        # crash-safe recovery (ISSUE 7): restore order is local-verified
        # checkpoint → swarm download (the catch-up path, triggered by the
        # tracker if the checkpoint is stale) → fresh initialization
        self.checkpoint_store: Optional[LocalCheckpointStore] = None
        self.checkpoint_every = max(1, int(checkpoint_every))
        self._checkpoint_executor: Optional[ThreadPoolExecutor] = None
        self._pending_checkpoint: Optional[Future] = None
        if checkpoint_dir is not None and not auxiliary:
            self.checkpoint_store = LocalCheckpointStore(
                checkpoint_dir, keep_last=checkpoint_keep_last
            )
            # serialize+fsync runs off the training thread; the state SNAPSHOT
            # is still taken synchronously so it is epoch-consistent
            self._checkpoint_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hm_ckpt"
            )
            restored_epoch = restore_from_local(self.state_averager, self.checkpoint_store)
            if restored_epoch is not None:
                # donors are ranked by sharing priority = epoch: a restored peer
                # should advertise what it actually holds
                self.state_averager.state_sharing_priority = restored_epoch
        self.grad_averager: Optional[GradientAverager] = None
        if not use_local_updates:
            tensors_like = (
                self.state_averager._host_state_tensors()[: len(self.state_averager._params_flat)]
                if self.state_averager is not None
                else []
            )
            if auxiliary:
                # aux peers know nothing about the model: bootstrap the gradient
                # schema from any working peer's averager state (VERDICT r1 item 7;
                # reference aux mode is schema-free)
                tensors_like = (grad_averager_opts or {}).pop("tensors_like", [])
                if not tensors_like:
                    tensors_like = self._bootstrap_grad_schema(
                        dht, f"{run_id}_grad_averager", timeout=load_state_timeout
                    )
            factory = grad_averager_factory if grad_averager_factory is not None else GradientAverager
            self.grad_averager = factory(
                tensors_like,
                dht=dht,
                prefix=f"{run_id}_grad_averager",
                start=True,
                compression=grad_compression,
                **averager_common,
                **(grad_averager_opts or {}),
            )
        self.tracker = ProgressTracker(
            dht, run_id, target_batch_size, client_mode=client_mode or auxiliary,
            **(tracker_opts or {}),
        )

    # ------------------------------------------------------------------ properties

    @property
    def params(self) -> Any:
        assert self.state_averager is not None
        return self.state_averager.params

    @property
    def local_epoch(self) -> int:
        return self.state_averager.local_epoch if self.state_averager is not None else self.tracker.global_epoch

    @property
    def ready_to_update_epoch(self) -> bool:
        return self.tracker.ready_to_update_epoch

    # ------------------------------------------------------------------ main entry

    def step(
        self,
        grads: Any = None,
        batch_size: Optional[int] = None,
    ) -> Any:
        """Report progress, accumulate gradients, and run the collaborative update
        when the swarm is ready. Returns the (possibly updated) parameter pytree."""
        # layer-5 span: the whole-step host timeline — a slow step's trace shows
        # WHICH child (catch-up, averaging round, state load) ate the time
        with _tracing_span("optimizer.step", peer=str(self.dht.peer_id), epoch=self.local_epoch):
            if self.auxiliary:
                self._auxiliary_step()
                return None
            assert self.state_averager is not None
            with self._step_lock:
                if self._should_load_state_from_peers():
                    self._catch_up_with_swarm()

                batch_size = batch_size if batch_size is not None else (self.batch_size_per_step or 1)
                if self.use_local_updates:
                    return self._local_updates_step(grads, batch_size)
                return self._collaborative_step(grads, batch_size)

    def _collaborative_step(self, grads: Any, batch_size: int) -> Any:
        assert self.grad_averager is not None and self.state_averager is not None
        if grads is not None:
            import jax

            grads_flat = jax.tree_util.tree_flatten(grads)[0] if not isinstance(grads, (list, tuple)) else list(grads)
            self.grad_averager.accumulate_grads_(grads_flat, batch_size)
        self.tracker.report_local_progress(self.local_epoch, self.grad_averager.local_samples_accumulated)
        self._maybe_schedule_gradient_averaging()
        if self.tracker.ready_to_update_epoch:
            if self.delay_optimizer_step:
                self._schedule_delayed_epoch_update()
            else:
                self._update_global_epoch()
        return self.state_averager.params

    def _local_updates_step(self, grads: Any, batch_size: int) -> Any:
        """Asynchronous mode: apply updates locally, average parameters periodically
        (reference use_local_updates, optimizer.py:143-145)."""
        assert self.state_averager is not None
        if grads is not None:
            # the compute lane of the step timeline (ISSUE 19): a delayed
            # state-averaging round overlapping these spans is the overlap
            # efficiency being measured
            with _tracing_span("optimizer.update", peer=str(self.dht.peer_id)):
                self.state_averager.apply_optimizer_step(grads)
        new_samples = self.tracker.local_progress.samples_accumulated + batch_size
        self.tracker.report_local_progress(self.local_epoch, new_samples)
        if self.tracker.ready_to_update_epoch:
            self.state_averager.local_epoch += 1
            if self.local_epoch % self.average_state_every == 0:
                if self.delay_state_averaging and self._update_executor is not None:
                    # overlap the round with further local steps; delta-rule
                    # averaging makes those concurrent steps survive the merge
                    if self._pending_update is None or self._pending_update.done():
                        self._finish_pending_update()
                        self._pending_update = self._update_executor.submit(
                            self.state_averager.do_averaging_round,
                            timeout=self.averaging_timeout,
                            scheduled_time=get_dht_time() + self._matchmaking_delay(),
                        )
                else:
                    self.state_averager.do_averaging_round(
                        timeout=self.averaging_timeout,
                        scheduled_time=get_dht_time() + self._matchmaking_delay(),
                    )
            self._maybe_save_checkpoint(self.local_epoch)
            _LEDGER.record_epoch(
                self.local_epoch,
                peer=str(self.dht.peer_id),
                num_peers=self.tracker.global_progress.num_peers,
            )
            self.tracker.update_epoch(self.local_epoch)
        return self.state_averager.params

    def _auxiliary_step(self) -> None:
        """Aux peers keep assisting gradient averaging rounds near epoch ends."""
        assert self.grad_averager is not None
        if self.tracker.ready_to_update_epoch:
            with contextlib.suppress(Exception):
                self.grad_averager.step(
                    weight=0.0, timeout=self.averaging_timeout,
                    scheduled_time=get_dht_time() + self._matchmaking_delay(),
                )
            self.tracker.update_epoch(self.tracker.global_epoch + 1)

    # ------------------------------------------------------------------ internals

    def _maybe_schedule_gradient_averaging(self) -> None:
        """Pre-schedule matchmaking so the group is ready the moment the swarm hits
        the target batch size (reference optimizer.py:559-567)."""
        assert self.grad_averager is not None
        if self.chronic_averaging_failure:
            # pre-scheduling re-declares in the DHT at full cadence every step; under
            # chronic failure only the (backed-off) step-time path may matchmake
            return
        eta = self.tracker.global_progress.eta_next_epoch - get_dht_time()
        if eta <= self.matchmaking_time * 2 and self._scheduled_control_invalid():
            scheduled_time = get_dht_time() + max(eta, 1e-2)
            self.scheduled_grads = self.grad_averager.schedule_step(
                scheduled_time=scheduled_time, timeout=self.averaging_timeout
            )
            logger.debug(f"pre-scheduled gradient averaging in {eta:.1f}s")

    def _scheduled_control_invalid(self) -> bool:
        control = self.scheduled_grads
        return control is None or control.done() or control.cancelled

    def _update_global_epoch(self) -> None:
        """Average gradients with the swarm, apply one optax update, advance the epoch
        (reference _update_global_epoch, optimizer.py:438-509)."""
        assert self.grad_averager is not None and self.state_averager is not None
        # a peer REJOINING after the swarm advanced lands ON the global epoch,
        # not past it (reference optimizer.py:462)
        next_epoch = max(self.local_epoch + 1, self.tracker.global_epoch)

        averaged_ok: Optional[bool] = None  # None = no round attempted (solo swarm)
        # step timeline (ISSUE 19): grads are ready HERE; everything between
        # this mark and the update landing is communication to hide
        _STEP_TIMELINE.note_grad_ready(str(self.dht.peer_id))
        if self.tracker.global_progress.num_peers > 1:
            averaged_ok = False
            control = None if self._scheduled_control_invalid() else self.scheduled_grads
            self.scheduled_grads = None
            try:
                # keep the accumulators until the update is applied: if averaging
                # fails we must fall back to the LOCAL gradients, not zeros
                self.grad_averager.step(
                    control=control,
                    weight=self.grad_averager.local_samples_accumulated,
                    timeout=self.averaging_timeout,
                    reset_accumulators=False,
                    scheduled_time=get_dht_time() + self._matchmaking_delay() if control is None else None,
                )
                averaged_ok = True
            except Exception as e:
                logger.warning(f"gradient averaging failed ({e!r}); applying local gradients")
        if not averaged_ok:
            # fall back to local gradients (reference optimizer.py:632-639)
            self.grad_averager.load_accumulators_into_averager_()

        with self.grad_averager.use_averaged_gradients() as averaged_grads:
            with _tracing_span("optimizer.update", peer=str(self.dht.peer_id), epoch=next_epoch):
                self.state_averager.apply_optimizer_step(list(averaged_grads))
        self.grad_averager.reset_accumulated_grads_()
        self._finish_epoch_transition(next_epoch, averaged_ok)

    # chronic counter/backoff/log members come from ChronicFailureTracking

    def _finish_epoch_transition(self, next_epoch: int, averaged_ok: Optional[bool]) -> None:
        """``averaged_ok``: True/False for an attempted swarm round, None when no
        round was attempted (num_peers <= 1 — a solo peer is healthy, not failing)."""
        assert self.state_averager is not None
        self._record_round_outcome(averaged_ok)
        self.state_averager.local_epoch = next_epoch
        if self.average_state_every and next_epoch % self.average_state_every == 0 and self.tracker.global_progress.num_peers > 1:
            self.state_averager.do_averaging_round(
                timeout=self.averaging_timeout,
                scheduled_time=get_dht_time() + self._matchmaking_delay(),
            )
        self.state_averager.state_sharing_priority = next_epoch
        # checkpoint AFTER the state-averaging round so the file holds the
        # swarm-averaged tensors this epoch actually produced
        self._maybe_save_checkpoint(next_epoch)
        # attribution ledger (ISSUE 8): close this epoch's record AFTER both
        # averaging rounds, so the rounds-since-last-epoch rollup covers them
        _LEDGER.record_epoch(
            next_epoch,
            peer=str(self.dht.peer_id),
            averaged_ok=averaged_ok,
            num_peers=self.tracker.global_progress.num_peers,
        )
        self.tracker.update_epoch(next_epoch)
        if self.verbose:
            logger.info(
                f"transitioned to epoch {next_epoch} "
                f"(averaged={averaged_ok}, peers={self.tracker.global_progress.num_peers})"
            )

    # ------------------------------------------------------------------ delayed (DPU)

    def _schedule_delayed_epoch_update(self) -> None:
        """Stage this epoch's gradients and hand the transition to the background
        thread; the caller keeps training on one-step-stale parameters
        (reference DPU, optimizer.py:87-88 + state_averager.py:478-574)."""
        assert self.grad_averager is not None and self._update_executor is not None
        if self._pending_update is not None and not self._pending_update.done():
            return  # previous transition still in flight; keep accumulating
        self._finish_pending_update()

        # stage NOW: later microbatches belong to the next epoch and must not leak
        # into the in-flight round (shared buffers hold this epoch's local average,
        # which doubles as the fallback if swarm averaging fails)
        self.grad_averager.load_accumulators_into_averager_()
        _STEP_TIMELINE.note_grad_ready(str(self.dht.peer_id))
        # weight 0 is correct for a peer with nothing accumulated: its zero buffers
        # must not dilute the group average (matches the synchronous path)
        weight = float(self.grad_averager.local_samples_accumulated)
        self.grad_averager.reset_accumulated_grads_()
        control = None if self._scheduled_control_invalid() else self.scheduled_grads
        self.scheduled_grads = None
        next_epoch = max(self.local_epoch + 1, self.tracker.global_epoch)
        self._pending_update = self._update_executor.submit(
            self._delayed_epoch_update, control, weight, next_epoch
        )

    def _delayed_epoch_update(self, control, weight: float, next_epoch: int) -> None:
        assert self.grad_averager is not None and self.state_averager is not None
        averaged_ok: Optional[bool] = None  # None = no round attempted (solo swarm)
        if self.tracker.global_progress.num_peers > 1:
            averaged_ok = False
            try:
                self.grad_averager.step(
                    control=control,
                    weight=weight,
                    timeout=self.averaging_timeout,
                    load_accumulators=False,
                    scheduled_time=get_dht_time() + self._matchmaking_delay() if control is None else None,
                )
                averaged_ok = True
            except Exception as e:
                logger.warning(f"delayed gradient averaging failed ({e!r}); applying local gradients")
        with self.grad_averager.use_averaged_gradients() as averaged_grads:
            with _tracing_span("optimizer.update", peer=str(self.dht.peer_id), epoch=next_epoch):
                self.state_averager.apply_optimizer_step(list(averaged_grads))
        self._finish_epoch_transition(next_epoch, averaged_ok)

    def _finish_pending_update(self, timeout: Optional[float] = None) -> None:
        """Surface exceptions from a completed (or awaited) background transition."""
        pending, self._pending_update = self._pending_update, None
        if pending is None:
            return
        try:
            pending.result(timeout)
        except Exception as e:
            # the whole background transition died (not just its averaging round):
            # count it toward chronic degradation and escalate past the threshold
            self._record_round_outcome(False)
            log = logger.error if self.chronic_averaging_failure else logger.warning
            log(f"background epoch transition failed "
                f"({self._consecutive_failed_rounds} consecutive): {e!r}")

    def _should_load_state_from_peers(self) -> bool:
        """One-epoch grace (reference optimizer.py:655-673): a peer overlapping its
        own transition (DPU) or trailing by exactly one epoch will catch up by itself;
        only a wider gap warrants downloading a peer's state."""
        if self._pending_update is not None and not self._pending_update.done():
            return False  # our own transition is mid-flight, not a straggler
        # one-epoch grace for EVERY mode (reference optimizer.py:654-672): the
        # first peer to see enough samples transitions and restarts the count —
        # a peer observing global == local + 1 is witnessing normal network
        # asynchrony and must transition itself (the tracker reports it ready),
        # not discard its progress and download state
        return self.local_epoch < self.tracker.global_epoch - 1

    def _catch_up_with_swarm(self) -> None:
        """We are behind the swarm: adopt a peer's state
        (reference _should_load_state_from_peers + load_state_from_peers)."""
        assert self.state_averager is not None
        global_epoch = self.tracker.global_epoch
        logger.info(
            f"local epoch {self.local_epoch} is behind the swarm ({global_epoch}); "
            f"downloading state"
        )
        # min_epoch: donors serving state older than the tracker's published
        # progress are rejected at their manifest, never adopted (ISSUE 7). The
        # one-epoch grace mirrors the protocol's own transition asynchrony: the
        # peer whose report SET global_epoch may have crashed, leaving every
        # live donor one epoch behind — adopting global-1 still lands us in the
        # normal grace band (we transition ourselves next ready step), whereas
        # zero grace would reject the whole swarm and fast-forward with STALE
        # local params, which is strictly worse
        if self.state_averager.load_full_state_from_peers(
            timeout=self.load_state_timeout, min_epoch=max(0, global_epoch - 1)
        ):
            if self.grad_averager is not None:
                self.grad_averager.reset_accumulated_grads_()
            # a crash right after catch-up should not redo the download
            self._maybe_save_checkpoint(self.local_epoch, force=True)
        else:
            # could not download: adopt the epoch NUMBER to avoid re-triggering
            # forever — but this peer now claims training it never did, so say
            # it loudly and count it (ISSUE 7 satellite): chronic occurrences
            # mean the swarm's recovery path is broken, not merely flaky
            _EPOCH_ADOPTED_WITHOUT_STATE.inc()
            logger.error(
                f"state download failed; fast-forwarding local epoch "
                f"{self.local_epoch} -> {self.tracker.global_epoch} WITHOUT adopting state "
                f"(parameters keep their pre-catch-up values)"
            )
            self.state_averager.local_epoch = self.tracker.global_epoch

    def _maybe_save_checkpoint(self, epoch: int, force: bool = False) -> None:
        """Publish a local checkpoint on the configured epoch cadence (crash-safe:
        recovery.LocalCheckpointStore). The epoch-consistent snapshot is captured
        here; serialize+write+fsync runs on the checkpoint executor so the
        training step is never blocked on disk (``force`` — shutdown / just after
        a catch-up — saves synchronously for durability). A save still in flight
        when the next cadence hits is not queued behind: that epoch is skipped.
        Failures never fail the step — a peer with a broken disk keeps training,
        loudly."""
        if self.checkpoint_store is None or self.state_averager is None:
            return
        if not force and epoch % self.checkpoint_every != 0:
            return
        if self._pending_checkpoint is not None and self._pending_checkpoint.done():
            pending, self._pending_checkpoint = self._pending_checkpoint, None
            try:
                pending.result(0)
            except Exception as e:
                logger.warning(f"background checkpoint save failed: {e!r}")
        if not force and self._pending_checkpoint is not None:
            # decided BEFORE the snapshot: copying the full state just to throw
            # it away would hold the state lock on the training thread for nothing
            logger.debug(f"checkpoint save at epoch {epoch} skipped: previous save in flight")
            return
        if force and self._pending_checkpoint is not None:
            # a forced save must not run concurrently with the background writer:
            # two interleaved save()/prune() passes could sweep each other's
            # temp files, and the forced save must end up the durable one
            pending, self._pending_checkpoint = self._pending_checkpoint, None
            try:
                pending.result(60)
            except Exception as e:
                logger.warning(f"background checkpoint save failed: {e!r}")
        try:
            state = self.state_averager.state_dict()
        except Exception as e:
            logger.warning(f"checkpoint snapshot at epoch {epoch} failed: {e!r}")
            return

        def _write() -> None:
            with _tracing_span("state_sync.checkpoint", epoch=epoch):
                self.checkpoint_store.save(state)

        if force or self._checkpoint_executor is None:
            try:
                _write()
            except Exception as e:
                logger.warning(f"checkpoint save at epoch {epoch} failed: {e!r}")
        else:
            self._pending_checkpoint = self._checkpoint_executor.submit(_write)

    @staticmethod
    def _bootstrap_grad_schema(dht: DHT, prefix: str, timeout: Optional[float]):
        """Learn the gradient tensor schema from any peer's running gradient averager
        (its shared state download); retries until the swarm has one."""
        import time as time_module

        from hivemind_tpu.averaging.averager import DecentralizedAverager

        deadline = get_dht_time() + (timeout or 60.0)
        while True:
            with contextlib.suppress(Exception):
                result = DecentralizedAverager.download_state_from_swarm(
                    dht, prefix, timeout=min(15.0, timeout or 15.0)
                )
                if result is not None and result[1]:
                    logger.info(f"bootstrapped gradient schema: {len(result[1])} tensors")
                    return [np.zeros(t.shape, np.float32) for t in result[1]]
            if get_dht_time() >= deadline:
                raise RuntimeError(
                    f"auxiliary peer could not learn the gradient schema from the swarm "
                    f"under {prefix!r} within {timeout}s (no peer sharing state yet?)"
                )
            time_module.sleep(1.0)

    def load_state_from_peers(self, timeout: Optional[float] = None) -> bool:
        assert self.state_averager is not None
        return self.state_averager.load_full_state_from_peers(timeout=timeout or self.load_state_timeout)

    # ------------------------------------------------------------------ checkpointing

    def state_dict(self) -> dict:
        """User-level checkpoint with the epoch embedded
        (reference optimizer.py:719-727)."""
        assert self.state_averager is not None
        return self.state_averager.state_dict()

    def load_state_dict(self, state: dict) -> None:
        """Restore a checkpoint: tensors + epoch, with LR schedules replayed to the
        restored epoch (reference state_averager.py:700-704)."""
        assert self.state_averager is not None
        self.state_averager.load_state_dict(state)
        if self.grad_averager is not None:
            self.grad_averager.reset_accumulated_grads_()

    def shutdown(self) -> None:
        if self._pending_update is not None:
            self._finish_pending_update(timeout=self.averaging_timeout)
        if self._update_executor is not None:
            self._update_executor.shutdown(wait=True)
        # final checkpoint: a clean shutdown restores exactly where it stopped
        # (drain the background writer first so the forced save is the newest)
        if self._checkpoint_executor is not None:
            self._checkpoint_executor.shutdown(wait=True)
            self._pending_checkpoint = None
            self._checkpoint_executor = None
        self._maybe_save_checkpoint(self.local_epoch, force=True)
        self.tracker.shutdown()
        if self.scheduled_grads is not None:
            self.scheduled_grads.cancel()
        if self.grad_averager is not None:
            self.grad_averager.shutdown()
        if self.state_averager is not None:
            self.state_averager.shutdown()

    def __repr__(self):
        return (
            f"Optimizer(run_id={self.run_id!r}, epoch={self.local_epoch}, "
            f"local_updates={self.use_local_updates}, client={self.client_mode}, aux={self.auxiliary})"
        )
