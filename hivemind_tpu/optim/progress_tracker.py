"""Swarm-wide training progress accounting (capability parity: reference
hivemind/optim/progress_tracker.py).

Every peer publishes LocalTrainingProgress (signed with its key) as a subkey of
``{run_id}_progress``; the tracker aggregates all records into GlobalTrainingProgress
and estimates when the swarm will finish the current virtual epoch. Epoch-based
accounting makes hyperparameters invariant to swarm size (reference optimizer.py:63-69)."""

from __future__ import annotations

import asyncio
import contextlib
import threading
from typing import Dict, Optional

import pydantic

from hivemind_tpu.dht import DHT
from hivemind_tpu.dht.crypto import Ed25519SignatureValidator
from hivemind_tpu.dht.schema import BytesWithEd25519PublicKey, SchemaValidator
from hivemind_tpu.utils.crypto import Ed25519PrivateKey
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.loop import LoopRunner, get_loop_runner
from hivemind_tpu.utils.performance_ema import PerformanceEMA
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time

logger = get_logger(__name__)

# layer-4 telemetry (docs/observability.md). Hot path: report_local_progress runs
# once per optimizer step, so the label-less children are bound once here —
# each update is one lock + one float store.
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY

_G_LOCAL_EPOCH = _TELEMETRY.gauge(
    "hivemind_optim_local_epoch", "this peer's local epoch"
).labels()
_G_LOCAL_SAMPLES = _TELEMETRY.gauge(
    "hivemind_optim_local_samples_accumulated", "samples this peer accumulated toward the current epoch"
).labels()
_G_GLOBAL_EPOCH = _TELEMETRY.gauge(
    "hivemind_optim_global_epoch", "swarm-wide epoch (max over peers)"
).labels()
_G_GLOBAL_SAMPLES = _TELEMETRY.gauge(
    "hivemind_optim_global_samples_accumulated", "swarm-wide samples toward target_batch_size"
).labels()
_G_NUM_PEERS = _TELEMETRY.gauge(
    "hivemind_optim_num_peers", "peers reporting progress on this run"
).labels()
_G_SAMPLES_PER_SECOND = _TELEMETRY.gauge(
    "hivemind_optim_swarm_samples_per_second", "aggregate swarm throughput estimate"
).labels()


class LocalTrainingProgress(pydantic.BaseModel):
    peer_id: bytes
    epoch: int
    samples_accumulated: int
    samples_per_second: float
    time: float
    client_mode: bool

    @pydantic.field_validator("epoch", "samples_accumulated")
    @classmethod
    def _non_negative(cls, value):
        assert value >= 0
        return value

    @pydantic.field_validator("samples_per_second")
    @classmethod
    def _finite_positive(cls, value):
        assert value >= 0 and value == value  # not NaN
        return value


class GlobalTrainingProgress(pydantic.BaseModel):
    global_epoch: int
    samples_accumulated: int
    target_batch_size: int
    num_peers: int
    num_clients: int
    eta_next_epoch: float
    next_fetch_time: float

    @property
    def ready_to_update_epoch(self) -> bool:
        return (
            self.samples_accumulated >= self.target_batch_size
            or get_dht_time() >= self.eta_next_epoch
        )


class ProgressTracker:
    """Publishes local progress and aggregates the swarm's; runs reporter + fetcher
    tasks on the shared event loop (the reference uses a thread,
    progress_tracker.py:44-363)."""

    def __init__(
        self,
        dht: DHT,
        prefix: str,
        target_batch_size: int,
        *,
        client_mode: bool = False,
        min_refresh_period: float = 0.5,
        max_refresh_period: float = 10.0,
        default_refresh_period: float = 3.0,
        expected_drift_peers: float = 3.0,
        expected_drift_rate: float = 0.2,
        performance_ema_alpha: float = 0.1,
        metadata_expiration: float = 60.0,
        private_key: Optional[Ed25519PrivateKey] = None,
        start: bool = True,
        loop_runner: Optional[LoopRunner] = None,
    ):
        self.dht, self.prefix = dht, prefix
        self.target_batch_size = target_batch_size
        self.client_mode = client_mode
        self.min_refresh_period, self.max_refresh_period = min_refresh_period, max_refresh_period
        self.default_refresh_period = default_refresh_period
        self.expected_drift_peers, self.expected_drift_rate = expected_drift_peers, expected_drift_rate
        self.metadata_expiration = metadata_expiration
        self.performance_ema = PerformanceEMA(alpha=performance_ema_alpha, paused=True)
        self._runner = loop_runner if loop_runner is not None else get_loop_runner()

        if private_key is None:
            # sign with THIS peer's transport identity (not the process-wide singleton:
            # several in-process peers would collide on one subkey)
            private_key = self._runner.run_coroutine(dht.replicate_p2p()).identity
        signature_validator = Ed25519SignatureValidator(private_key)
        progress_key_name = f"{prefix}_progress"
        schema = pydantic.create_model(
            "_TrackerSchema",
            **{progress_key_name: (Dict[BytesWithEd25519PublicKey, LocalTrainingProgress], ...)},
        )
        self.dht.add_validators([SchemaValidator(schema, allow_extra_keys=True), signature_validator])
        self._local_public_key = signature_validator.local_public_key
        self.progress_key = progress_key_name

        self.local_progress = LocalTrainingProgress(
            peer_id=dht.peer_id.to_bytes(),
            epoch=0,
            samples_accumulated=0,
            samples_per_second=0.0,
            time=get_dht_time(),
            client_mode=client_mode,
        )
        self.global_progress = GlobalTrainingProgress(
            global_epoch=0,
            samples_accumulated=0,
            target_batch_size=target_batch_size,
            num_peers=0,
            num_clients=0,
            eta_next_epoch=get_dht_time() + max_refresh_period,
            next_fetch_time=get_dht_time(),
        )
        self._lock = threading.Lock()
        self._report_event: Optional[asyncio.Event] = None
        self._fetch_soon: Optional[asyncio.Event] = None
        self._reporter_task = None
        self._fetcher_task = None
        self.shutdown_requested = False
        if start:
            self._runner.run_coroutine(self._start_tasks())

    async def _start_tasks(self) -> None:
        self._report_event = asyncio.Event()
        self._fetch_soon = asyncio.Event()
        self._reporter_task = spawn(self._reporter(), name="progress_tracker.reporter")
        self._fetcher_task = spawn(self._fetcher(), name="progress_tracker.fetcher")

    # ------------------------------------------------------------------ local side

    @property
    def global_epoch(self) -> int:
        return self.global_progress.global_epoch

    @property
    def ready_to_update_epoch(self) -> bool:
        # a peer whose swarm already advanced transitions ITSELF right away
        # (reference progress_tracker.py:128-134) — without this clause, peers
        # that see a groupmate bump the epoch first would mistake the normal
        # lack of network synchrony for having fallen behind
        return (
            self.global_progress.global_epoch > self.local_progress.epoch
            or self.global_progress.ready_to_update_epoch
        )

    def report_local_progress(self, local_epoch: int, samples_accumulated: int, update_ema: bool = True) -> None:
        """Update the local record and wake the reporter
        (reference progress_tracker.py:153-168)."""
        with self._lock:
            previous_local_samples = self.local_progress.samples_accumulated
            extra_samples = samples_accumulated - previous_local_samples
            if update_ema and extra_samples > 0:
                if self.performance_ema.paused:
                    self.performance_ema.paused = False
                    self.performance_ema.reset_timer()
                else:
                    self.performance_ema.update(extra_samples)
            self.local_progress = LocalTrainingProgress(
                peer_id=self.dht.peer_id.to_bytes(),
                epoch=local_epoch,
                samples_accumulated=samples_accumulated,
                samples_per_second=self.performance_ema.samples_per_second,
                time=get_dht_time(),
                client_mode=self.client_mode,
            )
        _G_LOCAL_EPOCH.set(local_epoch)
        _G_LOCAL_SAMPLES.set(samples_accumulated)
        self._wake_reporter()
        # our own progress may be what completes the epoch (always true for small
        # swarms): re-aggregate NOW instead of sleeping out the adaptive refresh —
        # otherwise a lone peer stalls for max_refresh_period after every report.
        # The snapshot already counts our PREVIOUS contribution, so subtract it,
        # or every tail-of-epoch report would re-wake the fetcher (a fetch storm).
        global_snapshot = self.global_progress
        if local_epoch != global_snapshot.global_epoch:
            # a straggler's samples are not part of the global sum (and ours were
            # not subtracted from it): the arithmetic below would either storm the
            # fetcher or never fire, so the early wake only applies when aligned
            return
        remote_samples = max(global_snapshot.samples_accumulated - previous_local_samples, 0)
        if not global_snapshot.ready_to_update_epoch and (
            samples_accumulated + remote_samples >= global_snapshot.target_batch_size
        ):
            self._wake_fetcher()

    def update_epoch(self, new_epoch: int) -> None:
        with self._lock:
            self.local_progress = self.local_progress.model_copy(
                update=dict(epoch=new_epoch, samples_accumulated=0, time=get_dht_time())
            )
            if new_epoch > self.global_progress.global_epoch:
                self.global_progress.global_epoch = new_epoch
                self.global_progress.samples_accumulated = 0
            self.global_progress.next_fetch_time = get_dht_time()
        self.performance_ema.paused = True
        self._wake_reporter()
        self._wake_fetcher()

    def _wake_reporter(self) -> None:
        if self._report_event is not None:
            self._runner.call_soon(self._report_event.set)

    def _wake_fetcher(self) -> None:
        if self._fetch_soon is not None:
            self._runner.call_soon(self._fetch_soon.set)

    # ------------------------------------------------------------------ tasks

    async def _reporter(self) -> None:
        """Store the local progress record whenever it changes (plus heartbeats)."""
        assert self._report_event is not None
        while not self.shutdown_requested:
            # clear BEFORE snapshotting: an update arriving mid-store must survive
            # into the next iteration, not be silently dropped
            self._report_event.clear()  # lint: single-writer — reporter clears its own wake event
            with contextlib.suppress(Exception):
                with self._lock:
                    record = self.local_progress
                await self.dht.node.store(
                    self.progress_key,
                    subkey=self._local_public_key,
                    value=record.model_dump(),
                    expiration_time=get_dht_time() + self.metadata_expiration,
                )
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._report_event.wait(), timeout=self.metadata_expiration / 2)

    async def _fetcher(self) -> None:
        """Aggregate everyone's records into GlobalTrainingProgress
        (reference progress_tracker.py:231-273)."""
        while not self.shutdown_requested:
            assert self._fetch_soon is not None
            wait_time = max(0.0, self.global_progress.next_fetch_time - get_dht_time())
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._fetch_soon.wait(), timeout=wait_time)
            self._fetch_soon.clear()  # lint: single-writer — fetcher clears its own wake event
            with contextlib.suppress(Exception):
                await self._fetch_global_progress()

    async def _fetch_global_progress(self) -> None:
        response = await self.dht.node.get(self.progress_key, latest=True)
        records = []
        current_time = get_dht_time()
        if response is not None and isinstance(response.value, dict):
            for _subkey, entry in response.value.items():
                try:
                    record = LocalTrainingProgress.model_validate(entry.value)
                    if current_time - record.time <= self.metadata_expiration:
                        records.append(record)
                except Exception:
                    continue
        with self._lock:
            local = self.local_progress
        # the in-memory record is always fresher than the DHT's copy of ourselves
        # (the reporter may not have re-stored yet): never aggregate a stale self
        records = [r for r in records if r.peer_id != local.peer_id]
        records.append(local)

        global_epoch = max((r.epoch for r in records), default=local.epoch)
        samples = sum(r.samples_accumulated for r in records if r.epoch == global_epoch)
        samples_per_second = sum(r.samples_per_second for r in records if r.epoch == global_epoch) or 1e-9
        num_peers = len(records)
        num_clients = sum(r.client_mode for r in records)
        remaining = max(0, self.target_batch_size - samples)
        eta_seconds = remaining / samples_per_second
        # adaptive refresh: fetch more often as the epoch end approaches, accounting
        # for expected peer churn (reference progress_tracker.py:321-331)
        drift = self.expected_drift_peers + self.expected_drift_rate * num_peers
        refresh = max(
            self.min_refresh_period,
            min(self.max_refresh_period, eta_seconds / max(drift, 1.0)),
        )
        with self._lock:
            self.global_progress = GlobalTrainingProgress(
                global_epoch=global_epoch,
                samples_accumulated=samples,
                target_batch_size=self.target_batch_size,
                num_peers=num_peers,
                num_clients=num_clients,
                eta_next_epoch=get_dht_time() + eta_seconds,
                next_fetch_time=get_dht_time() + refresh,
            )
        _G_GLOBAL_EPOCH.set(global_epoch)
        _G_GLOBAL_SAMPLES.set(samples)
        _G_NUM_PEERS.set(num_peers)
        _G_SAMPLES_PER_SECOND.set(samples_per_second)

    async def fetch_global_progress_now(self) -> GlobalTrainingProgress:
        await self._fetch_global_progress()
        return self.global_progress

    def shutdown(self, timeout: float = 2.0) -> None:
        self.shutdown_requested = True
        self._wake_reporter()
        self._wake_fetcher()

        async def _cancel():
            for task in (self._reporter_task, self._fetcher_task):
                if task is not None:
                    task.cancel()

        with contextlib.suppress(Exception):
            self._runner.run_coroutine(_cancel(), return_future=True).result(timeout)
