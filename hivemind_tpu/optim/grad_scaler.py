"""GradScaler analog (capability parity: reference hivemind/optim/grad_scaler.py:25-127).

DELIBERATE DEVIATION: the reference exists because fp16 CUDA training needs dynamic
loss scaling synchronized with global (epoch) steps. TPU training runs bf16, whose
exponent range matches fp32 — no loss scaling is needed — so this class is an
API-compatible passthrough that only tracks overflow statistics (useful when users
port fp16 recipes). It keeps the hivemind-specific contract: unscale/update are
deferred to global optimizer steps."""

from __future__ import annotations

import threading
from typing import Any, Optional

import numpy as np

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class GradScaler:
    def __init__(self, init_scale: float = 1.0, enabled: bool = True):
        if init_scale != 1.0:
            logger.warning(
                "bf16 TPU training needs no loss scaling; GradScaler runs with scale=1 "
                "(fp16-style dynamic scaling is a no-op here by design)"
            )
        self._enabled = enabled
        self._found_inf = False
        self._lock = threading.RLock()

    def scale(self, value):
        return value  # scale is always 1 on TPU/bf16

    def unscale_(self, grads) -> bool:
        """Record non-finite gradients (returns True if grads are clean)."""
        with self._lock:
            import jax

            leaves = jax.tree_util.tree_leaves(grads)
            self._found_inf = any(not bool(np.isfinite(np.asarray(l)).all()) for l in leaves)
            return not self._found_inf

    def step(self, apply_fn, *args, **kwargs):
        """Run the optimizer update unless the last unscale_ found inf/nan."""
        with self._lock:
            if self._found_inf:
                logger.warning("skipping optimizer step: non-finite gradients")
                return None
            return apply_fn(*args, **kwargs)

    def update(self) -> None:
        with self._lock:
            self._found_inf = False

    def get_scale(self) -> float:
        return 1.0  # bf16: scaling is always identity

    @property
    def found_inf(self) -> bool:
        return self._found_inf
