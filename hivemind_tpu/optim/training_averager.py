"""TrainingAverager: the legacy simple averager — average parameters and/or gradients
after each local step, no epoch accounting (capability parity: reference
hivemind/optim/training_averager.py:18-252)."""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

import numpy as np

from hivemind_tpu.averaging.averager import DecentralizedAverager
from hivemind_tpu.compression.base import as_numpy
from hivemind_tpu.dht import DHT
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class TrainingAverager(DecentralizedAverager):
    """:param get_tensors_fn: callable returning the CURRENT list of arrays to average
        (e.g. params flat + grads flat); results are handed to ``set_tensors_fn``"""

    def __init__(
        self,
        *,
        dht: DHT,
        get_tensors_fn,
        set_tensors_fn,
        prefix: str,
        average_parameters: bool = True,
        average_gradients: bool = False,
        **kwargs,
    ):
        self.get_tensors_fn, self.set_tensors_fn = get_tensors_fn, set_tensors_fn
        self.average_parameters, self.average_gradients = average_parameters, average_gradients
        self.local_step = 0
        self._step_lock = threading.Lock()
        initial = [np.asarray(as_numpy(t), np.float32) for t in get_tensors_fn()]
        super().__init__(averaged_tensors=initial, dht=dht, prefix=prefix, **kwargs)

    def average_step(self, weight: float = 1.0, timeout: Optional[float] = None, **kwargs):
        """Load current tensors, run one averaging round, write the averages back
        (reference TrainingAverager.step)."""
        with self._step_lock:
            current = [np.asarray(as_numpy(t), np.float32) for t in self.get_tensors_fn()]
            with self.get_tensors() as tensors:
                for buffer, fresh in zip(tensors, current):
                    np.copyto(buffer, fresh)
            try:
                gathered = self.step(weight=weight, timeout=timeout, **kwargs)
            except Exception as e:
                logger.warning(f"averaging step failed: {e!r}")
                return None
            with self.get_tensors() as tensors:
                self.set_tensors_fn([t.copy() for t in tensors])
            self.local_step += 1
            return gathered
