"""TrainingStateAverager: owns the optax optimizer + parameters and periodically
averages them with peers (capability parity: reference hivemind/optim/state_averager.py).

jax-first: the canonical train state (params + optax state) lives as device arrays;
the optimizer update is a jitted pure function. The reference's CPU-offload machinery
(offload_optimizer / reuse_tensors, state_averager.py:37-120) has no analog here —
host staging IS the transport path: averaging rounds device_get the state, all-reduce
it over the network, and device_put it back. Epoch-keyed schedules come for free:
optax schedules see the update count, and one optimizer step == one epoch."""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.averaging.averager import DecentralizedAverager
from hivemind_tpu.compression.base import as_numpy
from hivemind_tpu.dht import DHT
from hivemind_tpu.optim.recovery import _STATE_RESTORES
from hivemind_tpu.telemetry.device import record_transfer
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.profiling import tracked_jit

logger = get_logger(__name__)


class TrainingStateAverager(DecentralizedAverager):
    """Averages model parameters (and optionally optimizer statistics) across peers.

    :param optimizer: an optax.GradientTransformation
    :param params: the initial parameter pytree (jax arrays or numpy)
    :param average_opt_statistics: also average float optimizer-state leaves (e.g.
        Adam's mu/nu) so joining peers inherit momentum
    :param extra_tensors: additional arrays averaged and shared with state downloads
    :param delta_rule_averaging: apply each averaging round's result as a DELTA
        (average − pre-round snapshot) onto the CURRENT state instead of overwriting
        it, so optimizer steps taken concurrently with the round are not clobbered —
        required for delayed/local updates (reference state_averager.py:73-74)
    """

    def __init__(
        self,
        *,
        dht: DHT,
        optimizer,
        params: Any,
        prefix: str,
        average_opt_statistics: bool = True,
        extra_tensors: Sequence = (),
        delta_rule_averaging: bool = False,
        count_equals_epoch: bool = True,
        **kwargs,
    ):
        import jax

        self.optax_optimizer = optimizer
        self.delta_rule_averaging = delta_rule_averaging
        self.count_equals_epoch = count_equals_epoch
        params_flat, self._params_treedef = jax.tree_util.tree_flatten(params)
        self._params_flat = [jax.numpy.asarray(p) for p in params_flat]
        self.opt_state = optimizer.init(jax.tree_util.tree_unflatten(self._params_treedef, self._params_flat))
        self.average_opt_statistics = average_opt_statistics
        self.extra_tensors = [np.array(as_numpy(t), copy=True) for t in extra_tensors]
        self.local_epoch = 0
        self._state_lock = threading.Lock()

        opt_leaves, self._opt_treedef = jax.tree_util.tree_flatten(self.opt_state)
        self._averaged_opt_indices = [
            i
            for i, leaf in enumerate(opt_leaves)
            if average_opt_statistics
            and hasattr(leaf, "dtype")
            and np.issubdtype(np.asarray(leaf).dtype, np.floating)
            and np.asarray(leaf).ndim >= 1
        ]

        @tracked_jit(site="state_averager.apply")
        def _apply(params_flat, opt_state, grads_flat):
            params_tree = jax.tree_util.tree_unflatten(self._params_treedef, params_flat)
            grads_tree = jax.tree_util.tree_unflatten(self._params_treedef, grads_flat)
            updates, new_opt_state = optimizer.update(grads_tree, opt_state, params_tree)
            import optax

            new_params = optax.apply_updates(params_tree, updates)
            return jax.tree_util.tree_flatten(new_params)[0], new_opt_state

        self._jitted_apply = _apply

        averaged = self._host_state_tensors()
        super().__init__(averaged_tensors=averaged, dht=dht, prefix=prefix, **kwargs)

    # ------------------------------------------------------------------ state access

    @property
    def params(self) -> Any:
        import jax

        return jax.tree_util.tree_unflatten(self._params_treedef, self._params_flat)

    @property
    def params_flat(self) -> List:
        return list(self._params_flat)

    def _opt_leaves(self) -> list:
        import jax

        return jax.tree_util.tree_flatten(self.opt_state)[0]

    def _host_state_tensors(self) -> List[np.ndarray]:
        """The averageable view: params + chosen optimizer statistics + extras."""
        tensors = [np.asarray(as_numpy(p), dtype=np.float32) for p in self._params_flat]
        opt_leaves = self._opt_leaves()
        tensors += [np.asarray(as_numpy(opt_leaves[i]), dtype=np.float32) for i in self._averaged_opt_indices]
        tensors += [np.asarray(t, dtype=np.float32) for t in self.extra_tensors]
        # host staging IS the transport path (module docstring): every round
        # device_gets the whole averageable state — the d2h side of ISSUE 19's
        # transfer accounting on the averaging boundary
        record_transfer(sum(t.nbytes for t in tensors), "device_to_host")
        return tensors

    def _load_host_state_tensors(self, tensors: List[np.ndarray]) -> None:
        """Inverse of _host_state_tensors: write averaged values back to the device
        state, preserving original dtypes."""
        import jax
        import jax.numpy as jnp

        n_params = len(self._params_flat)
        n_opt = len(self._averaged_opt_indices)
        assert len(tensors) >= n_params + n_opt, "state tensor count mismatch"
        record_transfer(sum(int(t.nbytes) for t in tensors), "host_to_device")
        with self._state_lock:
            self._params_flat = [
                jnp.asarray(tensor, dtype=p.dtype)
                for tensor, p in zip(tensors[:n_params], self._params_flat)
            ]
            opt_leaves = self._opt_leaves()
            for slot, tensor in zip(self._averaged_opt_indices, tensors[n_params : n_params + n_opt]):
                opt_leaves[slot] = jnp.asarray(tensor, dtype=np.asarray(opt_leaves[slot]).dtype)
            self.opt_state = jax.tree_util.tree_unflatten(self._opt_treedef, opt_leaves)
            for extra, tensor in zip(self.extra_tensors, tensors[n_params + n_opt :]):
                np.copyto(extra, tensor.reshape(extra.shape))

    # ------------------------------------------------------------------ optimization

    def apply_optimizer_step(self, grads: Any) -> None:
        """One jitted optax update. ``grads`` may be a pytree matching params, or a
        flat list of arrays (e.g. the averaged-gradient buffers)."""
        import jax

        if isinstance(grads, (list, tuple)) and len(grads) == len(self._params_flat):
            grads_flat = [
                jax.numpy.asarray(g, dtype=p.dtype) for g, p in zip(grads, self._params_flat)
            ]
        else:
            grads_flat = [
                jax.numpy.asarray(g, dtype=p.dtype)
                for g, p in zip(jax.tree_util.tree_flatten(grads)[0], self._params_flat)
            ]
        with self._state_lock:
            self._params_flat, self.opt_state = self._jitted_apply(
                self._params_flat, self.opt_state, grads_flat
            )

    def do_averaging_round(self, timeout: Optional[float] = None, **kwargs) -> bool:
        """Stage state to host, average with the group, load it back. Returns True on
        success (reference state_averager averaging_round path).

        With ``delta_rule_averaging``, the result lands as ``current + (average −
        snapshot)``: local optimizer steps that ran while the round was in flight
        survive (reference state_averager.py:73-74,595-612)."""
        snapshot = self._host_state_tensors()
        with self.get_tensors() as tensors:
            for tensor, fresh in zip(tensors, snapshot):
                np.copyto(tensor, fresh)
        try:
            result = self.step(timeout=timeout, wait=True, **kwargs)
        except Exception as e:
            logger.warning(f"state averaging round failed: {e!r}")
            return False
        if result is None:
            return False
        with self.get_tensors() as tensors:
            averaged = [t.copy() for t in tensors]
        if self.delta_rule_averaging:
            current = self._host_state_tensors()
            merged = [cur + (avg - snap) for cur, avg, snap in zip(current, averaged, snapshot)]
            self._load_host_state_tensors(merged)
        else:
            self._load_host_state_tensors(averaged)
        return True

    # ------------------------------------------------------------------ schedules

    def replay_schedule_to_epoch(self, epoch: int) -> None:
        """Fast-forward optax step counters to ``epoch`` so epoch-keyed schedules
        (LR warmup/decay) resume at the right point after adopting a peer's params
        (reference state_averager.py:700-704 replays scheduler.step() local_epoch
        times; optax counters jump directly). Only scalar integer leaves whose field
        is named ``count`` are touched — the optax convention for step counters.

        Valid ONLY under the collaborative convention one optimizer step == one
        epoch; local-updates peers take many steps per epoch, so their counters are
        preserved (gated by ``count_equals_epoch``)."""
        if not self.count_equals_epoch:
            return
        self._set_opt_counts([epoch])

    @staticmethod
    def _is_count_leaf(key_path, leaf) -> bool:
        return bool(
            key_path
            and getattr(key_path[-1], "name", None) == "count"
            and hasattr(leaf, "dtype")
            and np.issubdtype(np.asarray(leaf).dtype, np.integer)
            and np.asarray(leaf).ndim == 0
        )

    def _set_opt_counts(self, values: Sequence[int]) -> None:
        """Overwrite the optax count leaves in flatten order; a single value is
        broadcast to every counter."""
        import jax
        import jax.numpy as jnp

        with self._state_lock:
            flat, _ = jax.tree_util.tree_flatten_with_path(self.opt_state)
            new_leaves, index = [], 0
            for key_path, leaf in flat:
                if self._is_count_leaf(key_path, leaf):
                    value = values[index] if index < len(values) else values[-1]
                    new_leaves.append(jnp.asarray(value, dtype=leaf.dtype))
                    index += 1
                else:
                    new_leaves.append(leaf)
            self.opt_state = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(self.opt_state), new_leaves
            )

    # ------------------------------------------------------------------ checkpointing

    def state_dict(self) -> dict:
        """Serializable snapshot: epoch + every averaged tensor (params, chosen opt
        statistics, extras) — the user-level checkpoint the reference embeds the
        epoch into (reference optimizer.py:719-727)."""
        with self._state_lock:
            tensors = self._host_state_tensors()
        return {
            "epoch": int(self.local_epoch),
            "tensors": tensors,
            # counters saved explicitly: local-updates peers take many optimizer
            # steps per epoch, so counts cannot be reconstructed from the epoch
            "opt_counts": self._get_opt_counts(),
        }

    def _get_opt_counts(self) -> List[int]:
        import jax

        return [
            int(leaf)
            for key_path, leaf in jax.tree_util.tree_flatten_with_path(self.opt_state)[0]
            if self._is_count_leaf(key_path, leaf)
        ]

    def load_state_dict(self, state: dict) -> None:
        expected = len(self._params_flat) + len(self._averaged_opt_indices) + len(self.extra_tensors)
        tensors = state["tensors"]
        if len(tensors) != expected:
            raise ValueError(f"checkpoint has {len(tensors)} tensors, expected {expected}")
        self._load_host_state_tensors([np.asarray(t, dtype=np.float32) for t in tensors])
        self.local_epoch = int(state["epoch"])
        counts = state.get("opt_counts")
        if counts:
            self._set_opt_counts(list(counts))
        else:
            self.replay_schedule_to_epoch(self.local_epoch)

    # ------------------------------------------------------------------ state sharing

    async def _get_current_state(self) -> Tuple[Any, List[np.ndarray]]:
        metadata = {"epoch": self.local_epoch}
        return metadata, self._host_state_tensors()

    def load_full_state_from_peers(
        self, timeout: Optional[float] = None, min_epoch: Optional[int] = None
    ) -> bool:
        """Download params/opt-state/epoch from the swarm and adopt them
        (reference load_state_from_peers path, state_averager.py:658-698).

        ``min_epoch`` (normally the progress tracker's global epoch) is enforced
        at the donor's MANIFEST: a donor whose epoch is behind it is rejected
        before any tensor bytes move, so catching up can never adopt state staler
        than the swarm's published progress (ISSUE 7 — the old path adopted any
        donor's epoch via ``max()`` with no freshness validation)."""
        future = self._runner.run_coroutine(
            self._load_state_from_peers_async(timeout, min_epoch=min_epoch), return_future=True
        )
        try:
            # small slack over the coroutine's own deadline so the in-loop
            # timeout (which preserves partial verification state) fires first
            result = future.result(None if timeout is None else timeout + 10.0)
        except Exception as e:
            logger.warning(f"state download did not complete: {e!r}")
            return False
        if result is None:
            return False
        expected = len(self._params_flat) + len(self._averaged_opt_indices) + len(self.extra_tensors)
        if len(result.tensors) != expected:
            logger.warning(f"donor sent {len(result.tensors)} tensors, expected {expected}; ignoring")
            return False
        self._load_host_state_tensors(result.tensors)
        # adopted tensors owe nothing to our pre-download quantization errors:
        # carrying the old error-feedback residuals forward would "compensate"
        # state we no longer hold (ISSUE 11)
        self._wire_residuals.reset()
        # the verified manifest's epoch is authoritative; a legacy (unverified)
        # stream falls back to the msgpack metadata it shipped
        donor_epoch = int(result.epoch)
        if not result.verified and isinstance(result.metadata, dict) and "epoch" in result.metadata:
            donor_epoch = max(donor_epoch, int(result.metadata["epoch"]))
        self.local_epoch = max(self.local_epoch, donor_epoch)
        # int step counters are not averaged tensors: fast-forward them so LR
        # schedules resume at the adopted epoch rather than restarting warmup
        self.replay_schedule_to_epoch(self.local_epoch)
        _STATE_RESTORES.inc(source="swarm")
        logger.info(
            f"adopted peer state at epoch {self.local_epoch} "
            f"({'digest-verified' if result.verified else 'UNVERIFIED legacy stream'})"
        )
        return True
