"""PowerSGD gradient averaging: rank-r factorization with error feedback
(capability parity: reference hivemind/optim/power_sgd_averager.py:28-222).

Each round runs TWO chained all-reduces inside one matchmade group: phase P averages
the projected matrices M·Q, which are then orthogonalized; phase Q averages Mᵀ·P
together with the uncompressed (1-d / tiny) tensors. Error feedback accumulates what
the rank-r approximation dropped, so compression error corrects itself over steps.
Matmuls/orthogonalization are small dense ops — numpy on host (they are tiny next to
the network transfer they eliminate)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from hivemind_tpu.averaging.group_info import GroupInfo
from hivemind_tpu.optim.grad_averager import GradientAverager
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.math_utils import get_flatten_greedy_dims, orthogonalize

logger = get_logger(__name__)


class PowerSGDGradientAverager(GradientAverager):
    """:param averager_rank: rank r of the factorization
    :param min_compression_ratio: tensors that rank-r would not compress by at least
        this factor are averaged uncompressed in phase Q (reference behavior for 1-d
        and small tensors, power_sgd_averager.py:172-174)"""

    def __init__(
        self,
        tensors_like: Sequence,
        *,
        averager_rank: int = 1,
        min_compression_ratio: float = 0.5,
        **kwargs,
    ):
        self.rank = averager_rank
        self.min_compression_ratio = min_compression_ratio
        super().__init__(tensors_like, **kwargs)

        self._compressed_idx: List[int] = []
        self._uncompressed_idx: List[int] = []
        with self.get_tensors() as tensors:
            for i, tensor in enumerate(tensors):
                m, n = get_flatten_greedy_dims(tensor.shape)
                if self.rank * (m + n) < tensor.size * min_compression_ratio:
                    self._compressed_idx.append(i)
                else:
                    self._uncompressed_idx.append(i)
            # error feedback buffers (reference _ms) + warm-start Qs: seeded identically
            # on every peer so the initial projections agree
            self._error_feedback = {i: np.zeros_like(tensors[i]) for i in self._compressed_idx}
            rng = np.random.RandomState(0xC0FFEE)
            self._qs = {}
            for i in self._compressed_idx:
                _m, n = get_flatten_greedy_dims(tensors[i].shape)
                self._qs[i] = np.asarray(rng.randn(n, self.rank), np.float32)

    async def _aggregate_with_group(self, group_info: GroupInfo, weight: float):
        bandwidths, modes, user_gathered, _adverts = self._decode_gathered(group_info)
        with self.get_tensors() as tensors:
            local = [t.copy() for t in tensors]

        ms = {}
        ps = []
        for i in self._compressed_idx:
            m_dims = get_flatten_greedy_dims(local[i].shape)
            ms[i] = (local[i] + self._error_feedback[i]).reshape(m_dims).astype(np.float32)
            ps.append(ms[i] @ self._qs[i])

        # phase P: average the projections (reference 117-130)
        averaged_ps = await self._run_manual_allreduce(
            group_info, ps, group_id_suffix=b".phase_p",
            modes=modes, bandwidths=bandwidths, weight=weight,
        )
        for p in averaged_ps:
            orthogonalize(p)

        # phase Q: average Mᵀ·P and the uncompressed tensors together (reference 161-178)
        qs = [ms[i].T @ p for i, p in zip(self._compressed_idx, averaged_ps)]
        raw = [local[i].astype(np.float32) for i in self._uncompressed_idx]
        averaged_phase_q = await self._run_manual_allreduce(
            group_info, qs + raw, group_id_suffix=b".phase_q",
            modes=modes, bandwidths=bandwidths, weight=weight,
        )
        averaged_qs = averaged_phase_q[: len(qs)]
        averaged_raw = averaged_phase_q[len(qs) :]

        # reconstruct, update error feedback, publish into the shared buffers
        with self.get_tensors() as tensors:
            for i, p, q in zip(self._compressed_idx, averaged_ps, averaged_qs):
                approx = (p @ q.T).reshape(tensors[i].shape)
                self._error_feedback[i] = ms[i].reshape(tensors[i].shape) - approx
                np.copyto(tensors[i], approx)
                self._qs[i] = q  # warm start for the next round
            for i, averaged in zip(self._uncompressed_idx, averaged_raw):
                np.copyto(tensors[i], averaged.reshape(tensors[i].shape))
        return user_gathered

    def compression_ratio(self) -> float:
        with self.get_tensors() as tensors:
            full = sum(t.size for t in tensors)
            sent = sum(
                self.rank * sum(get_flatten_greedy_dims(tensors[i].shape)) for i in self._compressed_idx
            ) + sum(tensors[i].size for i in self._uncompressed_idx)
        return sent / max(full, 1)
