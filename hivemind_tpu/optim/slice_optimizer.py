"""SliceOptimizer: the FULL collaborative ``Optimizer`` semantics — target_batch_size
epochs, swarm gradient averaging, progress tracker, periodic state averaging, and
``load_state_from_peers`` — running on a (possibly multi-host) jax device mesh, where
the whole mesh/slice is ONE swarm peer.

This joins the two halves of the TPU-native design (VERDICT r3 next-round #1): the
reference's flagship training API (reference hivemind/optim/optimizer.py:32-790 +
grad_averager.py:18-239) and the slice tier (`averaging/slice.py`, where previously
only local-SGD *parameter* averaging could ride a multi-host mesh).

Division of labor:

- **Every process** (the SPMD contract: all processes call every method at the same
  points): holds its shards of params / optax state / the on-device gradient
  accumulator; joins the collective staging, broadcast, and update phases.
- **Process 0** (the network process) exclusively owns the DHT, the
  ``ProgressTracker``, matchmaking (including the reference's pre-scheduled
  gradient-averaging groups), the butterfly all-reduce, and state sharing. Non-zero
  processes never construct any networking object — the same structural guarantee
  as ``SliceAverager``.

TPU-first choices:

- **Gradient accumulation stays on device.** ``step(grads)`` adds into a sharded
  fp32 accumulator tree with a jitted donated add — no per-microbatch device→host
  transfer. Gradients cross the host boundary ONCE per epoch, at averaging time,
  through :class:`MeshTensorBridge` (shard-wise staging).
- **The optax update is collective.** Parameters and optimizer state never leave
  the mesh: the final (swarm-averaged or local) gradients are scattered back to
  the params' shardings and one jitted donated update advances every shard.
- **Decisions are broadcast, not re-derived.** Whether to catch up, whether the
  swarm is ready for an epoch, and whether averaging succeeded are known only on
  process 0; a small decision vector is broadcast each step
  (``multihost_utils.broadcast_one_to_all``) so every process takes the same
  branch — control flow divergence across processes is a hang, not an error.

Wire compatibility: the slice peer matchmakes under the same prefixes
(``{run_id}_grad_averager``, ``{run_id}_state``) with the same tensor schemas as
host-resident :class:`hivemind_tpu.optim.Optimizer` peers, so slices, GPU boxes and
laptops share one swarm. Its advertised bandwidth is the slice's aggregate egress
(host count × base), as in :class:`MeshAverager`.

Gradient compression composes: ``grad_averager_factory`` accepts e.g.
``PowerSGDGradientAverager`` — the rank-r P/Q phases run on the staged host
gradients on process 0, wire-compatible with host PowerSGD peers in the same run.

Comm/compute overlap (the DPU analog, reference optimizer.py:87-88,131-132 +
state_averager.py:478-574): with ``delay_grad_averaging=True`` the swarm gradient
round runs on a BACKGROUND thread of process 0 while every process keeps
stepping into a fresh accumulator — the mesh never stalls for the round's
matchmaking + allreduce. The collective contract survives because the round's
LIFECYCLE is replicated, not its execution: the launch happens at a collective
step (every process stages the epoch's gradients and remembers the pending
round), completion is announced through the per-step decision broadcast, and
the adoption (scatter + optax update + state phase) happens at the next step
boundary on every process — one epoch stale, exactly the reference's DPU
semantics.

Deviations from the host Optimizer (documented, not silent): no
``use_local_updates`` mode (use ``SliceAverager`` for the local-SGD family),
and no aux/client modes (a slice is by definition a full NODE peer).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.averaging.averager import DecentralizedAverager
from hivemind_tpu.averaging.control import StepControl
from hivemind_tpu.compression import CompressionBase, Float16Compression
from hivemind_tpu.optim.chronic import ChronicFailureTracking
from hivemind_tpu.optim.grad_averager import GradientAverager
from hivemind_tpu.optim.progress_tracker import ProgressTracker
from hivemind_tpu.parallel.ici import MeshTensorBridge
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.profiling import tracked_jit
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

# layer-4 telemetry (docs/observability.md). The skipped-steps child is bound
# once: it increments on the broadcast-free hot path.
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY

_C_SKIPPED_STEPS = _TELEMETRY.counter(
    "hivemind_optim_skipped_broadcast_steps_total",
    "steps that skipped the per-step decision broadcast (thinning)",
).labels()
_C_EPOCH_TRANSITIONS = _TELEMETRY.counter(
    "hivemind_optim_epoch_transitions_total", "slice epoch transitions", ("kind",)
)
_C_POISONED_ROUNDS = _TELEMETRY.counter(
    "hivemind_optim_poisoned_averager_rounds_total",
    "delayed rounds whose thread outlived its join timeout, poisoning the grad averager",
).labels()


def _broadcast(value: np.ndarray) -> np.ndarray:
    """Broadcast one host array from process 0 to all processes (device collective)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(value))


class _SliceStateAverager(DecentralizedAverager):
    """State-sharing endpoint of a slice peer: serves the staged state mirrors with
    the slice's current epoch as metadata (the canonical state lives sharded on the
    mesh; mirrors are refreshed at every epoch transition, so downloads are at most
    one epoch stale — a joiner adopts them and catches up through the tracker)."""

    def __init__(self, *args, epoch_fn, **kwargs):
        self._epoch_fn = epoch_fn
        super().__init__(*args, **kwargs)

    async def _get_current_state(self) -> Tuple[Any, List[np.ndarray]]:
        return {"epoch": int(self._epoch_fn())}, self._snapshot_tensors()


class SliceOptimizer(ChronicFailureTracking):
    """See module docstring.

    :param mesh: the global Mesh (possibly spanning several processes/hosts)
    :param params: the initial parameter pytree, sharded over ``mesh``
    :param optimizer: an optax.GradientTransformation (same on every peer)
    :param dht_factory: zero-arg callable building the network process's DHT;
        called ONLY on process 0
    :param run_id: swarm identifier — must match the host peers' ``run_id``
    :param target_batch_size: global samples per virtual epoch (swarm-wide)
    :param batch_size_per_step: default GLOBAL samples per ``step`` call (every
        process passes the same number — the global microbatch, not its shard)
    :param average_state_every: run a parameter/opt-state averaging round every N
        epochs (reference average_state_every)
    :param average_opt_statistics: also average floating optimizer-state leaves
        (must match the host peers' setting or the state schemas diverge)
    :param delay_grad_averaging: overlap the swarm gradient round with training
        (the reference's delayed parameter updates): the round runs on a process-0
        background thread while the whole mesh keeps stepping; the averaged
        update is adopted collectively at the next step boundary, one epoch
        stale. See the module docstring.
    :param max_broadcast_skip: thin the per-step decision broadcast: while the
        tracker's ETA to the next epoch is far, process 0 announces how many
        upcoming steps may skip the collective entirely (every process counts
        down the same number, so lockstep holds). 0 disables thinning; skipping
        never happens near a boundary, during a pending round, or while chronic.
    """

    _chronic_peer_noun = "slice"

    def __init__(
        self,
        *,
        mesh,
        params: Any,
        optimizer,
        dht_factory,
        run_id: str,
        target_batch_size: int,
        batch_size_per_step: Optional[int] = None,
        average_state_every: int = 1,
        average_opt_statistics: bool = True,
        delay_grad_averaging: bool = False,
        max_broadcast_skip: int = 8,
        matchmaking_time: float = 5.0,
        averaging_timeout: float = 60.0,
        load_state_timeout: float = 60.0,
        grad_compression: CompressionBase = Float16Compression(),
        state_averaging_compression: CompressionBase = Float16Compression(),
        target_group_size: Optional[int] = None,
        min_group_size: int = 2,
        bandwidth: Optional[float] = None,
        grad_averager_factory=None,
        chronic_failure_threshold: int = 5,
        verbose: bool = False,
        **averager_opts,
    ):
        self.mesh = mesh
        self.run_id = run_id
        self.target_batch_size = target_batch_size
        self.batch_size_per_step = batch_size_per_step
        self.average_state_every = max(int(average_state_every), 1)
        self.delay_grad_averaging = delay_grad_averaging
        self.max_broadcast_skip = max(int(max_broadcast_skip), 0)
        self.matchmaking_time = matchmaking_time
        self.averaging_timeout = averaging_timeout
        self.load_state_timeout = load_state_timeout
        self.verbose = verbose
        self.process_index = jax.process_index()
        self.is_network_process = self.process_index == 0
        self.bridge = MeshTensorBridge(mesh)
        self._optax_optimizer = optimizer
        self._step_lock = threading.Lock()

        # -------- device state (every process) --------
        self.params = params
        self.opt_state = jax.jit(optimizer.init)(params)
        self._params_leaves, self._params_treedef = jax.tree_util.tree_flatten(params)
        opt_leaves, self._opt_treedef = jax.tree_util.tree_flatten(self.opt_state)
        # same selection rule as TrainingStateAverager (host peers): floating,
        # ndim>=1 — the schemas must agree or slices cannot group with host peers.
        # dtype/ndim read from attributes: a multi-process global array cannot be
        # np.asarray'd from one process.
        self._averaged_opt_indices = [
            i
            for i, leaf in enumerate(opt_leaves)
            if average_opt_statistics
            and hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) >= 1
        ]
        self._accum = self._jit_zeros_like()(params)
        self._samples = 0
        self.local_epoch = 0
        self.scheduled_grads: Optional[StepControl] = None
        # delayed-round state, REPLICATED on every process (set and cleared only
        # at collective steps, so `self._pending is not None` is identical
        # everywhere — the in-flight check never needs its own collective)
        self._pending: Optional[dict] = None
        self._bg_thread: Optional[threading.Thread] = None  # process 0 only
        self._bg_outcome: Optional[dict] = None  # process 0 only
        # a delayed-round thread that outlived its join timeout still owns the
        # grad averager's shared tensors: until it is confirmed dead the averager
        # is POISONED and must not be reused (silent data race otherwise)
        self._poisoned_bg_thread: Optional[threading.Thread] = None  # process 0 only
        # broadcast thinning, also replicated: process 0 announces a skip count in
        # the decision vector; every process counts the same number down
        self._skip_remaining = 0
        self._deferred_network_error: Optional[BaseException] = None
        self._step_time_ema: Optional[float] = None
        self._last_step_time: Optional[float] = None
        # chronic-degradation tracking (host Optimizer parity, optimizer.py:100-136):
        # epochs that fell back to local gradients count; past the threshold the
        # condition escalates to ERROR and matchmaking backs off exponentially.
        # Tracked consistently on EVERY process — the outcome flag is broadcast.
        self.chronic_failure_threshold = chronic_failure_threshold
        self._consecutive_failed_rounds = 0

        import optax

        def _accumulate(acc, grads, scale):
            return jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32) * scale, acc, grads
            )

        def _apply(params_, opt_state_, grads_):
            updates, new_state = optimizer.update(grads_, opt_state_, params_)
            return optax.apply_updates(params_, updates), new_state

        def _normalize(acc, inv_scale):
            return jax.tree_util.tree_map(lambda a: a * inv_scale, acc)

        # tracked_jit (ISSUE 19): these three are the slice's hottest device
        # calls — a retrace here (e.g. a dtype drift in the grads tree) must
        # surface on the compile tracker, not hide as a slow step
        self._jit_accumulate = tracked_jit(
            _accumulate, site="slice_optimizer.accumulate", donate_argnums=(0,)
        )
        self._jit_apply = tracked_jit(_apply, site="slice_optimizer.apply", donate_argnums=(0, 1))
        self._jit_normalize = tracked_jit(_normalize, site="slice_optimizer.normalize")

        # -------- networking (process 0 only) --------
        self.dht = None
        self.grad_averager: Optional[DecentralizedAverager] = None
        self.state_averager: Optional[_SliceStateAverager] = None
        self.tracker: Optional[ProgressTracker] = None
        if self.is_network_process:
            self.dht = dht_factory()
            num_hosts = len({d.process_index for d in mesh.devices.flat})
            slice_bandwidth = bandwidth if bandwidth is not None else 1.0e8 * max(num_hosts, 1)
            common = dict(
                dht=self.dht,
                start=True,
                target_group_size=target_group_size,
                min_group_size=min_group_size,
                min_matchmaking_time=matchmaking_time,
                bandwidth=slice_bandwidth,
                **averager_opts,
            )
            grad_templates = [
                np.zeros(leaf.shape, np.float32) for leaf in self._params_leaves
            ]
            # grad_averager_factory (API parity with the host Optimizer): e.g.
            # PowerSGDGradientAverager for rank-r compressed swarm rounds — the
            # P/Q phases run on the staged host gradients on process 0, so the
            # slice interoperates with host PowerSGD peers on the same run_id.
            # The factory must accept (templates, dht=..., prefix=..., ...).
            # When it resolves to a GradientAverager subclass (class or
            # functools.partial of one), host accumulators are skipped — the
            # slice accumulates on device and stages directly, so they would be
            # a wasted model copy of host RAM.
            factory = grad_averager_factory if grad_averager_factory is not None else DecentralizedAverager
            factory_class = factory if isinstance(factory, type) else getattr(factory, "func", None)
            extra_opts = (
                {"accumulate_grads_on_host": False}
                if isinstance(factory_class, type) and issubclass(factory_class, GradientAverager)
                else {}
            )
            self.grad_averager = factory(
                grad_templates,
                prefix=f"{run_id}_grad_averager",
                compression=grad_compression,
                **extra_opts,
                **common,
            )
            state_templates = [
                np.zeros(leaf.shape, np.float32) for leaf in self._state_leaves()
            ]
            self.state_averager = _SliceStateAverager(
                state_templates,
                prefix=f"{run_id}_state",
                compression=state_averaging_compression,
                state_compression=state_averaging_compression,
                epoch_fn=lambda: self.local_epoch,
                **common,
            )
            self.tracker = ProgressTracker(self.dht, run_id, target_batch_size)

    # ------------------------------------------------------------------ device trees

    def _jit_zeros_like(self):
        fn = getattr(self, "_zeros_fn", None)
        if fn is None:
            fn = self._zeros_fn = tracked_jit(
                lambda tree: jax.tree_util.tree_map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), tree
                ),
                site="slice_optimizer.zeros_like",
            )
        return fn

    def _state_leaves(self) -> List:
        """Params + selected optimizer statistics, in the host peers' flatten order
        (params first, then stats — matching TrainingStateAverager's schema)."""
        opt_leaves = jax.tree_util.tree_flatten(self.opt_state)[0]
        return list(self._params_leaves) + [opt_leaves[i] for i in self._averaged_opt_indices]

    def _refresh_param_leaves(self) -> None:
        self._params_leaves = jax.tree_util.tree_flatten(self.params)[0]

    # ------------------------------------------------------------------ main entry

    @property
    def ready_to_update_epoch(self) -> bool:
        """Meaningful on the network process; followers learn it via the broadcast."""
        return bool(self.tracker is not None and self.tracker.ready_to_update_epoch)

    def step(self, grads: Any = None, batch_size: Optional[int] = None) -> Any:
        """Accumulate one (global) microbatch of sharded gradients; when the swarm
        reaches ``target_batch_size``, run the collective epoch transition
        (synchronously, or — with ``delay_grad_averaging`` — launch the swarm
        round in the background and adopt it at a later step boundary). Every
        process of the slice must call this at the same point with the same
        ``batch_size`` (the global microbatch size). Returns the parameter tree."""
        with self._step_lock:
            batch_size = batch_size if batch_size is not None else (self.batch_size_per_step or 1)
            if grads is not None:
                self._accum = self._jit_accumulate(
                    self._accum, grads, jnp.float32(batch_size)
                )
                self._samples += batch_size
            self._observe_step_time()

            # thinned step: process 0 announced this many broadcast-free steps;
            # every process counts the SAME number down, so lockstep holds with
            # zero collectives on the hot path. Process 0 still does its local
            # networking — but an error there is deferred to the next broadcast
            # step (raising here would desync the skip countdown).
            if self._skip_remaining > 0:
                self._skip_remaining -= 1
                _C_SKIPPED_STEPS.inc()
                if self.is_network_process and self._deferred_network_error is None:
                    try:
                        assert self.tracker is not None
                        self.tracker.report_local_progress(self.local_epoch, self._samples)
                        if self._pending is None:
                            self._maybe_schedule_gradient_averaging()
                    except BaseException as e:
                        self._deferred_network_error = e
                return self.params

            # process 0 decides; everyone else adopts the decision (one small
            # device broadcast per step — control flow must not diverge). The
            # decision vector carries an ERROR flag in slot 4: if process 0's
            # networking raises (DHT shutdown, tracker store failure), it still
            # broadcasts — with the flag set — so every process raises in
            # lockstep instead of the followers parking forever in the
            # collective (advisor r4 medium finding). Slots 5-6 announce a
            # pending background round's completion; slot 7 the next skip count.
            in_flight = self._pending is not None
            network_error: Optional[BaseException] = None
            if self.is_network_process:
                try:
                    if self._deferred_network_error is not None:
                        network_error = self._deferred_network_error
                        self._deferred_network_error = None
                        raise network_error
                    assert self.tracker is not None
                    self.tracker.report_local_progress(self.local_epoch, self._samples)
                    if not in_flight:
                        self._maybe_schedule_gradient_averaging()
                    # one-epoch grace (reference optimizer.py:654-672): global ==
                    # local + 1 is normal network asynchrony — the tracker
                    # reports us ready and we transition ourselves onto the
                    # global epoch; only a 2+ gap downloads state
                    catch_up = self.local_epoch < self.tracker.global_epoch - 1
                    ready = self.tracker.ready_to_update_epoch
                    round_done = round_ok = 0.0
                    if in_flight and self._bg_thread is not None:
                        if ready and self._bg_thread.is_alive():
                            # the NEXT boundary arrived while the round is still
                            # in flight: staleness is capped at one epoch — wait
                            # the round out (its own timeouts bound this)
                            self._bg_thread.join(timeout=self.averaging_timeout + 30.0)
                        if not self._bg_thread.is_alive():
                            round_done = 1.0
                            round_ok = 1.0 if (self._bg_outcome or {}).get("ok") else 0.0
                    elif in_flight:
                        # solo-swarm pending (no thread): adopt immediately
                        round_done, round_ok = 1.0, 0.0
                    decision = np.asarray(
                        [
                            1.0 if catch_up else 0.0,
                            1.0 if ready else 0.0,
                            float(self.tracker.global_epoch),
                            float(self.tracker.global_progress.num_peers),
                            0.0,
                            round_done,
                            round_ok,
                            float(self._suggest_skip(catch_up, ready, in_flight)),
                        ],
                        np.float32,
                    )
                except BaseException as e:
                    network_error = e
                    decision = np.asarray(
                        [0.0, 0.0, -1.0, -1.0, 1.0, 0.0, 0.0, 0.0], np.float32
                    )
            else:
                decision = np.zeros(8, np.float32)
            decision = _broadcast(decision)
            if decision[4] >= 0.5:
                if network_error is not None:
                    raise network_error
                raise RuntimeError(
                    "the slice's network process failed during its decision phase; "
                    "raising in lockstep (see process 0's traceback for the cause)"
                )
            catch_up, ready = decision[0] >= 0.5, decision[1] >= 0.5
            global_epoch, num_peers = int(decision[2]), int(decision[3])
            round_done, round_ok = decision[5] >= 0.5, decision[6] >= 0.5
            self._skip_remaining = max(int(decision[7]), 0)

            if catch_up:
                # local_epoch already counts a launched delayed round (the epoch
                # advances at LAUNCH, reference optimizer.py:131-132), so being
                # behind here is genuine — drop the pending round and download
                self._discard_pending()
                self._collective_catch_up(global_epoch)
                return self.params
            if in_flight:
                if round_done:
                    self._finish_delayed_epoch(round_ok)
                return self.params
            if ready:
                if self.delay_grad_averaging and num_peers > 1:
                    self._begin_delayed_epoch(num_peers, global_epoch)
                else:
                    self._collective_epoch_update(num_peers, global_epoch)
            return self.params

    def _observe_step_time(self) -> None:
        """EMA of the wall time between step() calls (used to size the skip)."""
        now = get_dht_time()
        if self._last_step_time is not None:
            dt = max(now - self._last_step_time, 1e-6)
            self._step_time_ema = (
                dt if self._step_time_ema is None else 0.8 * self._step_time_ema + 0.2 * dt
            )
        self._last_step_time = now

    def _suggest_skip(self, catch_up: bool, ready: bool, in_flight: bool) -> int:
        """How many upcoming steps may skip the decision broadcast (network
        process only). Never skips when anything needs low-latency signaling:
        a boundary is near (in step-time terms), a round is pending, we are
        behind, or rounds are chronically failing."""
        if (
            self.max_broadcast_skip <= 0
            or catch_up
            or ready
            or in_flight
            or self.chronic_averaging_failure
            or self._step_time_ema is None
        ):
            return 0
        assert self.tracker is not None
        progress = self.tracker.global_progress
        eta = progress.eta_next_epoch - get_dht_time()
        # stay broadcast-per-step inside the pre-scheduling window so the group
        # forms at full cadence, and keep a 2x step-time safety margin
        if eta <= max(self.matchmaking_time * 2, 4 * self._step_time_ema):
            return 0
        # additionally cap by the locally-known samples remaining to the target:
        # the ETA extrapolates the swarm's PAST rate, so a swarm speed-up (new
        # peers joining mid-window) can close the epoch well before it — the
        # sample count cannot be outrun the same way, and with the same 2x
        # margin our own contribution can cover at most half the known gap
        # before the next broadcast re-checks
        per_step = max(int(self.batch_size_per_step or 1), 1)
        remaining_samples = max(progress.target_batch_size - progress.samples_accumulated, 0)
        steps_to_target = int(remaining_samples // (2 * per_step))
        return min(self.max_broadcast_skip, int(eta / (2 * self._step_time_ema)), steps_to_target)

    # ------------------------------------------------------------------ delayed rounds

    def _begin_delayed_epoch(self, num_peers: int, global_epoch: int = 0) -> None:
        """COLLECTIVE: stage this epoch's normalized gradients to identical host
        copies on every process, remember the pending round, reset the on-device
        accumulator (training continues into the NEXT epoch), ADVANCE the epoch
        (reference DPU semantics, optimizer.py:131-132 — the epoch counts the
        launched round; only the parameter update is delayed; advancing here
        also resets the tracker so ``ready`` cannot re-fire into an immediate
        blocking join), and — network process only — launch the swarm round on
        a background thread."""
        _C_EPOCH_TRANSITIONS.inc(kind="delayed_launch")
        inv = jnp.float32(1.0 / max(self._samples, 1))
        normalized = self._jit_normalize(self._accum, inv)
        scratch = self.bridge.gather_to_host(normalized)
        self._pending = {"scratch": scratch, "num_peers": num_peers}
        # weight 0 is correct for a peer with nothing accumulated (the grace rule
        # can transition an empty peer): its zero buffers must not dilute the
        # group average — matches the host Optimizer (optimizer.py:379-383)
        weight = float(self._samples)
        self._accum = self._jit_zeros_like()(self.params)
        self._samples = 0
        # a rejoining peer lands ON the global epoch, not past it
        self.local_epoch = max(self.local_epoch + 1, global_epoch)
        if not self.is_network_process:
            return
        assert self.tracker is not None
        self.tracker.update_epoch(self.local_epoch)
        control = None if self._scheduled_control_invalid() else self.scheduled_grads
        self.scheduled_grads = None
        outcome: dict = {"ok": False}
        self._bg_outcome = outcome

        def run_round() -> None:
            # writing the average back into process 0's scratch is race-free:
            # the adoption step reads it only after joining this thread
            outcome["ok"] = self._run_swarm_round(scratch, weight, control)

        self._bg_thread = threading.Thread(
            target=run_round, name="slice-delayed-round", daemon=True
        )
        self._bg_thread.start()

    def _finish_delayed_epoch(self, round_ok: bool) -> None:
        """COLLECTIVE: adopt the background round's outcome — averaged gradients
        if it succeeded (per-leaf broadcast from process 0), the staged local
        gradients otherwise — then run the shared update + state phase tail.
        The CURRENT accumulator (next epoch's partial progress) is untouched."""
        pending = self._pending
        assert pending is not None
        self._pending = None
        scratch = pending["scratch"]
        num_peers = pending["num_peers"]
        if self.is_network_process and self._bg_thread is not None:
            self._bg_thread.join(timeout=5.0)  # decision said done; near-instant
        averaged_ok = bool(round_ok)
        if averaged_ok:
            # process 0's scratch already holds the group average (written by the
            # background round before it finished)
            for i in range(len(scratch)):
                scratch[i] = _broadcast(np.ascontiguousarray(scratch[i]))
        self._bg_thread = None
        self._bg_outcome = None
        self._apply_epoch_tail(
            scratch, averaged_ok, num_peers, reset_accumulator=False, advance_epoch=False
        )

    def _discard_pending(self) -> None:
        """Drop an in-flight delayed round (all processes; the catch-up path is
        about to replace the state it would have updated). Process 0 waits the
        background thread out so the averager is free for the state download; a
        thread that survives the join timeout POISONS the grad averager — its
        buffers are not reused until the thread is confirmed dead (a wedged round
        writing into tensors a new round is reading is a silent data race)."""
        if self._pending is None:
            return
        self._pending = None
        if self.is_network_process and self._bg_thread is not None:
            self._bg_thread.join(timeout=self.averaging_timeout + 30.0)
            if self._bg_thread.is_alive():
                self._poisoned_bg_thread = self._bg_thread
                _C_POISONED_ROUNDS.inc()
                logger.error(
                    "a discarded delayed averaging round did not terminate within "
                    f"{self.averaging_timeout + 30.0:.0f}s; the grad averager is POISONED — "
                    "swarm gradient rounds degrade to local gradients until the round "
                    "thread is confirmed dead (see "
                    "hivemind_optim_poisoned_averager_rounds_total)"
                )
        self._bg_thread = None
        self._bg_outcome = None

    def _grad_averager_poisoned(self) -> bool:
        """True while a timed-out delayed-round thread may still touch the grad
        averager's buffers; self-clears once the thread is confirmed dead."""
        thread = self._poisoned_bg_thread
        if thread is None:
            return False
        if thread.is_alive():
            return True
        self._poisoned_bg_thread = None
        logger.warning(
            "the poisoned delayed-round thread has terminated; grad averager "
            "buffers are safe to reuse again"
        )
        return False

    # ------------------------------------------------------------------ scheduling

    # chronic counter/backoff/log members come from ChronicFailureTracking

    def _maybe_schedule_gradient_averaging(self) -> None:
        """Pre-schedule matchmaking so the group is formed when the swarm hits the
        target (reference optimizer.py:559-567). Network process only, no collective."""
        assert self.tracker is not None and self.grad_averager is not None
        if self.chronic_averaging_failure:
            # pre-scheduling re-declares in the DHT at full cadence every step;
            # under chronic failure only the (backed-off) step-time path matchmakes
            return
        if self._grad_averager_poisoned():
            return  # a wedged round still owns the averager's buffers
        eta = self.tracker.global_progress.eta_next_epoch - get_dht_time()
        if eta <= self.matchmaking_time * 2 and self._scheduled_control_invalid():
            scheduled_time = get_dht_time() + max(eta, 1e-2)
            if isinstance(self.grad_averager, GradientAverager):
                # its step() override hardcodes require_trigger; use the dedicated
                # scheduling entry point (same as the host Optimizer)
                self.scheduled_grads = self.grad_averager.schedule_step(
                    scheduled_time=scheduled_time, timeout=self.averaging_timeout
                )
            else:
                self.scheduled_grads = self.grad_averager.step(
                    scheduled_time=scheduled_time,
                    timeout=self.averaging_timeout,
                    require_trigger=True,
                    wait=False,
                )
            logger.debug(f"pre-scheduled slice gradient averaging in {eta:.1f}s")

    def _scheduled_control_invalid(self) -> bool:
        control = self.scheduled_grads
        return control is None or control.done() or control.cancelled

    # ------------------------------------------------------------------ epoch transition

    def _run_swarm_round(self, scratch: List[np.ndarray], weight: float, control) -> bool:
        """Network process only; the ONE swarm-gradient-round implementation shared
        by the synchronous and delayed paths: stage ``scratch`` into the shared
        tensors, run the round (pre-claimed ``control`` or a fresh step), and on
        success write the group average back INTO ``scratch``. Never raises —
        every failure (staging included) degrades to False so the caller's flag
        broadcast keeps the mesh in lockstep (advisor r4 medium finding), and a
        claimed control is cancelled so matched groupmates are not stranded."""
        try:
            assert self.grad_averager is not None
            if self._grad_averager_poisoned():
                # refusing to touch the shared tensors IS the fix: the wedged
                # thread may still be writing them (loud log already emitted)
                if control is not None and not control.done():
                    with contextlib.suppress(Exception):
                        control.cancel()
                return False
            with self.grad_averager.get_tensors() as tensors:
                for tensor, fresh in zip(tensors, scratch):
                    np.copyto(tensor, fresh)
            if isinstance(self.grad_averager, GradientAverager):
                # one call covers scheduled and unscheduled (the host Optimizer's
                # DPU path, optimizer.py:430-436); gradients are ALREADY staged
                # in the shared tensors, so the host accumulators must not
                # overwrite them
                result = self.grad_averager.step(
                    control=control,
                    weight=weight,
                    timeout=self.averaging_timeout,
                    load_accumulators=False,
                    scheduled_time=(
                        get_dht_time() + self._matchmaking_delay() if control is None else None
                    ),
                )
            elif control is not None:
                control.weight = weight
                control.allow_allreduce()
                result = control.result(self.averaging_timeout)
            else:
                result = self.grad_averager.step(
                    weight=weight,
                    timeout=self.averaging_timeout,
                    scheduled_time=get_dht_time() + self._matchmaking_delay(),
                )
            if result is None:
                return False
            with self.grad_averager.get_tensors() as tensors:
                for mirror, tensor in zip(scratch, tensors):
                    np.copyto(mirror, tensor)
            return True
        except Exception as e:
            if control is not None and not control.done():
                with contextlib.suppress(Exception):
                    control.cancel()
            logger.warning(f"slice gradient averaging failed ({e!r}); applying local gradients")
            return False

    def _collective_epoch_update(self, num_peers: int, global_epoch: int = 0) -> None:
        """The slice analog of reference _update_global_epoch (optimizer.py:438-509):
        stage → swarm-average (p0) → broadcast → collective optax update → state round."""

        _C_EPOCH_TRANSITIONS.inc(kind="synchronous")
        # phase A (collective): normalize the on-device accumulator and stage it to
        # identical full host copies on EVERY process (per-leaf bounded staging).
        # These doubles as the local-gradient fallback: if the swarm round fails,
        # every process already holds the same local average — no broadcast needed.
        inv = jnp.float32(1.0 / max(self._samples, 1))
        normalized = self._jit_normalize(self._accum, inv)
        scratch = self.bridge.gather_to_host(normalized)

        # phase B (network process): the swarm round
        averaged_ok: Optional[bool] = None  # None = no round attempted (solo swarm)
        if num_peers > 1:
            averaged_ok = False
            if self.is_network_process:
                # claim the pre-scheduled control BEFORE the round: if staging
                # fails, the control must still be consumed (and cancelled), not
                # left live to block re-scheduling and strand its matched
                # groupmates until the averaging timeout
                control = None if self._scheduled_control_invalid() else self.scheduled_grads
                self.scheduled_grads = None
                # weight 0 for a peer with nothing accumulated (see
                # _begin_delayed_epoch / host optimizer.py:379-383)
                averaged_ok = self._run_swarm_round(scratch, float(self._samples), control)

            # phase C (collective): adopt the round outcome
            flag = _broadcast(np.asarray([1.0 if averaged_ok else 0.0], np.float32))
            averaged_ok = bool(flag[0] >= 0.5)
            if averaged_ok:
                for i in range(len(scratch)):
                    scratch[i] = _broadcast(np.ascontiguousarray(scratch[i]))

        self._apply_epoch_tail(
            scratch, averaged_ok, num_peers, reset_accumulator=True, global_epoch=global_epoch
        )

    def _apply_epoch_tail(
        self,
        scratch: List[np.ndarray],
        averaged_ok: Optional[bool],
        num_peers: int,
        reset_accumulator: bool,
        advance_epoch: bool = True,
        global_epoch: int = 0,
    ) -> None:
        """The shared end of every epoch transition (synchronous and delayed).

        phase D (collective): scatter the final gradients back to the params'
        shardings and run ONE jitted donated update — params/opt state never
        left the mesh. phase E (collective): record the round outcome, refresh
        the state mirrors, run the periodic state round, advance the epoch.
        ``reset_accumulator=False`` / ``advance_epoch=False`` on the delayed
        path: the accumulator already holds the NEXT epoch's partial progress,
        and the epoch was counted at launch — this tail only lands the update."""
        next_epoch = (
            max(self.local_epoch + 1, global_epoch) if advance_epoch else self.local_epoch
        )
        grads_tree = jax.tree_util.tree_unflatten(
            self._params_treedef,
            [
                self.bridge.scatter_leaf(leaf, value)
                for leaf, value in zip(self._params_leaves, scratch)
            ],
        )
        self.params, self.opt_state = self._jit_apply(self.params, self.opt_state, grads_tree)
        self._refresh_param_leaves()
        if reset_accumulator:
            self._accum = self._jit_zeros_like()(self.params)
            self._samples = 0

        # record the grad-round outcome FIRST (reference order, optimizer.py:384-388):
        # the state phase's matchmaking delay must see the recovered counter
        self._record_round_outcome(averaged_ok)
        self._collective_state_phase(next_epoch, num_peers)

        self.local_epoch = next_epoch
        if self.is_network_process:
            assert self.tracker is not None and self.state_averager is not None
            self.state_averager.state_sharing_priority = next_epoch
            if advance_epoch:
                self.tracker.update_epoch(next_epoch)
        if self.verbose:
            logger.info(
                f"[proc {self.process_index}] slice transitioned to epoch {next_epoch} "
                f"(averaged={averaged_ok}, peers={num_peers})"
            )

    def _refresh_state_mirrors(self) -> List[np.ndarray]:
        """COLLECTIVE: stage current params+opt-stats to every process's host
        copies and (network process) into the state averager's mirrors, so state
        downloads serve fresh tensors. Returns the per-process host copies."""
        state_scratch = self.bridge.gather_to_host(self._state_leaves())
        if self.is_network_process:
            try:
                assert self.state_averager is not None
                with self.state_averager.get_tensors() as tensors:
                    for tensor, fresh in zip(tensors, state_scratch):
                        np.copyto(tensor, fresh)
            except Exception as e:
                # non-fatal: the download mirrors stay one epoch staler; raising
                # here would strand the followers at the next collective
                logger.warning(f"failed to refresh state mirrors: {e!r}")
        return state_scratch

    def _collective_state_phase(self, next_epoch: int, num_peers: int) -> None:
        """Stage params+opt-stats to the state mirrors; every ``average_state_every``
        epochs additionally average them with the swarm and adopt the result."""
        state_scratch = self._refresh_state_mirrors()

        run_round = num_peers > 1 and next_epoch % self.average_state_every == 0
        if not run_round:
            return
        ok = False
        if self.is_network_process:
            # round + averaged-result readback both precede the flag broadcast,
            # under one guard (same hang-proofing as the gradient phase)
            try:
                assert self.state_averager is not None
                ok = (
                    self.state_averager.step(
                        timeout=self.averaging_timeout,
                        scheduled_time=get_dht_time() + self._matchmaking_delay(),
                    )
                    is not None
                )
                if ok:
                    with self.state_averager.get_tensors() as tensors:
                        for mirror, tensor in zip(state_scratch, tensors):
                            np.copyto(mirror, tensor)
            except Exception as e:
                ok = False
                logger.warning(f"slice state averaging failed: {e!r}")
        flag = _broadcast(np.asarray([1.0 if ok else 0.0], np.float32))
        if not bool(flag[0] >= 0.5):
            return
        for i in range(len(state_scratch)):
            state_scratch[i] = _broadcast(np.ascontiguousarray(state_scratch[i]))
        self._adopt_state_tensors(state_scratch)

    # ------------------------------------------------------------------ catch-up

    def _collective_catch_up(self, global_epoch: int) -> bool:
        """We are behind the swarm: process 0 downloads a donor's state, then the
        whole slice adopts it collectively (broadcast + shard upload) — the
        reference load_state_from_peers path (optimizer.py:655-717), landing on
        every process's shards. Returns True when a donor's state was adopted."""
        # header = [ok, epoch]: the epoch is broadcast on BOTH outcomes — on the
        # failure path every process must adopt the SAME epoch (process 0's view
        # can differ from a follower's argument, and divergent epochs desync the
        # collective schedule of later phases)
        _C_EPOCH_TRANSITIONS.inc(kind="catch_up")
        header = np.asarray([0.0, float(global_epoch)], np.float32)
        tensors: Optional[List[np.ndarray]] = None
        if self.is_network_process:
            assert self.state_averager is not None
            logger.info(
                f"slice epoch {self.local_epoch} is behind the swarm ({global_epoch}); downloading state"
            )
            state_leaves = self._state_leaves()
            try:
                result = self.state_averager.load_state_from_peers(timeout=self.load_state_timeout)
            except Exception as e:
                logger.warning(f"state download failed: {e!r}")
                result = None
            if result is not None:
                metadata, downloaded = result
                # count AND per-leaf sizes must match BEFORE broadcasting ok=1: a
                # shape-mismatched donor failing mid-adoption would leave the
                # followers parked in a leaf broadcast forever
                shapes_ok = len(downloaded) == len(state_leaves) and all(
                    np.asarray(t).size == int(np.prod(leaf.shape))
                    for t, leaf in zip(downloaded, state_leaves)
                )
                if shapes_ok:
                    tensors = [np.asarray(t, np.float32) for t in downloaded]
                    epoch = (
                        int(metadata["epoch"])
                        if isinstance(metadata, dict) and "epoch" in metadata
                        else global_epoch
                    )
                    header = np.asarray([1.0, float(max(epoch, global_epoch))], np.float32)
                else:
                    logger.warning(
                        f"donor state does not match our schema "
                        f"({len(downloaded)} tensors vs {len(state_leaves)} expected); ignoring"
                    )
        header = _broadcast(header)
        ok, adopted_epoch = bool(header[0] >= 0.5), int(header[1])
        if not ok:
            # could not download: every process adopts the BROADCAST epoch so we
            # stop re-triggering and stay in collective lockstep
            # (reference optimizer.py:481-482 fallback)
            self.local_epoch = max(self.local_epoch, adopted_epoch)
            return False

        # collective adoption: per-leaf broadcast from process 0, then every
        # process uploads its local shards (same fabric path as SliceAverager)
        state_leaves = self._state_leaves()
        adopted: List[np.ndarray] = []
        for i, leaf in enumerate(state_leaves):
            value = tensors[i] if tensors is not None else np.zeros(leaf.shape, np.float32)
            adopted.append(_broadcast(np.ascontiguousarray(value.reshape(leaf.shape))))
        self._adopt_checkpoint(adopted, adopted_epoch)
        logger.info(f"[proc {self.process_index}] slice adopted swarm state at epoch {adopted_epoch}")
        return True

    def _adopt_checkpoint(self, tensors: List[np.ndarray], epoch: int) -> None:
        """Shared adoption tail for catch-up and checkpoint restore (COLLECTIVE):
        validate against the state schema BEFORE touching anything (a half-restored
        optimizer — new params, stale Adam moments — is worse than an error), write
        the sharded device state, fast-forward counters/epoch, reset accumulation,
        and restage the state mirrors so downloads immediately serve the adopted
        state at its true epoch rather than init-time zeros."""
        state_leaves = self._state_leaves()
        if len(tensors) != len(state_leaves) or any(
            int(np.asarray(t).size) != int(np.prod(leaf.shape))
            for t, leaf in zip(tensors, state_leaves)
        ):
            raise ValueError(
                f"checkpoint tensors do not match the state schema "
                f"({len(tensors)} tensors vs {len(state_leaves)} leaves)"
            )
        self._adopt_state_tensors(tensors)
        self._set_opt_counts(epoch)
        self.local_epoch = epoch
        self._accum = self._jit_zeros_like()(self.params)
        self._samples = 0
        if self.is_network_process:
            assert self.state_averager is not None and self.tracker is not None
            # the adopted host tensors ARE the new state: restage the download
            # mirrors from them directly — no redundant device→host gather of
            # what was just scattered
            with self.state_averager.get_tensors() as mirrors:
                for mirror, tensor, leaf in zip(mirrors, tensors, state_leaves):
                    np.copyto(mirror, np.asarray(tensor, np.float32).reshape(leaf.shape))
            self.state_averager.state_sharing_priority = epoch
            self.tracker.report_local_progress(epoch, 0)

    def _adopt_state_tensors(self, host_tensors: List[np.ndarray]) -> None:
        """Write host values (identical on every process) into the sharded device
        state: params first, then the selected optimizer-statistic leaves."""
        n_params = len(self._params_leaves)
        new_param_leaves = [
            self.bridge.scatter_leaf(leaf, value)
            for leaf, value in zip(self._params_leaves, host_tensors[:n_params])
        ]
        self.params = jax.tree_util.tree_unflatten(self._params_treedef, new_param_leaves)
        self._refresh_param_leaves()
        opt_leaves = jax.tree_util.tree_flatten(self.opt_state)[0]
        for slot, value in zip(self._averaged_opt_indices, host_tensors[n_params:]):
            opt_leaves[slot] = self.bridge.scatter_leaf(opt_leaves[slot], value)
        self.opt_state = jax.tree_util.tree_unflatten(self._opt_treedef, opt_leaves)

    def _set_opt_counts(self, epoch: int) -> None:
        """Fast-forward optax integer step counters to the adopted epoch so LR
        schedules resume correctly (collaborative convention: one update == one
        epoch; reference state_averager.py:700-704)."""
        flat, _ = jax.tree_util.tree_flatten_with_path(self.opt_state)
        new_leaves = []
        for key_path, leaf in flat:
            is_count = bool(
                key_path
                and getattr(key_path[-1], "name", None) == "count"
                and hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.integer)
                and getattr(leaf, "ndim", None) == 0
            )
            if is_count:
                new_leaves.append(
                    self.bridge.scatter_leaf(leaf, np.asarray(epoch, leaf.dtype))
                )
            else:
                new_leaves.append(leaf)
        self.opt_state = jax.tree_util.tree_unflatten(self._opt_treedef, new_leaves)

    # ------------------------------------------------------------------ lifecycle

    def state_dict(self) -> dict:
        """User-level checkpoint with the epoch embedded (API parity with
        ``Optimizer.state_dict``, reference optimizer.py:719-727). COLLECTIVE:
        every process must call it (the gather is a mesh collective on a
        multi-process mesh); every process returns the same full host tensors.
        Takes the step lock so a checkpoint can never capture a torn mid-epoch
        state (params advanced but epoch not yet). With ``delay_grad_averaging``
        a checkpoint taken while a round is in flight captures the pre-update
        params at the CURRENT epoch — consistent, one round behind (the pending
        gradients are accumulator-external state, exactly as between boundaries
        in synchronous mode). NOTE: the lock covers
        concurrent threads WITHIN one process only — on a multi-process mesh all
        collective calls (step/checkpoint/restore) must come from one thread per
        process in the same order, or the processes' collectives mismatch."""
        with self._step_lock:
            tensors = self.bridge.gather_to_host(self._state_leaves())
            return {"epoch": int(self.local_epoch), "tensors": tensors}

    def load_state_dict(self, state: dict) -> None:
        """Restore a checkpoint onto the sharded device state. COLLECTIVE: every
        process must call it with the same checkpoint. Takes the step lock — a
        restore racing a training step in another thread would swap the param
        tree under it (single-process protection only; see ``state_dict``'s
        multi-process ordering note). An in-flight delayed round is discarded:
        its staged gradients were computed against the state being replaced, and
        landing them on the restored params would silently corrupt it."""
        with self._step_lock:
            self._discard_pending()
            self._adopt_checkpoint(
                [np.asarray(t, np.float32) for t in state["tensors"]], int(state["epoch"])
            )

    def force_epoch_transition(self, num_peers: int = 1) -> None:
        """Run the collective epoch transition NOW with whatever has accumulated —
        the deterministic alternative to waiting for the tracker's async fetch
        (tests, drills, graceful drain before shutdown). COLLECTIVE: every process
        must call it; ``num_peers`` > 1 additionally attempts the swarm rounds.
        A pending delayed round is finished FIRST (process 0 waits it out and
        broadcasts the outcome), so no staged epoch is ever lost to a drain."""
        with self._step_lock:
            if self._pending is not None:
                ok = 0.0
                if self.is_network_process:
                    if self._bg_thread is not None:
                        self._bg_thread.join(timeout=self.averaging_timeout + 30.0)
                        if not self._bg_thread.is_alive() and (self._bg_outcome or {}).get("ok"):
                            ok = 1.0
                flag = _broadcast(np.asarray([ok], np.float32))
                self._finish_delayed_epoch(bool(flag[0] >= 0.5))
            self._collective_epoch_update(num_peers)

    def load_state_from_peers(self, timeout: Optional[float] = None) -> bool:
        """Explicit collective state download (every process must call this).
        Takes the step lock like every other public collective entry point — a
        concurrent ``step`` in another thread must not interleave with the
        catch-up and tear the param tree (advisor r4 finding)."""
        del timeout  # the network process uses self.load_state_timeout
        with self._step_lock:
            self._discard_pending()  # the download replaces what the round would update
            epoch_target = self.local_epoch
            if self.is_network_process and self.tracker is not None:
                epoch_target = max(epoch_target, self.tracker.global_epoch)
            return self._collective_catch_up(epoch_target)

    def shutdown(self) -> None:
        if self._bg_thread is not None:
            self._bg_thread.join(timeout=self.averaging_timeout + 30.0)
            self._bg_thread = None
        if self.tracker is not None:
            self.tracker.shutdown()
        if self.scheduled_grads is not None:
            self.scheduled_grads.cancel()
        if self.grad_averager is not None:
            self.grad_averager.shutdown()
        if self.state_averager is not None:
            self.state_averager.shutdown()
        if self.dht is not None:
            self.dht.shutdown()

    def __repr__(self):
        return (
            f"SliceOptimizer(run_id={self.run_id!r}, epoch={self.local_epoch}, "
            f"proc={self.process_index}, network={self.is_network_process})"
        )
