"""NaN/Inf training guard with periodic in-memory state backups (capability
parity: reference examples/albert/run_trainer.py:62-130 — the flagship recipe
keeps a host-side copy of the full trainer state and rolls back to it instead of
poisoning the swarm when a peer's loss turns non-finite).

Library-level here (the reference buries it in the example) so every recipe gets
it and it is unit-testable: wrap the collaborative :class:`Optimizer` and route
``step`` through the guard."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class NaNGuard:
    """Backs up ``optimizer.state_dict()`` every ``backup_every`` healthy steps;
    a step with non-finite loss restores the backup (params, optimizer stats AND
    epoch — schedules replay to it) and drops the poisoned gradients.

    :param optimizer: a :class:`hivemind_tpu.optim.Optimizer`
    :param backup_every: healthy steps between state snapshots
    :param check_grads: additionally scan gradient pytrees for non-finite values
        (costs one reduction per leaf; the loss check alone is the reference
        behavior — an exploded backward almost always surfaces in the next loss)
    """

    def __init__(self, optimizer, backup_every: int = 30, check_grads: bool = False):
        self.optimizer = optimizer
        self.backup_every = max(int(backup_every), 1)
        self.check_grads = check_grads
        self._backup: Optional[dict] = None
        self._healthy_steps = 0
        self.restores = 0
        self.skipped_steps = 0

    def _grads_finite(self, grads: Any) -> bool:
        import jax

        leaves = jax.tree_util.tree_leaves(grads)
        return all(bool(np.isfinite(np.asarray(leaf).sum())) for leaf in leaves)

    def step(self, loss, grads: Any = None, batch_size: Optional[int] = None) -> Any:
        """Drop-in for ``optimizer.step(grads)`` with the loss routed through.
        Returns the (possibly restored) parameter pytree."""
        finite = bool(np.isfinite(np.asarray(loss)))
        if finite and self.check_grads and grads is not None:
            finite = self._grads_finite(grads)
        if not finite:
            self.skipped_steps += 1
            if self._backup is not None:
                self.optimizer.load_state_dict(self._backup)
                self.restores += 1
                logger.error(
                    f"non-finite loss ({float(np.asarray(loss)):.3g}); restored the "
                    f"backup from epoch {self._backup.get('epoch')} "
                    f"(restore #{self.restores}) and dropped this step's gradients"
                )
            else:
                logger.error(
                    "non-finite loss before any backup existed; dropping the step "
                    "(no state to restore yet)"
                )
            return self.optimizer.params

        if self._backup is None or self._healthy_steps % self.backup_every == 0:
            self._backup = self.optimizer.state_dict()
        self._healthy_steps += 1
        kwargs = {} if batch_size is None else {"batch_size": batch_size}
        return self.optimizer.step(grads, **kwargs)
