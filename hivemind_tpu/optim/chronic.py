"""Chronic-averaging-failure tracking shared by the host ``Optimizer`` and the
mesh ``SliceOptimizer`` (reference behavior introduced for the host optimizer:
consecutive epochs that degrade to local gradients escalate to ERROR and back
matchmaking off exponentially — a persistently failing peer must not silently
train local SGD forever, nor hammer the DHT at full cadence).

Host classes mix this in and provide ``chronic_failure_threshold``,
``matchmaking_time``, ``averaging_timeout``, and ``_consecutive_failed_rounds``
attributes; ``_chronic_peer_noun`` names the subject in log lines."""

from __future__ import annotations

from typing import Optional

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# layer-4 telemetry (docs/observability.md): swarm-round outcomes and the chronic
# counter, shared by the host Optimizer and SliceOptimizer via this mixin
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY

_ROUND_OUTCOMES = _TELEMETRY.counter(
    "hivemind_optim_averaging_rounds_total", "attempted swarm averaging rounds", ("outcome",)
)
_G_CONSECUTIVE_FAILURES = _TELEMETRY.gauge(
    "hivemind_optim_consecutive_failed_rounds",
    "epochs in a row that degraded to local gradients (chronic past the threshold)",
).labels()


class ChronicFailureTracking:
    _chronic_peer_noun = "peer"

    @property
    def consecutive_failed_averaging_rounds(self) -> int:
        """Epochs in a row that fell back to local gradients (0 = healthy)."""
        return self._consecutive_failed_rounds

    @property
    def chronic_averaging_failure(self) -> bool:
        """True once ``chronic_failure_threshold`` consecutive epochs degraded to
        local SGD — the swarm is effectively unreachable for this peer."""
        return self._consecutive_failed_rounds >= self.chronic_failure_threshold

    def _should_log_chronic(self) -> bool:
        # a slice logs only from its network process; host peers always log
        return bool(getattr(self, "is_network_process", True))

    def _record_round_outcome(self, averaged_ok: Optional[bool]) -> None:
        """``averaged_ok``: True/False for an attempted swarm round, None when no
        round was attempted (num_peers <= 1 — a solo peer is healthy, not failing)."""
        if averaged_ok is None:
            return
        _ROUND_OUTCOMES.inc(outcome="ok" if averaged_ok else "degraded_to_local")
        if averaged_ok:
            if self.chronic_averaging_failure and self._should_log_chronic():
                logger.info(
                    f"swarm averaging recovered after "
                    f"{self._consecutive_failed_rounds} failed epochs"
                )
            self._consecutive_failed_rounds = 0
            _G_CONSECUTIVE_FAILURES.set(0)
            return
        self._consecutive_failed_rounds += 1
        _G_CONSECUTIVE_FAILURES.set(self._consecutive_failed_rounds)
        if self._consecutive_failed_rounds == self.chronic_failure_threshold and self._should_log_chronic():
            logger.error(
                f"{self._consecutive_failed_rounds} consecutive epochs degraded to local "
                f"gradients — this {self._chronic_peer_noun} is training local SGD, not "
                f"collaborating; check connectivity/matchmaking (backing off matchmaking "
                f"exponentially)"
            )

    def _matchmaking_delay(self) -> float:
        """Matchmaking lead time, exponentially backed off under chronic failure
        (cap 8×), and never past half the averaging timeout — a scheduled_time
        beyond the step deadline would make every later round fail by
        construction, locking the peer in chronic failure even after the network
        heals."""
        excess = self._consecutive_failed_rounds - self.chronic_failure_threshold
        if excess < 0:
            delay = self.matchmaking_time
        else:
            delay = self.matchmaking_time * min(2.0 ** (excess + 1), 8.0)
        ceiling = getattr(self, "averaging_timeout", None)
        if ceiling:
            delay = min(delay, max(ceiling / 2.0, self.matchmaking_time))
        return delay
