"""Gradient accumulation + swarm averaging (capability parity: reference
hivemind/optim/grad_averager.py).

jax-first design: gradients arrive as pytrees/lists of jax arrays from the user's
jitted step; accumulators are HOST buffers (network-adjacent — all-reduce data must
reach the host anyway), so accumulate is a device→host add, not a torch .grad swap.
Three buffer roles as in the reference (grad_averager.py:23-29): live gradients
(user's), local accumulators, and the averager's shared averaged-gradient tensors."""

from __future__ import annotations

import contextlib
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from hivemind_tpu.averaging.averager import DecentralizedAverager
from hivemind_tpu.averaging.control import StepControl
from hivemind_tpu.compression.base import as_numpy
from hivemind_tpu.dht import DHT
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time

logger = get_logger(__name__)


class GradientAverager(DecentralizedAverager):
    """Accumulates local gradients toward a virtual large batch, then averages the
    accumulated gradients with a group of peers.

    :param tensor_shapes_like: list/pytree leaves of arrays defining gradient shapes
    :param local_updates: if True, peers apply updates locally and this averager is
        used only for state averaging (reference use_local_updates)
    """

    def __init__(
        self,
        tensors_like: Sequence,
        *,
        dht: DHT,
        prefix: str,
        reuse_grad_buffers: bool = False,
        accumulate_grads_on_host: bool = True,
        **kwargs,
    ):
        self.reuse_grad_buffers = reuse_grad_buffers
        templates = [as_numpy(t) for t in tensors_like]
        # accumulate_grads_on_host=False skips the host accumulator allocation (a
        # full model copy) for callers that stage gradients straight into the
        # shared tensors — e.g. SliceOptimizer, whose accumulation lives on device
        self._grad_accumulators: Optional[List[np.ndarray]] = (
            [np.zeros(t.shape, np.float32) for t in templates]
            if accumulate_grads_on_host
            else None
        )
        self.local_samples_accumulated = 0
        self.local_times_accumulated = 0
        self._new_averaged_grads = False
        super().__init__(
            averaged_tensors=[np.zeros(t.shape, np.float32) for t in templates],
            dht=dht,
            prefix=prefix,
            **kwargs,
        )

    def accumulate_grads_(self, grads: Iterable, batch_size: int) -> None:
        """Add one microbatch's gradients (jax or numpy arrays, already averaged over
        the microbatch) scaled by its size (reference grad_averager.py:129-148)."""
        grads = list(grads)
        assert self._grad_accumulators is not None, (
            "this averager was built with accumulate_grads_on_host=False — "
            "gradients are staged externally into the shared tensors"
        )
        assert len(grads) == len(self._grad_accumulators), (
            f"got {len(grads)} gradient tensors, expected {len(self._grad_accumulators)}"
        )
        for accumulator, grad in zip(self._grad_accumulators, grads):
            accumulator += np.asarray(as_numpy(grad), dtype=np.float32) * batch_size
        self.local_samples_accumulated += batch_size
        self.local_times_accumulated += 1

    def schedule_step(self, scheduled_time: Optional[DHTExpiration] = None, **kwargs) -> StepControl:
        """Begin matchmaking early; the accumulated gradients are loaded and the
        all-reduce triggered later, by step(control=...) (reference
        grad_averager.py:163-184). Bypasses this class's step override: accumulators
        must NOT be loaded yet."""
        assert kwargs.get("weight") is None, "weight is set automatically at trigger time"
        return DecentralizedAverager.step(
            self, scheduled_time=scheduled_time, wait=False, require_trigger=True, **kwargs
        )

    def step(
        self,
        weight: Optional[float] = None,
        control: Optional[StepControl] = None,
        reset_accumulators: bool = True,
        load_accumulators: bool = True,
        wait: bool = True,
        timeout: Optional[float] = None,
        **kwargs,
    ):
        """Average the accumulated gradients with the group; fills the shared
        averaged-gradient buffers (reference grad_averager.py:163-201).

        :param load_accumulators: stage the live accumulators into the shared buffers
            now. Delayed (DPU) updates stage them at schedule time instead and pass
            False, so gradients of the NEXT epoch accumulating concurrently cannot
            leak into the in-flight round."""
        if control is None:
            control = super().step(weight=weight, wait=False, require_trigger=True, timeout=timeout, **kwargs)
        elif weight is not None:
            control.weight = weight
        if load_accumulators:
            self.load_accumulators_into_averager_()
            if control.weight == 1.0 and self.local_samples_accumulated > 0:
                control.weight = self.local_samples_accumulated
            if reset_accumulators:
                self.reset_accumulated_grads_()
        control.allow_allreduce()
        return control.result(timeout) if wait else control

    def load_accumulators_into_averager_(self) -> None:
        """Normalize accumulators by sample count and copy into the shared tensors
        (reference grad_averager.py:203-210)."""
        assert self._grad_accumulators is not None, (
            "accumulate_grads_on_host=False: stage into the shared tensors directly"
        )
        denominator = max(self.local_samples_accumulated, 1)
        with self.get_tensors() as tensors:
            for tensor, accumulator in zip(tensors, self._grad_accumulators):
                np.divide(accumulator, denominator, out=tensor)
        self._new_averaged_grads = True

    def reset_accumulated_grads_(self) -> None:
        if self._grad_accumulators is not None:
            for accumulator in self._grad_accumulators:
                accumulator.fill(0.0)
        self.local_samples_accumulated = 0
        self.local_times_accumulated = 0

    @contextlib.contextmanager
    def use_averaged_gradients(self) -> Iterator[List[np.ndarray]]:
        """Access the averaged gradients after a successful step
        (reference grad_averager.py:221-235 swaps param.grad; here we just expose the
        buffers — the jax caller feeds them to its optax update)."""
        self._new_averaged_grads = False
        with self.get_tensors() as tensors:
            yield tensors

    def averaged_grads_as_jax(self):
        import jax.numpy as jnp

        with self.get_tensors() as tensors:
            return [jnp.asarray(t) for t in tensors]
