from hivemind_tpu.optim.grad_averager import GradientAverager
from hivemind_tpu.optim.grad_scaler import GradScaler
from hivemind_tpu.optim.nan_guard import NaNGuard
from hivemind_tpu.optim.optimizer import Optimizer
from hivemind_tpu.optim.power_sgd_averager import PowerSGDGradientAverager
from hivemind_tpu.optim.recovery import CheckpointError, LocalCheckpointStore, restore_from_local
from hivemind_tpu.optim.progress_tracker import (
    GlobalTrainingProgress,
    LocalTrainingProgress,
    ProgressTracker,
)
from hivemind_tpu.optim.slice_optimizer import SliceOptimizer
from hivemind_tpu.optim.state_averager import TrainingStateAverager
from hivemind_tpu.optim.training_averager import TrainingAverager
