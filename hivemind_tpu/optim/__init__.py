from hivemind_tpu.optim.grad_averager import GradientAverager
from hivemind_tpu.optim.optimizer import Optimizer
from hivemind_tpu.optim.progress_tracker import (
    GlobalTrainingProgress,
    LocalTrainingProgress,
    ProgressTracker,
)
from hivemind_tpu.optim.state_averager import TrainingStateAverager
