"""Serving-path attribution (ISSUE 9 tentpole): the serving ledger and the
client-side expert scorecards.

The training path already has deep attribution (metrics, traces, the round
ledger) — this module gives the *serving* path the same treatment. Two sides:

- **Server** — :class:`ServingLedger` (process-wide :data:`SERVING_LEDGER`, a
  sibling of :class:`~hivemind_tpu.telemetry.ledger.RoundLedger`) subscribes to
  finished spans (:func:`~hivemind_tpu.telemetry.tracing.add_span_listener`)
  and assembles **one record per expert request** from the ``serving.request``
  span the :class:`~hivemind_tpu.moe.server.connection_handler.ConnectionHandler`
  opens around every ``rpc_forward`` / ``rpc_backward`` / ``rpc_decode`` (and
  their streaming variants). The record decomposes the request into
  **queue-wait / batch-assembly / device-compute / serialize** phases (the
  TaskPool stamps the first three onto the span, the handler stamps the
  fourth), carries the batch occupancy its device batch ran at (samples ÷
  ``max_batch_size`` — the TPU-serving lever arxiv 2605.25645 optimizes), and
  names the calling client. Because the handler span joins the remote caller's
  trace via the existing cross-peer propagation, the record's ``trace`` id is
  the *caller's* trace — ``hivemind-top`` can name which expert on which peer
  ate a slow request's time.
- **Client** — :class:`ExpertScorecards` (process-wide :data:`SCORECARDS`)
  accrues per-expert outcome cards from every
  :meth:`~hivemind_tpu.moe.client.expert.RemoteExpert._call`: success rate,
  latency quantiles, timeouts, and **sheds** (the server's typed
  ``ServerOverloadedError`` load-shed answer, recognized across the RPC
  boundary by :func:`is_overload_error` and fed into the existing
  ``EXPERT_BREAKERS``).

Both views ride the DHT peer snapshot (``serving`` key, size-budgeted like the
round ledger) and are served raw at ``GET /serving`` on the MetricsExporter.
Cost discipline matches the round ledger: the span listener is one name check
per finished span; per-request work is a few dict ops under one lock; nothing
serializes off the export path.
"""

from __future__ import annotations

import sys
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from hivemind_tpu.telemetry.ledger import _percentile
from hivemind_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from hivemind_tpu.telemetry.tracing import Span, add_span_listener, current_span, wall_time
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# the span name the ConnectionHandler opens per expert request; the ONLY name
# this ledger reacts to (everything else is one failed string compare)
SERVING_SPAN = "serving.request"

# the server's typed load-shed errors travel as "<TypeName>: <msg>" inside
# P2PHandlerError text (mux ERROR frames carry type name + message), so the
# client recognizes a shed without importing the server module. Two kinds:
# the pool's bounded-queue shed, and the fair-share admission shed (ISSUE 13,
# a subclass — one hot client over its token budget while others keep flowing)
OVERLOAD_ERROR_NAME = "ServerOverloadedError"
OVERLOAD_ERROR_NAMES = (OVERLOAD_ERROR_NAME, "ClientOverBudgetError")

# phase attributes the TaskPool / handler stamp onto the serving span
_PHASE_FIELDS = ("queue_wait_s", "assembly_s", "compute_s", "serialize_s")

# registry families the summary reads for the saturation columns (absent
# families — a layer that never loaded — contribute nothing)
_SATURATION_GAUGES = {
    "queue_depth": "hivemind_moe_pool_queue_depth",
    "queue_age_s": "hivemind_moe_queue_age_seconds",
    "decode_sessions": "hivemind_moe_decode_sessions",
    "decode_session_occupancy": "hivemind_moe_decode_session_occupancy",
    "runtime_utilization": "hivemind_moe_runtime_utilization",
}
_SATURATION_COUNTERS = {
    "sheds": "hivemind_moe_shed_total",
    "decode_evictions": "hivemind_moe_decode_session_evictions_total",
    "decode_resets": "hivemind_moe_decode_session_resets_total",
    "wire_bytes_sent": "hivemind_moe_bytes_sent_total",
    "wire_bytes_received": "hivemind_moe_bytes_received_total",
}

# serving-path wire accounting (ISSUE 10): serialized expert RPC payload bytes
# by the role this process played — "client" = RemoteExpert callers here,
# "server" = the ConnectionHandler. The compressed-RPC win (fp16 activations ≈
# half the fp32 wire bytes) is read directly off these, and the llama serving
# benchmark asserts they move in --smoke mode.
WIRE_BYTES_SENT = REGISTRY.counter(
    "hivemind_moe_bytes_sent_total",
    "expert RPC payload bytes sent on the serving path",
    ("direction",),
)
WIRE_BYTES_RECEIVED = REGISTRY.counter(
    "hivemind_moe_bytes_received_total",
    "expert RPC payload bytes received on the serving path",
    ("direction",),
)

# replica robustness accounting (ISSUE 13): hedges fired when an in-flight
# request crossed the expert's scorecard p95, who won the race, and failovers
# onto another replica after a shed / connection loss. Client-side counters
# (this process as the caller), cataloged in docs/observability.md.
HEDGES = REGISTRY.counter(
    "hivemind_moe_hedge_total",
    "hedged expert requests by outcome (fired / primary_won / hedge_won)",
    ("outcome",),
)
REPLICA_FAILOVERS = REGISTRY.counter(
    "hivemind_moe_replica_failover_total",
    "expert calls retried on another replica after a typed shed or connection loss",
    ("kind",),
)


def is_overload_error(error: BaseException) -> bool:
    """True when ``error`` is (or wraps, across the RPC boundary) one of the
    server's typed load-shed answers. String-matched so the client side needs
    no import of the server module and a P2PHandlerError re-raise still
    classifies."""
    text = f"{type(error).__name__}: {error}"
    return any(name in text for name in OVERLOAD_ERROR_NAMES)


def accrue_span_phase(key: str, seconds: float) -> None:
    """Add ``seconds`` onto the active serving span's phase attribute. A span
    chain runs several pools/steps sequentially, so phases ACCUMULATE per
    request (TaskPool stamps queue_wait/assembly/compute, the handler stamps
    serialize — this module owns the phase-field vocabulary)."""
    span = current_span()
    if span is not None:
        previous = (span.attributes or {}).get(key, 0.0)
        span.set(key, round(float(previous) + seconds, 6))


def _quantiles(values: List[float]) -> Dict[str, float]:
    return {
        "mean": round(sum(values) / len(values), 6),
        "p50": round(_percentile(values, 0.5), 6),
        "p95": round(_percentile(values, 0.95), 6),
    }


class _ExpertStats:
    __slots__ = ("requests", "errors", "sheds", "total_s", "durations")

    def __init__(self, window: int):
        self.requests = 0
        self.errors = 0
        self.sheds = 0
        self.total_s = 0.0
        self.durations: "deque[float]" = deque(maxlen=window)


class ServingLedger:
    """See module docstring. One process-wide instance (:data:`SERVING_LEDGER`)
    is fed by the span listener; tests may build private instances and call
    :meth:`on_span` directly."""

    def __init__(
        self,
        capacity: int = 256,
        expert_window: int = 128,
        max_experts: int = 256,
        max_clients: int = 256,
        slowest_capacity: int = 8,
        registry: MetricsRegistry = REGISTRY,
        scorecards: Optional["ExpertScorecards"] = None,
    ):
        self._lock = threading.Lock()
        self._registry = registry
        # injected like the registry: an exporter bound to a private ledger
        # must not leak the process-global scorecards (None = the global)
        self._scorecards = scorecards
        self._expert_window = expert_window
        self._max_experts = max_experts
        self._max_clients = max_clients
        self._records: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        # the N slowest requests ever seen since clear(), slowest first — the
        # exemplars the dashboard shows next to the quantiles
        self._slowest: List[Dict[str, Any]] = []
        self._slowest_capacity = slowest_capacity
        self._experts: Dict[str, _ExpertStats] = {}
        self._clients: Dict[str, Dict[str, float]] = {}
        self._request_index = 0
        self._totals = {"requests": 0, "errors": 0, "sheds": 0}
        # record listeners (the black-box spool subscribes): called with
        # ("serving", copied record) OUTSIDE the lock — file I/O must not
        # serialize the serving hot path
        self._record_listeners: List = []

    def add_record_listener(self, listener) -> None:
        if listener not in self._record_listeners:
            self._record_listeners.append(listener)

    def remove_record_listener(self, listener) -> None:
        try:
            self._record_listeners.remove(listener)
        except ValueError:
            pass

    # ------------------------------------------------------------------ feeding

    def on_span(self, span: Span) -> None:
        """Span listener: one name compare per finished span; record assembly
        only for serving.request spans."""
        if span.name != SERVING_SPAN:
            return
        attrs = span.attributes or {}
        error_type: Optional[str] = None
        for _when, event_name, event_attrs in span.events or ():
            if event_name == "error":
                error_type = str((event_attrs or {}).get("type", "error"))
        record: Dict[str, Any] = {
            "expert": str(attrs.get("expert", "?")),
            "kind": str(attrs.get("kind", "?")),
            "client": str(attrs.get("client", "?")),
            "peer": str(attrs.get("peer", "?")),
            "total_s": round(span.duration, 6),
            "trace": f"{span.trace_id:016x}",
        }
        for field in _PHASE_FIELDS:
            value = attrs.get(field)
            if value is not None:
                record[field] = round(float(value), 6)
        for field in ("batch", "occupancy", "pool", "span_len"):
            if field in attrs:
                record[field] = attrs[field]
        if error_type is not None:
            record["error"] = error_type
        with self._lock:
            self._request_index += 1
            record["request"] = self._request_index
            record["time"] = round(wall_time(), 3)
            self._records.append(record)
            self._totals["requests"] += 1
            stats = self._expert_stats(record["expert"])
            stats.requests += 1
            stats.total_s = round(stats.total_s + record["total_s"], 6)
            stats.durations.append(record["total_s"])
            if error_type is not None:
                self._totals["errors"] += 1
                stats.errors += 1
                if error_type in OVERLOAD_ERROR_NAMES:
                    self._totals["sheds"] += 1
                    stats.sheds += 1
            client = self._client_stats(record["client"])
            client["requests"] += 1
            client["total_s"] = round(client["total_s"] + record["total_s"], 6)
            if error_type is not None:
                client["errors"] += 1
            # slowest-request exemplars: a sorted top-N, cheap at N=8
            if (
                len(self._slowest) < self._slowest_capacity
                or record["total_s"] > self._slowest[-1]["total_s"]
            ):
                self._slowest.append(dict(record))
                self._slowest.sort(key=lambda r: -r["total_s"])
                del self._slowest[self._slowest_capacity:]
            published = dict(record) if self._record_listeners else None
        if published is not None:
            for listener in self._record_listeners:
                try:
                    listener("serving", published)
                except Exception as e:  # pragma: no cover - listeners stay harmless
                    logger.debug(f"serving record listener failed: {e!r}")

    def _expert_stats(self, uid: str) -> _ExpertStats:
        stats = self._experts.get(uid)
        if stats is None:
            if len(self._experts) >= self._max_experts:
                # uid cardinality is server-controlled but bound it anyway
                self._experts.pop(next(iter(self._experts)), None)
            stats = self._experts[uid] = _ExpertStats(self._expert_window)
        return stats

    def _client_stats(self, client: str) -> Dict[str, float]:
        stats = self._clients.get(client)
        if stats is None:
            if len(self._clients) >= self._max_clients:
                # client ids are REMOTE-controlled: a peer cycling identities
                # must not grow this dict without bound
                self._clients.pop(next(iter(self._clients)), None)
            stats = self._clients[client] = {"requests": 0, "errors": 0, "total_s": 0.0}
        return stats

    def _gauge_values(self, metric_name: str) -> Dict[str, float]:
        metric = self._registry.get(metric_name)
        if metric is None:
            return {}
        out = {}
        for key, child in metric.series():
            out[",".join(key) or "_"] = round(child.value, 6)  # type: ignore[union-attr]
        return out

    def _counter_total(self, metric_name: str) -> float:
        metric = self._registry.get(metric_name)
        if metric is None:
            return 0.0
        return round(sum(child.value for _k, child in metric.series()), 6)  # type: ignore[union-attr]

    # ------------------------------------------------------------------ reading

    def records(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._records)
        if limit:
            records = records[-limit:]
        return [dict(record) for record in records]

    def expert_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-expert latency quantiles + counters, busiest expert first."""
        with self._lock:
            items = [
                (uid, stats.requests, stats.errors, stats.sheds, stats.total_s,
                 list(stats.durations))
                for uid, stats in self._experts.items()
            ]
        out: Dict[str, Dict[str, Any]] = {}
        for uid, requests, errors, sheds, total_s, durations in sorted(
            items, key=lambda item: -item[1]
        ):
            entry: Dict[str, Any] = {"requests": requests, "total_s": round(total_s, 6)}
            if errors:
                entry["errors"] = errors
            if sheds:
                entry["sheds"] = sheds
            if durations:
                entry.update({f"{k}_s": v for k, v in _quantiles(durations).items()})
            out[uid] = entry
        return out

    def client_stats(self, limit: Optional[int] = None) -> Dict[str, Dict[str, float]]:
        with self._lock:
            items = sorted(
                ((client, dict(stats)) for client, stats in self._clients.items()),
                key=lambda kv: -kv[1]["requests"],
            )
        return dict(items[:limit] if limit else items)

    def slowest(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            slowest = [dict(record) for record in self._slowest]
        return slowest[:limit] if limit else slowest

    def saturation(self) -> Dict[str, Any]:
        """The live saturation view read from the registry (queue depth/age per
        pool, decode-session occupancy, runtime utilization, shed totals) — the
        levers the records explain."""
        # refresh depth/age at READ time: a fully stalled server neither
        # submits nor drains, so event-driven sampling alone would report the
        # pre-stall age forever (lazy module lookup — telemetry must never
        # force a moe import, and sampling must never fail a scrape)
        task_pool = sys.modules.get("hivemind_tpu.moe.server.task_pool")
        if task_pool is not None:
            try:
                task_pool.sample_all_pool_gauges()
            except Exception as e:  # pragma: no cover - best effort
                logger.debug(f"pool gauge refresh failed: {e!r}")
        out: Dict[str, Any] = {}
        for field, metric_name in _SATURATION_GAUGES.items():
            values = self._gauge_values(metric_name)
            if values:
                out[field] = values
        for field, metric_name in _SATURATION_COUNTERS.items():
            total = self._counter_total(metric_name)
            if total:
                out[field] = total
        return out

    def summary(self) -> Dict[str, Any]:
        """Compact rollup for BENCH artifacts and the dashboard header: request
        and shed counts, per-phase quantiles, batch occupancy, per-expert
        p50/p95 — a serving regression's artifact then says WHERE the
        regression lives (queue? device? serialize? one expert?)."""
        records = self.records()
        with self._lock:
            out: Dict[str, Any] = dict(self._totals)
        phases: Dict[str, Any] = {}
        for field in ("total_s",) + _PHASE_FIELDS:
            values = [r[field] for r in records if field in r]
            if values:
                phases[field] = _quantiles(values)
        if phases:
            out["phases"] = phases
        occupancies = [r["occupancy"] for r in records if "occupancy" in r]
        if occupancies:
            out["batch_occupancy"] = _quantiles([float(o) for o in occupancies])
        experts = self.expert_stats()
        if experts:
            out["experts"] = experts
        saturation = self.saturation()
        if saturation:
            out["saturation"] = saturation
        return out

    def snapshot(
        self, max_experts: int = 8, max_clients: int = 5, max_slowest: int = 3
    ) -> Dict[str, Any]:
        """The compact view that rides the DHT peer snapshot: totals, busiest
        experts, top clients, slowest exemplars, and the live saturation
        gauges. Size-budgeted by monitor._shrink_to_fit."""
        out: Dict[str, Any] = {}
        with self._lock:
            totals = dict(self._totals)
        if not totals["requests"]:
            return out
        out["totals"] = totals
        experts = self.expert_stats()
        if experts:
            out["experts"] = dict(list(experts.items())[:max_experts])
        clients = self.client_stats(limit=max_clients)
        if clients:
            out["clients"] = clients
        slowest = self.slowest(limit=max_slowest)
        if slowest:
            out["slowest"] = slowest
        saturation = self.saturation()
        if saturation:
            out["saturation"] = saturation
        return out

    def export(self) -> Dict[str, Any]:
        """Everything, raw — the ``GET /serving`` response body (plus the
        paired client-side scorecards, so one endpoint answers both roles)."""
        scorecards = self._scorecards if self._scorecards is not None else SCORECARDS
        return {
            "records": self.records(),
            "experts": self.expert_stats(),
            "clients": self.client_stats(),
            "slowest": self.slowest(),
            "summary": self.summary(),
            "scorecards": scorecards.export(),
        }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._slowest.clear()
            self._experts.clear()
            self._clients.clear()
            self._request_index = 0
            self._totals = {"requests": 0, "errors": 0, "sheds": 0}

    def __len__(self) -> int:
        return len(self._records)


# ------------------------------------------------------------------ client side


class ExpertScorecards:
    """Per-expert outcome cards accrued by the CLIENT (RemoteExpert._call and
    the call_many fan-out): success rate, latency quantiles, timeouts, sheds.
    These are the client's view of the swarm's serving quality — they ride the
    DHT snapshot so the operator sees which experts are slow or shedding from
    the *caller's* side, not just the server's."""

    def __init__(self, max_experts: int = 256, window: int = 128, max_replicas: int = 8):
        self._lock = threading.Lock()
        self._max_experts = max_experts
        self._window = window
        self._max_replicas = max_replicas  # per-card replica sub-entries (bounded)
        self._cards: Dict[str, Dict[str, Any]] = {}

    def record(
        self,
        uid: str,
        seconds: float,
        ok: bool,
        kind: str = "forward",
        error: Optional[BaseException] = None,
    ) -> None:
        """Classify one RPC outcome: ok / shed / timeout / failure. Cancelled
        calls count as timeouts (the fan-out cancels exactly the stragglers it
        abandoned at a deadline)."""
        import asyncio

        outcome = "ok"
        if not ok:
            if error is not None and is_overload_error(error):
                outcome = "sheds"
            elif isinstance(error, (asyncio.TimeoutError, asyncio.CancelledError)):
                outcome = "timeouts"
            else:
                outcome = "failures"
        with self._lock:
            card = self._card(uid)
            card["requests"] += 1
            card["kinds"][kind] = card["kinds"].get(kind, 0) + 1
            if outcome == "ok":
                card["ok"] += 1
                card["durations"].append(seconds)
            else:
                card[outcome] += 1
                card["last_error"] = f"{type(error).__name__}: {error}"[:200] if error else outcome

    # ------------------------------------------------------------ replica level

    def _card(self, uid: str) -> Dict[str, Any]:
        card = self._cards.get(uid)
        if card is None:
            if len(self._cards) >= self._max_experts:
                self._cards.pop(next(iter(self._cards)), None)
            card = self._cards[uid] = {
                "requests": 0, "ok": 0, "failures": 0, "timeouts": 0, "sheds": 0,
                "durations": deque(maxlen=self._window), "kinds": {},
            }
        return card

    def record_replica(self, uid: str, replica: str, seconds: float, ok: bool,
                       shed: bool = False) -> None:
        """One per-replica attempt outcome (ISSUE 13): feeds the latency view
        :meth:`replica_latency` that RemoteExpert load-balances and hedges by.
        Attempt-level — the uid-level :meth:`record` still fires exactly once
        per logical call, so existing totals keep their meaning. A hedge's
        cancelled loser is never recorded here (no outcome happened)."""
        with self._lock:
            stats = self._replica_stats(self._card(uid), replica)
            stats["requests"] += 1
            if ok:
                stats["ok"] += 1
                stats["durations"].append(seconds)
            elif shed:
                stats["sheds"] += 1
            else:
                stats["failures"] += 1

    def _replica_stats(self, card: Dict[str, Any], replica: str) -> Dict[str, Any]:
        replicas = card.setdefault("replicas", {})
        stats = replicas.get(replica)
        if stats is None:
            if len(replicas) >= self._max_replicas:
                replicas.pop(next(iter(replicas)), None)
            stats = replicas[replica] = {
                "requests": 0, "ok": 0, "failures": 0, "sheds": 0,
                "durations": deque(maxlen=self._window),
            }
        return stats

    def note_hedge_loss(self, uid: str, replica: str, elapsed: float) -> None:
        """The hedge's cancelled loser: NOT a failure, NOT a breaker strike —
        but ``elapsed`` is a real censored observation ("this replica took at
        least this long"), appended to the replica's latency window so a
        consistently-hanging replica drifts down the routing order instead of
        winning the next pick on stale fast quantiles."""
        with self._lock:
            stats = self._replica_stats(self._card(uid), replica)
            stats["durations"].append(elapsed)
            stats["hedge_losses"] = stats.get("hedge_losses", 0) + 1

    def replica_latency(self, uid: str, replica: str, quantile: float = 0.95
                        ) -> Optional[float]:
        """The replica's observed latency quantile — falls back to the expert's
        uid-level window when this replica is cold; None when both are cold
        (a cold expert fires no hedge and keeps its seeded initial choice)."""
        with self._lock:
            card = self._cards.get(uid)
            if card is None:
                return None
            stats = (card.get("replicas") or {}).get(replica)
            durations = list(stats["durations"]) if stats and stats["durations"] else None
            if durations is None:
                durations = list(card["durations"]) or None
        if durations is None:
            return None
        return _percentile(durations, quantile)

    def replica_health(self, uid: str, replica: str) -> Tuple[float, float]:
        """``(mean_latency_or_inf, failure_rate)`` for replica ordering: cold
        replicas sort last among known ones (inf latency) so the seeded rng
        breaks the tie, and a shedding/failing replica ranks after a clean one."""
        with self._lock:
            card = self._cards.get(uid)
            stats = ((card or {}).get("replicas") or {}).get(replica)
            if not stats:
                return float("inf"), 0.0
            durations = list(stats["durations"])
            requests = max(stats["requests"], 1)
            bad = stats["failures"] + stats["sheds"]
        mean = sum(durations) / len(durations) if durations else float("inf")
        return mean, bad / requests

    def card(self, uid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            card = self._cards.get(uid)
            return self._render(uid, card) if card is not None else None

    @staticmethod
    def _render(uid: str, card: Dict[str, Any]) -> Dict[str, Any]:
        out = {
            k: v for k, v in card.items() if k not in ("durations", "kinds", "replicas")
        }
        out["success_rate"] = round(card["ok"] / max(card["requests"], 1), 4)
        durations = list(card["durations"])
        if durations:
            out.update({f"{k}_s": v for k, v in _quantiles(durations).items()})
        out["kinds"] = dict(card["kinds"])
        replicas = card.get("replicas")
        if replicas:
            rendered = {}
            for peer, stats in replicas.items():
                entry = {k: v for k, v in stats.items() if k != "durations"}
                replica_durations = list(stats["durations"])
                if replica_durations:
                    entry.update(
                        {f"{k}_s": v for k, v in _quantiles(replica_durations).items()}
                    )
                rendered[peer] = entry
            out["replicas"] = rendered
        return out

    def snapshot(self, limit: int = 16) -> Dict[str, Dict[str, Any]]:
        """Busiest experts first, compact (DHT snapshot / hivemind-top)."""
        with self._lock:
            items = sorted(self._cards.items(), key=lambda kv: -kv[1]["requests"])[:limit]
            return {uid: self._render(uid, card) for uid, card in items}

    def export(self) -> Dict[str, Dict[str, Any]]:
        return self.snapshot(limit=10**9)

    def clear(self) -> None:
        with self._lock:
            self._cards.clear()

    def __len__(self) -> int:
        return len(self._cards)


# ------------------------------------------------------------------ board data


def collect_swarm_serving(records: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-peer snapshots' ``serving`` sections into structured board
    data — the ONE parser behind both serving renderers (``hivemind-top
    --serving`` and ``SwarmMonitor.render_serving_board``), so a snapshot
    schema change cannot make the two boards silently disagree.

    Returns ``{"experts": [(peer, uid, stats)], "saturation": [(peer, entry)],
    "degraded_scorecards": [(peer, uid, card)], "slowest": [(total_s, peer,
    record)] (slowest first), "malformed": [peer]}``. Snapshots are
    DHT-supplied: a malformed (buggy/version-skewed/hostile) peer lands in
    ``malformed``, never in an exception."""
    experts: List[Tuple[str, str, Dict[str, Any]]] = []
    saturation: List[Tuple[str, Dict[str, float]]] = []
    degraded: List[Tuple[str, str, Dict[str, Any]]] = []
    slowest: List[Tuple[float, str, Dict[str, Any]]] = []
    malformed: List[str] = []
    for peer, snapshot in sorted(records.items(), key=lambda kv: str(kv[0])):
        serving = snapshot.get("serving") if isinstance(snapshot, dict) else None
        if serving is None:
            continue  # peer simply reports no serving section
        if not isinstance(serving, dict):
            malformed.append(str(peer))  # present but unparseable: flag, don't hide
            continue
        # remember list lengths so a mid-parse failure rolls this peer's
        # partial rows back — a malformed peer must appear ONCE, in malformed,
        # not twice with half its data
        marks = (len(experts), len(saturation), len(degraded), len(slowest))
        try:
            for uid, stats in (serving.get("experts") or {}).items():
                p95 = stats.get("p95_s")
                experts.append((str(peer), str(uid), {
                    "requests": float(stats.get("requests", 0) or 0),
                    "p95_s": float(p95) if isinstance(p95, (int, float)) else None,
                    "sheds": int(stats.get("sheds", 0) or 0),
                }))
            sat = serving.get("saturation") or {}
            entry: Dict[str, float] = {}
            depth = sat.get("queue_depth") or {}
            if depth:
                entry["queue_depth_max"] = max(float(v) for v in depth.values())
            age = sat.get("queue_age_s") or {}
            if age:
                oldest = max(float(v) for v in age.values())
                if oldest > 0:
                    entry["queue_age_max_s"] = oldest
            for field, source in (
                ("runtime_utilization", "runtime_utilization"),
                ("decode_session_occupancy", "decode_session_occupancy"),
            ):
                values = list((sat.get(source) or {}).values())
                if values:
                    entry[field] = float(values[0])
            for field in ("wire_bytes_sent", "wire_bytes_received"):
                if sat.get(field):
                    entry[field] = float(sat[field])
            if sat.get("sheds"):
                entry["sheds"] = float(sat["sheds"])
            if entry:
                saturation.append((str(peer), entry))
            for uid, card in (serving.get("scorecards") or {}).items():
                rate = float(card.get("success_rate", 1.0) or 0.0)
                if rate < 1.0 or card.get("sheds") or card.get("timeouts"):
                    degraded.append((str(peer), str(uid), dict(card)))
            for record in serving.get("slowest") or ():
                slowest.append(
                    (float(record.get("total_s", 0.0) or 0.0), str(peer), dict(record))
                )
        except (TypeError, ValueError, AttributeError) as e:
            logger.debug(f"malformed serving section from {peer!r}: {e!r}")
            del experts[marks[0]:], saturation[marks[1]:], degraded[marks[2]:], slowest[marks[3]:]
            malformed.append(str(peer))
    slowest.sort(key=lambda item: -item[0])
    return {
        "experts": experts,
        "saturation": saturation,
        "degraded_scorecards": degraded,
        "slowest": slowest,
        "malformed": malformed,
    }


def format_slowest_phases(record: Dict[str, Any]) -> str:
    """``queue_wait=180.0ms compute=28.0ms …`` from one slowest-request record
    (shared by both renderers)."""
    return " ".join(
        f"{name[:-2]}={float(record[name]) * 1e3:.1f}ms"
        for name in ("queue_wait_s", "assembly_s", "compute_s", "serialize_s")
        if isinstance(record.get(name), (int, float))
    )


def format_saturation_parts(entry: Dict[str, float], red: str = "", reset: str = "") -> List[str]:
    """One peer's saturation summary as phrase parts — the ONE wording both
    renderers print, so the boards cannot drift apart."""
    parts: List[str] = []
    if "queue_depth_max" in entry:
        parts.append(f"queue depth max {entry['queue_depth_max']:g}")
    if "queue_age_max_s" in entry:
        parts.append(f"oldest task {entry['queue_age_max_s']:.2f}s")
    if "runtime_utilization" in entry:
        parts.append(f"runtime util {entry['runtime_utilization']:.0%}")
    if "decode_session_occupancy" in entry:
        parts.append(f"decode sessions {entry['decode_session_occupancy']:.0%} full")
    if "wire_bytes_sent" in entry or "wire_bytes_received" in entry:
        parts.append(
            f"wire {entry.get('wire_bytes_sent', 0.0) / 1e6:.1f}MB out"
            f" / {entry.get('wire_bytes_received', 0.0) / 1e6:.1f}MB in"
        )
    if "sheds" in entry:
        parts.append(f"{red}SHEDS {entry['sheds']:g}{reset}")
    return parts


def format_scorecard_line(
    peer: str, uid: str, card: Dict[str, Any], peer_width: int = 14, uid_width: int = 22
) -> str:
    """One degraded client-side scorecard line (shared by both renderers)."""
    return (
        f"{peer[:peer_width]:<{peer_width}} sees {uid[:uid_width]:<{uid_width}} "
        f"ok={float(card.get('success_rate', 0.0) or 0.0):.0%} "
        f"timeouts={card.get('timeouts', 0)} sheds={card.get('sheds', 0)} "
        f"fails={card.get('failures', 0)}"
    )


def format_slowest_line(
    total_s: float, peer: str, record: Dict[str, Any],
    peer_width: int = 14, uid_width: int = 22,
) -> str:
    """One slowest-request exemplar line with its phase decomposition (shared
    by both renderers)."""
    phases = format_slowest_phases(record)
    return (
        f"{total_s * 1e3:8.1f}ms {str(record.get('expert'))[:uid_width]:<{uid_width}} "
        f"@ {peer[:peer_width]} kind={record.get('kind')} "
        f"client={str(record.get('client'))[:peer_width]}"
        + (f"  [{phases}]" if phases else "")
    )


SERVING_LEDGER = ServingLedger()
SCORECARDS = ExpertScorecards()
add_span_listener(SERVING_LEDGER.on_span)
