"""Event-loop watchdog (ISSUE 8 tentpole): lag probe, stall stack capture, and
executor-queue-depth gauges.

The whole stack runs on one shared asyncio loop (utils/loop.py) — so the most
common *silent* failure mode is a blocked event loop: a synchronous call that
sneaks onto the loop thread makes this peer stop answering matchmaking, DHT
RPCs and part streams at once, and to the rest of the swarm it is
indistinguishable from a network straggler. The watchdog makes that failure
loud and attributable:

- **lag probe** — a daemon thread schedules a heartbeat callback onto the
  watched loop every ``HIVEMIND_WATCHDOG_INTERVAL_S`` (default 0.25 s) and
  observes scheduled→executed delta into the
  ``hivemind_event_loop_lag_seconds`` histogram (label: ``loop``);
- **stall capture** — when the heartbeat does not land within
  ``HIVEMIND_STALL_THRESHOLD_S`` (default 1.0 s), the loop thread's stack is
  captured *right now* via ``sys._current_frames()`` — naming the exact frame
  that is blocking — logged, attached as an ``event_loop.stall`` event on the
  span active on the loop thread, kept on ``last_stall`` for programmatic
  consumers, and counted in ``hivemind_event_loop_stalls_total``. One stall
  episode counts once, however long it lasts;
- **executor gauges** — each tick samples the shared thread pools' backlog
  into ``hivemind_executor_queue_depth`` (label: ``executor`` ∈ ``blocking`` /
  ``lock`` / ``aead``): a deep blocking-pool queue with a healthy loop means
  the *executor* is the bottleneck, not the loop.

Wiring: :func:`ensure_watchdog` is idempotent per loop and called wherever a
loop-owning component starts — the averager, the DHT, the MoE server, and the
CLI entrypoints — so any process that participates in a swarm is watched
without the operator doing anything. ``HIVEMIND_WATCHDOG=0`` disables it.
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from hivemind_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from hivemind_tpu.telemetry.tracing import thread_current_span, wall_time
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

enabled = os.environ.get("HIVEMIND_WATCHDOG", "1") != "0"

DEFAULT_STALL_THRESHOLD_S = float(os.environ.get("HIVEMIND_STALL_THRESHOLD_S", "1.0"))
DEFAULT_INTERVAL_S = float(os.environ.get("HIVEMIND_WATCHDOG_INTERVAL_S", "0.25"))

# loop lag skews far smaller than RPC latency: sub-millisecond buckets matter
_LAG_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_LOOP_LAG = REGISTRY.histogram(
    "hivemind_event_loop_lag_seconds",
    "scheduled-to-executed delta of the watchdog heartbeat on an event loop",
    ("loop",),
    buckets=_LAG_BUCKETS,
)
_STALLS = REGISTRY.counter(
    "hivemind_event_loop_stalls_total",
    "event-loop stalls (heartbeat missing past the stall threshold)",
    ("loop",),
)
_EXECUTOR_DEPTH = REGISTRY.gauge(
    "hivemind_executor_queue_depth",
    "tasks queued (not yet running) in a shared thread pool",
    ("executor",),
)


# extra per-tick samplers (ISSUE 19): device telemetry registers its memory
# probe here so HBM gauges ride the existing watchdog cadence instead of
# growing another daemon thread. Samplers must be cheap and never instantiate
# lazy state (same discipline as _executor_queue_depths).
_TICK_SAMPLERS: List = []


def add_tick_sampler(sampler) -> None:
    """Register a zero-arg callable invoked on every watchdog tick (all
    watchdogs). Idempotent; exceptions are swallowed per tick."""
    if sampler not in _TICK_SAMPLERS:
        _TICK_SAMPLERS.append(sampler)


def remove_tick_sampler(sampler) -> None:
    try:
        _TICK_SAMPLERS.remove(sampler)
    except ValueError:
        pass


def _run_tick_samplers() -> None:
    for sampler in list(_TICK_SAMPLERS):
        try:
            sampler()
        except Exception as e:
            logger.debug(f"watchdog tick sampler failed: {e!r}")


def _executor_queue_depths() -> Dict[str, int]:
    """Backlogs of the shared pools; only pools that already exist are sampled
    (peeking must never instantiate an executor)."""
    depths: Dict[str, int] = {}
    asyncio_utils = sys.modules.get("hivemind_tpu.utils.asyncio_utils")
    if asyncio_utils is not None:
        for label, attr in (("blocking", "_blocking_executor"), ("lock", "_lock_executor")):
            executor = getattr(asyncio_utils, attr, None)
            if executor is not None:
                depths[label] = executor._work_queue.qsize()
    crypto_channel = sys.modules.get("hivemind_tpu.p2p.crypto_channel")
    if crypto_channel is not None:
        aead = getattr(crypto_channel, "_aead_executor", None)
        if aead is not None:
            depths["aead"] = aead._work_queue.qsize()
    return depths


class EventLoopWatchdog:
    """Watch one asyncio loop from a daemon thread. Use :func:`ensure_watchdog`
    in production code; tests construct private instances with tight thresholds
    and their own registry."""

    def __init__(
        self,
        loop: asyncio.AbstractEventLoop,
        name: str = "loop",
        *,
        interval: Optional[float] = None,
        stall_threshold: Optional[float] = None,
        registry: MetricsRegistry = REGISTRY,
        start: bool = True,
    ):
        self.loop = loop
        self.name = name
        self.interval = interval if interval is not None else DEFAULT_INTERVAL_S
        self.stall_threshold = (
            stall_threshold if stall_threshold is not None else DEFAULT_STALL_THRESHOLD_S
        )
        self._lag = registry.histogram(
            "hivemind_event_loop_lag_seconds",
            _LOOP_LAG.documentation,
            ("loop",),
            buckets=_LAG_BUCKETS,
        ).labels(name)
        self._stall_counter = registry.counter(
            "hivemind_event_loop_stalls_total", _STALLS.documentation, ("loop",)
        ).labels(name)
        self._depth_gauge = registry.gauge(
            "hivemind_executor_queue_depth", _EXECUTOR_DEPTH.documentation, ("executor",)
        )
        self.max_lag = 0.0
        self.stalls = 0
        self.last_stall: Optional[Dict[str, Any]] = None
        self._loop_thread_id: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"loop-watchdog-{self.name}", daemon=True
        )
        self._thread.start()

    @property
    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.loop.is_closed():
                break
            if not self._tick():
                break
            self._sample_executors()
            _run_tick_samplers()
            self._stop.wait(self.interval)

    def _tick(self) -> bool:
        """One heartbeat round-trip; returns False when the loop is gone."""
        fired = threading.Event()
        executed: List[float] = []

        def _beat() -> None:
            executed.append(time.perf_counter())
            if self._loop_thread_id is None:
                self._loop_thread_id = threading.get_ident()
            fired.set()

        scheduled = time.perf_counter()
        try:
            self.loop.call_soon_threadsafe(_beat)
        except RuntimeError:
            return False  # loop closed under us: a normal shutdown, not a stall
        if not fired.wait(self.stall_threshold):
            # a stopping/closed loop discards scheduled callbacks: that is a
            # clean shutdown, not a stall (is_running stays True while a
            # genuinely BLOCKED loop sits inside a callback, so real stalls
            # still capture)
            if self._stop.is_set() or self.loop.is_closed() or not self.loop.is_running():
                return False
            self._capture_stall(scheduled)
            # keep waiting for THIS heartbeat: the episode's full length lands
            # in the histogram once, and heartbeats never pile up behind a stall
            while not fired.wait(self.stall_threshold):
                # same exits as above: a loop stopped (but perhaps never
                # closed) after the capture must not wedge this thread forever
                if self._stop.is_set() or self.loop.is_closed() or not self.loop.is_running():
                    return False
        lag = max(executed[0] - scheduled, 0.0)
        self.max_lag = max(self.max_lag, lag)
        self._lag.observe(lag)
        return True

    def _capture_stall(self, scheduled: float) -> None:
        stack = "<loop thread not identified yet>"
        blocked_for = time.perf_counter() - scheduled
        if self._loop_thread_id is not None:
            frame = sys._current_frames().get(self._loop_thread_id)
            if frame is not None:
                stack = "".join(traceback.format_stack(frame))
        self.stalls += 1
        self._stall_counter.inc()
        # the stack's last line names the blocking call — the short form that
        # travels in snapshots/events; the full stack stays local (log + here)
        frame_tail = stack.strip().splitlines()[-1].strip() if stack else ""
        self.last_stall = {
            "time": round(wall_time(), 3),
            "loop": self.name,
            "blocked_s_at_capture": round(blocked_for, 3),
            "threshold_s": self.stall_threshold,
            "frame": frame_tail[:200],
            "stack": stack,
        }
        logger.warning(
            f"event loop {self.name!r} stalled: heartbeat missing for "
            f"{blocked_for:.2f}s (threshold {self.stall_threshold}s); loop thread stack:\n{stack}"
        )
        if self._loop_thread_id is not None:
            span = thread_current_span(self._loop_thread_id)
            if span is not None and span.end is None:
                span.add_event(
                    "event_loop.stall",
                    loop=self.name,
                    blocked_s=round(blocked_for, 3),
                    frame=frame_tail[:200],
                )

    def _sample_executors(self) -> None:
        try:
            for label, depth in _executor_queue_depths().items():
                self._depth_gauge.set(depth, executor=label)
        except Exception as e:  # pragma: no cover - private-attr peeking may drift
            logger.debug(f"executor depth sampling failed: {e!r}")

    def shutdown(self, timeout: float = 2.0) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout)


# ---------------------------------------------------------------- process-wide

_WATCHDOGS: Dict[int, EventLoopWatchdog] = {}
_watchdogs_lock = threading.Lock()


def ensure_watchdog(
    loop: Optional[asyncio.AbstractEventLoop] = None, name: str = "hmtpu-loop"
) -> Optional[EventLoopWatchdog]:
    """Start (or return) the watchdog for ``loop`` (default: the running loop).
    Idempotent per loop object — the averager, DHT and MoE server all share one
    loop and one watchdog. Returns None when disabled (``HIVEMIND_WATCHDOG=0``)
    or no loop is available."""
    if not enabled:
        return None
    if loop is None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return None
    with _watchdogs_lock:
        existing = _WATCHDOGS.get(id(loop))
        if existing is not None and existing.is_alive and not loop.is_closed():
            return existing
        watchdog = EventLoopWatchdog(loop, name=name)
        _WATCHDOGS[id(loop)] = watchdog
        return watchdog


def active_watchdogs() -> List[EventLoopWatchdog]:
    with _watchdogs_lock:
        return [w for w in _WATCHDOGS.values() if w.is_alive]


def shutdown_all() -> None:
    """Stop every registered watchdog (test isolation; conftest calls this)."""
    with _watchdogs_lock:
        watchdogs = list(_WATCHDOGS.values())
        _WATCHDOGS.clear()
    for watchdog in watchdogs:
        watchdog.shutdown()


def watchdog_summary() -> Dict[str, Any]:
    """Rollup for BENCH artifacts and the dashboard: stall count, worst lag,
    and the loops being watched."""
    watchdogs = active_watchdogs()
    summary: Dict[str, Any] = {
        "loops": sorted({w.name for w in watchdogs}),
        "stalls": sum(w.stalls for w in watchdogs),
        "max_lag_s": round(max((w.max_lag for w in watchdogs), default=0.0), 6),
        "stall_threshold_s": max((w.stall_threshold for w in watchdogs), default=DEFAULT_STALL_THRESHOLD_S),
    }
    last = [w.last_stall for w in watchdogs if w.last_stall is not None]
    if last:
        newest = max(last, key=lambda s: s["time"])
        summary["last_stall"] = {k: v for k, v in newest.items() if k != "stack"}
    return summary
