"""Black-box flight recorder (ISSUE 17 tentpole): crash-durable telemetry
spools.

The in-memory observability stack (metrics, the span ring, the round/serving
ledgers) is live-only: a crash-killed peer takes its evidence with it, exactly
when attribution matters most. This module spools the same signals to disk as
they happen, so a post-mortem (``hivemind-blackbox``, ``hivemind-top
--from-spool``) can rebuild a dead peer's final round and name its last
in-flight span.

Spool format — bounded, segment-rotated, torn-tail tolerant:

- a spool is a directory of segments: ``spool-NNNNNNNN.seg`` (complete,
  published with the PR 6 atomic conventions: fsync → rename → fsync(dir))
  plus at most one ``spool-NNNNNNNN.open`` (the active segment, flushed per
  frame — a kill-9 loses at most the frame being written, which the reader
  truncates as a torn tail);
- each frame is ``>II`` (payload length, crc32) + a msgpack map
  ``{"t": wall_ts, "k": kind, "d": data}``. Kinds: ``header`` (first frame of
  every segment: peer, segment index, wall anchor + drift estimate, clock
  model), ``span`` (finished), ``span_start`` (open — the only way a victim's
  last operation reaches disk), ``ledger_round``, ``ledger_epoch``,
  ``serving``, ``metrics``, ``device`` (ISSUE 19: compile / recompile-storm /
  device-memory / leak / overlap events — device telemetry is process-scoped,
  so these frames bypass ``peer_filter`` and land in every co-resident box);
- retention is a segment-count cap: the oldest ``.seg`` is deleted when the
  cap is exceeded, so a spool is O(retention × segment_bytes) forever.

Feeding is listener-based — span start/finish hooks (tracing), record hooks
on the round/serving ledgers, and an optional metrics-snapshot thread — so
arming a :class:`BlackBox` costs the hot path one extra listener call (a
msgpack pack + buffered write, single-digit µs). ``peer_filter`` scopes a box
to one peer's frames when many peers share a process (tests, the chaos soak,
the sim). Under the sim's virtual clock (``set_telemetry_time_source``) all
frame timestamps are virtual and the segment header says so — per-peer spools
from one seeded scenario are bit-identical across same-seed runs.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

from hivemind_tpu.telemetry.registry import REGISTRY
from hivemind_tpu.telemetry.tracing import (
    Span,
    add_span_listener,
    add_span_start_listener,
    remove_span_listener,
    remove_span_start_listener,
    wall_anchor,
    wall_anchor_info,
    wall_time,
)
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)

_FRAME_HEADER = struct.Struct(">II")  # (payload length, crc32(payload))
# a frame length beyond this is garbage, not data (a torn length field would
# otherwise send the reader seeking gigabytes past the end)
_MAX_FRAME_BYTES = 16 * 1024 * 1024
SPOOL_VERSION = 1

FRAMES_WRITTEN = REGISTRY.counter(
    "hivemind_blackbox_frames_total",
    "telemetry frames appended to the black-box spool, by frame kind",
    ("kind",),
)
BYTES_WRITTEN = REGISTRY.counter(
    "hivemind_blackbox_bytes_total",
    "bytes appended to the black-box spool (frame headers included)",
)
ROTATIONS = REGISTRY.counter(
    "hivemind_blackbox_rotations_total",
    "spool segments rotated out (published as .seg) by the black-box writer",
)
READ_SKIPPED = REGISTRY.counter(
    "hivemind_blackbox_read_skipped_total",
    "unreadable spool frames skipped by the reader (torn tails, crc mismatches)",
    ("reason",),
)


# ------------------------------------------------------------------- writing


class SpoolWriter:
    """Append-only segment-rotated frame writer. Thread-safe: listeners fire
    from arbitrary threads, every append holds one lock around a pack + write
    + flush. Durability model: flush-per-frame keeps frames in the OS page
    cache (survives process kill-9), fsync happens at segment publication
    (rotation/close) per the PR 6 atomic-publication conventions."""

    def __init__(
        self,
        directory: os.PathLike,
        peer: Optional[str] = None,
        segment_bytes: int = 4 * 1024 * 1024,
        retention_segments: int = 8,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.peer = str(peer) if peer is not None else None
        self.segment_bytes = int(segment_bytes)
        self.retention_segments = int(retention_segments)
        self._lock = threading.Lock()
        self._file = None
        self._written = 0
        # a restarted peer must not clobber its pre-crash evidence: publish
        # any leftover .open from the previous incarnation, continue numbering
        self._segment = 0
        for stale in sorted(self.directory.glob("spool-*.open")):
            stale.rename(stale.with_suffix(".seg"))
        for seg in self.directory.glob("spool-*.seg"):
            try:
                self._segment = max(self._segment, int(seg.stem.split("-")[1]))
            except (IndexError, ValueError):
                continue
        self._open_segment()

    # lock held for everything below --------------------------------------

    def _segment_path(self, index: int, suffix: str) -> Path:
        return self.directory / f"spool-{index:08d}{suffix}"

    def _open_segment(self) -> None:
        self._segment += 1
        self._file = open(self._segment_path(self._segment, ".open"), "wb")
        self._written = 0
        self._append_locked(
            "header",
            {
                "version": SPOOL_VERSION,
                "peer": self.peer,
                "segment": self._segment,
                "created": round(wall_time(), 6),
                **wall_anchor_info(),
            },
        )

    def _append_locked(self, kind: str, data: Dict[str, Any]) -> None:
        payload = MSGPackSerializer.dumps({"t": round(wall_time(), 6), "k": kind, "d": data})
        self._file.write(_FRAME_HEADER.pack(len(payload), zlib.crc32(payload)))
        self._file.write(payload)
        self._file.flush()
        self._written += _FRAME_HEADER.size + len(payload)
        FRAMES_WRITTEN.inc(kind=kind)
        BYTES_WRITTEN.inc(_FRAME_HEADER.size + len(payload))

    def _publish_locked(self) -> None:
        """fsync → atomic rename .open → .seg → fsync(dir): after this the
        segment is complete-by-construction for any reader/merger."""
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        open_path = self._segment_path(self._segment, ".open")
        open_path.rename(self._segment_path(self._segment, ".seg"))
        dir_fd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self._file = None
        ROTATIONS.inc()

    def _enforce_retention_locked(self) -> None:
        segments = sorted(self.directory.glob("spool-*.seg"))
        for stale in segments[: max(0, len(segments) - self.retention_segments)]:
            stale.unlink(missing_ok=True)

    # public ----------------------------------------------------------------

    def append(self, kind: str, data: Dict[str, Any]) -> None:
        with self._lock:
            if self._file is None:
                return  # closed writer: late listener fire after disarm
            self._append_locked(kind, data)
            if self._written >= self.segment_bytes:
                self._publish_locked()
                self._enforce_retention_locked()
                self._open_segment()

    def close(self) -> None:
        with self._lock:
            if self._file is None:
                return
            self._publish_locked()
            self._enforce_retention_locked()


# ------------------------------------------------------------------- reading


def _iter_file_frames(path: Path, stats: Dict[str, int]) -> Iterator[Dict[str, Any]]:
    with open(path, "rb") as f:
        while True:
            header = f.read(_FRAME_HEADER.size)
            if not header:
                return
            if len(header) < _FRAME_HEADER.size:
                stats["torn_tail"] += 1
                READ_SKIPPED.inc(reason="torn-tail")
                return
            length, crc = _FRAME_HEADER.unpack(header)
            if length > _MAX_FRAME_BYTES:
                # a corrupt length field: nothing after it is frame-aligned
                stats["corrupt"] += 1
                READ_SKIPPED.inc(reason="bad-length")
                return
            payload = f.read(length)
            if len(payload) < length:
                stats["torn_tail"] += 1
                READ_SKIPPED.inc(reason="torn-tail")
                return
            if zlib.crc32(payload) != crc:
                stats["corrupt"] += 1
                READ_SKIPPED.inc(reason="crc")
                continue  # length was intact: the NEXT frame is still aligned
            try:
                frame = MSGPackSerializer.loads(payload)
            except Exception:
                stats["corrupt"] += 1
                READ_SKIPPED.inc(reason="decode")
                continue
            if isinstance(frame, dict) and "k" in frame:
                yield frame


def read_spool(directory: os.PathLike) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
    """All frames of one peer's spool in write order, plus reader stats
    ``{"frames", "segments", "torn_tail", "corrupt"}``. Torn tails (a crash
    mid-frame) are truncated silently-but-counted; frames with a bad crc are
    skipped individually; a corrupt length field ends that segment."""
    directory = Path(directory)
    stats = {"frames": 0, "segments": 0, "torn_tail": 0, "corrupt": 0}
    frames: List[Dict[str, Any]] = []
    paths = sorted(directory.glob("spool-*.seg")) + sorted(directory.glob("spool-*.open"))
    paths.sort(key=lambda p: int(p.stem.split("-")[1]))
    for path in paths:
        stats["segments"] += 1
        for frame in _iter_file_frames(path, stats):
            frames.append(frame)
            stats["frames"] += 1
    return frames, stats


# ------------------------------------------------------------------- feeding


def _span_data(span: Span) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "name": span.name,
        "trace": f"{span.trace_id:016x}",
        "span": f"{span.span_id:016x}",
        "start": round(span.start + wall_anchor(), 6),
    }
    if span.parent_id:
        out["parent"] = f"{span.parent_id:016x}"
    if span.end is not None:
        out["dur_s"] = round(span.duration, 6)
    if span.attributes:
        out["attrs"] = {
            k: v for k, v in span.attributes.items() if isinstance(v, (str, int, float, bool))
        }
    if span.events:
        anchor = wall_anchor()
        out["events"] = [
            [round(when + anchor, 6), name] for when, name, _attrs in span.events
        ]
    return out


class BlackBox:
    """One armed flight recorder: a :class:`SpoolWriter` subscribed to the
    span hooks and both ledgers, with an optional metrics-snapshot thread.

    ``peer_filter`` keeps only frames attributable to that peer (matched
    against the ``peer`` span attribute / record field) — the multi-peer-in-
    one-process harnesses (chaos soak, sim) arm one box per peer on a shared
    telemetry plane. ``metrics_interval=None`` disables the snapshot thread
    (the sim does: a wall-interval thread is non-deterministic by nature)."""

    def __init__(
        self,
        directory: os.PathLike,
        peer: Optional[str] = None,
        peer_filter: Optional[str] = None,
        segment_bytes: int = 4 * 1024 * 1024,
        retention_segments: int = 8,
        metrics_interval: Optional[float] = None,
        spool_span_starts: bool = True,
        ledger: Optional[Any] = None,
        serving_ledger: Optional[Any] = None,
    ):
        self.writer = SpoolWriter(
            directory,
            peer=peer if peer is not None else peer_filter,
            segment_bytes=segment_bytes,
            retention_segments=retention_segments,
        )
        self.peer_filter = str(peer_filter) if peer_filter is not None else None
        self._spool_span_starts = spool_span_starts
        self._closed = False
        self._stop = threading.Event()
        self._metrics_thread: Optional[threading.Thread] = None
        # default to the process-wide ledgers; the sim passes its own private
        # RoundLedger so per-peer spools see only deterministic virtual-time
        # records (imports deferred to dodge the telemetry import cycle)
        if ledger is None:
            from hivemind_tpu.telemetry.ledger import LEDGER as ledger
        if serving_ledger is None:
            from hivemind_tpu.telemetry.serving import SERVING_LEDGER as serving_ledger
        self._ledger = ledger
        self._serving_ledger = serving_ledger
        add_span_listener(self._on_span_finish)
        if spool_span_starts:
            add_span_start_listener(self._on_span_start)
        self._ledger.add_record_listener(self._on_ledger_record)
        self._serving_ledger.add_record_listener(self._on_serving_record)
        # device telemetry (ISSUE 19) is process-scoped (one jit cache, one
        # HBM pool), so device frames deliberately BYPASS peer_filter: every
        # co-resident box carries the compile/memory state a post-mortem needs
        from hivemind_tpu.telemetry.device import add_device_listener

        self._last_device_memory_frame = 0.0
        add_device_listener(self._on_device_record)
        if metrics_interval is not None:
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop,
                args=(float(metrics_interval),),
                name="hmtpu-blackbox-metrics",
                daemon=True,
            )
            self._metrics_thread.start()

    # ------------------------------------------------------------- listeners

    def _peer_of_span(self, span: Span) -> Optional[str]:
        if span.attributes is None:
            return None
        peer = span.attributes.get("peer")
        return str(peer) if peer is not None else None

    def _on_span_start(self, span: Span) -> None:
        if self.peer_filter is not None and self._peer_of_span(span) != self.peer_filter:
            return
        self.writer.append("span_start", _span_data(span))

    def _on_span_finish(self, span: Span) -> None:
        if self.peer_filter is not None and self._peer_of_span(span) != self.peer_filter:
            return
        self.writer.append("span", _span_data(span))

    def _on_ledger_record(self, kind: str, record: Dict[str, Any]) -> None:
        if self.peer_filter is not None and str(record.get("peer")) != self.peer_filter:
            return
        self.writer.append(f"ledger_{kind}", record)

    def _on_serving_record(self, _kind: str, record: Dict[str, Any]) -> None:
        if self.peer_filter is not None and str(record.get("peer")) != self.peer_filter:
            return
        self.writer.append("serving", record)

    def _on_device_record(self, kind: str, record: Dict[str, Any]) -> None:
        # memory samples arrive on every watchdog tick — throttle them so a
        # long-lived box doesn't rotate its whole retention on gauge chatter;
        # the rare kinds (compile/storm/leak/overlap) always spool
        if kind == "memory":
            now = time.monotonic()
            if now - self._last_device_memory_frame < 5.0:
                return
            self._last_device_memory_frame = now
        frame = dict(record)
        # overlap records carry their comm span's name under "kind" — keep it
        # as "span" so the frame's own kind discriminator survives the merge
        inner = frame.pop("kind", None)
        if inner is not None:
            frame["span"] = inner
        frame["kind"] = kind
        self.writer.append("device", frame)

    def _metrics_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.snapshot_metrics()

    def _unsubscribe(self) -> None:
        remove_span_listener(self._on_span_finish)
        if self._spool_span_starts:
            remove_span_start_listener(self._on_span_start)
        self._ledger.remove_record_listener(self._on_ledger_record)
        self._serving_ledger.remove_record_listener(self._on_serving_record)
        from hivemind_tpu.telemetry.device import remove_device_listener

        remove_device_listener(self._on_device_record)
        if self._metrics_thread is not None:
            self._metrics_thread.join(timeout=2.0)
            self._metrics_thread = None

    # --------------------------------------------------------------- public

    def snapshot_metrics(self) -> None:
        """Append one metrics snapshot frame (called periodically by the
        metrics thread; harnesses without the thread call it at checkpoints)."""
        try:
            self.writer.append("metrics", {"metrics": REGISTRY.snapshot()})
        except Exception as e:  # pragma: no cover - spooling must stay harmless
            logger.debug(f"blackbox metrics snapshot failed: {e!r}")

    def close(self) -> None:
        """Unsubscribe, stop the metrics thread, publish the active segment."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._unsubscribe()
        self.writer.close()

    def abandon(self) -> None:
        """Kill-9 semantics for harnesses: unsubscribe WITHOUT publishing the
        active segment — the .open file stays exactly as the dead peer left
        it, torn tail and all. What a real crash leaves behind."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._unsubscribe()
        with self.writer._lock:
            if self.writer._file is not None:
                self.writer._file.flush()
                self.writer._file.close()
                self.writer._file = None


# ------------------------------------------------------------ process global

# the one CLI-armed box (run_server/run_dht/Optimizer --blackbox_dir); tests
# and the soak build private BlackBox instances instead
_ACTIVE: Optional[BlackBox] = None
_ACTIVE_LOCK = threading.Lock()


def arm_blackbox(
    directory: os.PathLike,
    peer: Optional[str] = None,
    metrics_interval: Optional[float] = 15.0,
    **kwargs: Any,
) -> BlackBox:
    """Arm (or re-arm) the process-wide black box writing under ``directory``.
    Idempotent per directory: re-arming the same path returns the existing
    box, so run_server + Optimizer can both pass ``--blackbox_dir`` without
    double-spooling every span."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None and not _ACTIVE._closed:
            if _ACTIVE.writer.directory == Path(directory):
                return _ACTIVE
            _ACTIVE.close()
        _ACTIVE = BlackBox(directory, peer=peer, metrics_interval=metrics_interval, **kwargs)
        return _ACTIVE


def disarm_blackbox() -> None:
    """Close and forget the process-wide box (conftest resets through here)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.close()
            _ACTIVE = None


def active_blackbox() -> Optional[BlackBox]:
    return _ACTIVE


__all__ = [
    "BlackBox",
    "SpoolWriter",
    "read_spool",
    "arm_blackbox",
    "disarm_blackbox",
    "active_blackbox",
    "SPOOL_VERSION",
]
