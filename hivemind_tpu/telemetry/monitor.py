"""Swarm-wide telemetry: publish each peer's snapshot to the DHT, aggregate all
peers' snapshots into one view.

Per-peer side — :class:`TelemetryPublisher`: a daemon thread stores a compact
snapshot of the process-wide registry (plus optional caller extras, e.g. a
``StepProfiler.summary()``) under ``{key}`` / subkey ``peer_id`` on a timer, so
one DHT read answers "where did this round's time go" for the whole swarm.

Monitor side — :func:`fetch_swarm_telemetry` + :func:`aggregate_swarm_view` and
the :class:`SwarmMonitor` convenience wrapper, which can stream the aggregate
into a :class:`~hivemind_tpu.utils.profiling.JsonlMetricsSink` (the offline
wandb-style sink the flagship recipe's monitor already uses).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from hivemind_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

DEFAULT_TELEMETRY_KEY = "hivemind_telemetry"
# the default TelemetryPublisher cadence, and how many missed publishes make a
# peer STALE — shared by SwarmMonitor.render_report and hivemind-top so the
# two renderers can never disagree about staleness
DEFAULT_PUBLISH_INTERVAL = 30.0
STALE_AFTER_FACTOR = 3.0
# a snapshot must stay a small DHT record: drop histogram series first, then
# whole metrics, before giving up on the publish
_MAX_SNAPSHOT_BYTES = 48 * 1024


def build_peer_snapshot(
    registry: MetricsRegistry = REGISTRY, extras: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """One peer's compact telemetry record (msgpack/JSON-able). Besides the
    metric snapshot it carries the peer's *health* — tripped breaker boards and
    the last few slow spans — plus recent span summaries, so the swarm monitor
    can show which peers are degraded and reconstruct a cross-peer timeline
    without scraping every peer's ``/trace`` endpoint."""
    # lazy import: telemetry must stay importable before resilience (which
    # itself imports this package for its metrics)
    from hivemind_tpu.resilience import all_board_states
    from hivemind_tpu.telemetry.ledger import LEDGER
    from hivemind_tpu.telemetry.tracing import RECORDER
    from hivemind_tpu.telemetry.watchdog import watchdog_summary

    snapshot: Dict[str, Any] = {
        "time": get_dht_time(),
        "metrics": registry.snapshot(),
    }
    breakers = all_board_states()
    if breakers:
        snapshot["breakers"] = breakers
    slow = RECORDER.slow_spans()
    if slow:
        snapshot["slow_spans"] = [span.summary() for span in slow[-5:]]
    recent = RECORDER.summaries(limit=30)
    if recent:
        snapshot["recent_spans"] = recent
    # per-round attribution (ISSUE 8): recent records + straggler scores +
    # epoch transitions ride the snapshot so ONE DHT read answers "which peer
    # is taxing the swarm" without scraping anyone's /ledger
    ledger = LEDGER.snapshot()
    if ledger:
        snapshot["ledger"] = ledger
    # serving attribution (ISSUE 9): per-expert serving stats + saturation on
    # the server side, expert scorecards on the client side — hivemind-top's
    # --serving board renders entirely from this section
    from hivemind_tpu.telemetry.serving import SCORECARDS, SERVING_LEDGER

    serving = SERVING_LEDGER.snapshot()
    scorecards = SCORECARDS.snapshot()
    if scorecards:
        serving["scorecards"] = scorecards
    if serving:
        snapshot["serving"] = serving
    watchdog = watchdog_summary()
    if watchdog.get("loops"):
        snapshot["watchdog"] = watchdog
    # device-side observability (ISSUE 19): compile counts / HBM / transfer /
    # overlap ride the snapshot so hivemind-top's device board renders from ONE
    # DHT read; empty dict when the process never touched an accelerator
    from hivemind_tpu.telemetry.device import device_snapshot

    device = device_snapshot()
    if device:
        snapshot["device"] = device
    if extras:
        snapshot.update(extras)
    return snapshot


def _shrink_to_fit(snapshot: Dict[str, Any], max_bytes: int = _MAX_SNAPSHOT_BYTES) -> Dict[str, Any]:
    from hivemind_tpu.utils.serializer import MSGPackSerializer

    if len(MSGPackSerializer.dumps(snapshot)) <= max_bytes:
        return snapshot
    # span summaries are nice-to-have context; the health + counter core wins.
    # Ledger records shrink before they drop: straggler scores are the most
    # load-bearing part of the attribution layer, so they go last
    ledger = snapshot.get("ledger")
    if isinstance(ledger, dict) and "records" in ledger:
        shrunk_ledger = {k: v for k, v in ledger.items() if k != "records"}
        candidate = {**snapshot, "ledger": shrunk_ledger, "truncated": True}
        if len(MSGPackSerializer.dumps(candidate)) <= max_bytes:
            return candidate
        snapshot = candidate
    # serving records shrink before they drop: the per-expert stats + totals
    # are the board's load-bearing part, the slowest exemplars are context
    serving = snapshot.get("serving")
    if isinstance(serving, dict) and ("slowest" in serving or "clients" in serving):
        shrunk_serving = {k: v for k, v in serving.items() if k not in ("slowest", "clients")}
        candidate = {**snapshot, "serving": shrunk_serving, "truncated": True}
        if len(MSGPackSerializer.dumps(candidate)) <= max_bytes:
            return candidate
        snapshot = candidate
    # device section shrinks before it drops: headline compile/HBM/overlap
    # numbers survive as a compact dict, per-site/per-device detail goes
    device = snapshot.get("device")
    if isinstance(device, dict) and device:
        from hivemind_tpu.telemetry.device import compact_device_snapshot

        compacted = compact_device_snapshot(device)
        if compacted != device:
            candidate = {**snapshot, "device": compacted, "truncated": True}
            if len(MSGPackSerializer.dumps(candidate)) <= max_bytes:
                return candidate
            snapshot = candidate
    # span summaries are nice-to-have context: they go first
    for optional_key in ("recent_spans", "slow_spans"):
        if optional_key in snapshot:
            snapshot = {k: v for k, v in snapshot.items() if k != optional_key}
            snapshot["truncated"] = True
            if len(MSGPackSerializer.dumps(snapshot)) <= max_bytes:
                return snapshot
    metrics = dict(snapshot.get("metrics", {}))
    # per-label series are the bulk; the swarm view only ever aggregates a
    # family's totals, so COMPACT the largest families to one summed series
    # BEFORE dropping the attribution sections — a label explosion must cost
    # label detail (recoverable swarm-wide), not the ledger/serving records
    # (irreplaceable; ISSUE 9 made this ordering explicit)
    by_size = sorted(metrics, key=lambda name: -len(str(metrics[name])))
    for name in by_size:
        metrics[name] = _compact_family(metrics[name])
        shrunk = {**snapshot, "metrics": metrics, "truncated": True}
        if len(MSGPackSerializer.dumps(shrunk)) <= max_bytes:
            return shrunk
    snapshot = {**snapshot, "metrics": metrics}
    # the (already compacted) device section drops before serving/ledger: its
    # headline numbers are re-derivable from metrics, attribution records aren't
    for optional_key in ("device", "serving", "ledger"):
        if optional_key in snapshot:
            snapshot = {k: v for k, v in snapshot.items() if k != optional_key}
            snapshot["truncated"] = True
            if len(MSGPackSerializer.dumps(snapshot)) <= max_bytes:
                return snapshot
    # still too big (pathological family count): drop largest families outright
    for name in sorted(metrics, key=lambda name: -len(str(metrics[name]))):
        metrics.pop(name)
        shrunk = {**snapshot, "metrics": metrics, "truncated": True}
        if len(MSGPackSerializer.dumps(shrunk)) <= max_bytes:
            return shrunk
    return {**snapshot, "metrics": {}, "truncated": True}


def _compact_family(family: Dict[str, Any]) -> Dict[str, Any]:
    """Collapse a family's per-label series into one aggregate series (same shape
    the aggregator consumes, so totals survive label-free)."""
    series = family.get("series") or {}
    if len(series) <= 1:
        return family
    if family.get("type") == "histogram":
        merged: Dict[str, float] = {"count": 0.0, "sum": 0.0}
        for value in series.values():
            if isinstance(value, dict):
                merged["count"] += float(value.get("count", 0))
                merged["sum"] = round(merged["sum"] + float(value.get("sum", 0.0)), 6)
        return {**family, "series": {"": merged}, "compacted": True}
    total = 0.0
    for value in series.values():
        if not isinstance(value, dict):
            total += float(value)
    return {**family, "series": {"": round(total, 6)}, "compacted": True}


class TelemetryPublisher:
    """Periodically store this peer's snapshot in the DHT (one subkey per peer).

    :param dht: the peer's :class:`~hivemind_tpu.dht.DHT`
    :param key: DHT key to publish under; swarm members must agree on it
        (convention: ``f"{run_id}_telemetry"`` for training runs)
    :param interval: seconds between publishes
    :param extras_fn: zero-arg callable merged into every snapshot (e.g.
        ``lambda: {"step_profiler": profiler.summary()}``)
    """

    def __init__(
        self,
        dht,
        key: str = DEFAULT_TELEMETRY_KEY,
        *,
        interval: float = 30.0,
        registry: MetricsRegistry = REGISTRY,
        extras_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        start: bool = True,
    ):
        self.dht = dht
        self.key = key
        self.interval = interval
        self.registry = registry
        self.extras_fn = extras_fn
        self.last_published: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="telemetry-publisher", daemon=True
        )
        self._thread.start()

    def publish_once(self) -> bool:
        """Build + store one snapshot now (also used by the timer thread)."""
        extras: Dict[str, Any] = {}
        if self.extras_fn is not None:
            try:
                extras = dict(self.extras_fn())
            except Exception as e:
                logger.debug(f"telemetry extras_fn failed: {e!r}")
        extras.setdefault("peer_id", str(self.dht.peer_id))
        snapshot = _shrink_to_fit(build_peer_snapshot(self.registry, extras))
        try:
            ok = self.dht.store(
                self.key,
                value=snapshot,
                subkey=self.dht.peer_id.to_bytes(),
                expiration_time=get_dht_time() + max(self.interval * 3, 60.0),
            )
        except Exception as e:
            logger.debug(f"telemetry publish failed: {e!r}")
            return False
        if ok:
            self.last_published = snapshot
        return bool(ok)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.publish_once()

    def shutdown(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)


# ------------------------------------------------------------------ monitor side


def fetch_swarm_telemetry(dht, key: str = DEFAULT_TELEMETRY_KEY) -> Dict[str, Dict[str, Any]]:
    """All peers' live snapshots: ``{peer_id_str: snapshot_dict}``."""
    response = dht.get(key, latest=True)
    records: Dict[str, Dict[str, Any]] = {}
    if response is None or not isinstance(response.value, dict):
        return records
    for subkey, entry in response.value.items():
        snapshot = entry.value if hasattr(entry, "value") else entry
        if not isinstance(snapshot, dict):
            continue
        peer = snapshot.get("peer_id")
        if not isinstance(peer, str):
            peer = subkey.hex() if isinstance(subkey, bytes) else str(subkey)
        records[peer] = snapshot
    return records


def aggregate_swarm_view(records: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Collapse per-peer snapshots into the swarm-wide view: counter/gauge totals
    per metric (counters/histogram-counts sum; gauges also carry min/max so a
    straggler epoch is visible), plus a per-peer health summary."""
    totals: Dict[str, Dict[str, Any]] = {}
    peers: Dict[str, Dict[str, Any]] = {}
    now = get_dht_time()
    for peer, snapshot in records.items():
        # snapshots are DHT-supplied: a malformed (buggy/version-skewed/hostile)
        # peer contributes an error marker, never a crashed aggregation
        try:
            age = round(max(now - float(snapshot.get("time", now)), 0.0), 1)
        except (TypeError, ValueError):
            age = -1.0  # unparseable timestamp
        peers[peer] = {
            "age_s": age,
            # recent_spans feed render_timeline, not the per-peer health line
            **{k: v for k, v in snapshot.items() if k not in ("metrics", "time", "peer_id", "recent_spans")},
        }
        metrics = snapshot.get("metrics")
        if not isinstance(metrics, dict):
            if metrics is not None:
                peers[peer]["malformed"] = True
            continue
        for name, family in metrics.items():
            if not isinstance(family, dict):
                continue
            ftype = family.get("type", "untyped")
            agg = totals.setdefault(name, {"type": ftype, "total": 0.0, "peers": 0})
            agg["peers"] += 1
            series = family.get("series")
            for _label, value in (series.items() if isinstance(series, dict) else ()):
                try:
                    if isinstance(value, dict):  # histogram: count/sum
                        agg["total"] += float(value.get("count", 0))
                        agg["sum"] = round(agg.get("sum", 0.0) + float(value.get("sum", 0.0)), 6)
                    else:
                        agg["total"] += float(value)
                        if ftype == "gauge":
                            agg["min"] = min(agg.get("min", float(value)), float(value))
                            agg["max"] = max(agg.get("max", float(value)), float(value))
                except (TypeError, ValueError):
                    peers[peer]["malformed"] = True
    for agg in totals.values():
        agg["total"] = round(agg["total"], 6)
    return {"num_peers": len(records), "metrics": totals, "peers": peers}


class SwarmMonitor:
    """Fetch + aggregate on demand, optionally appending each view to a
    :class:`~hivemind_tpu.utils.profiling.JsonlMetricsSink`."""

    # the swarm's agreed TelemetryPublisher cadence: a peer whose snapshot age
    # exceeds STALE_AFTER_FACTOR x this is flagged STALE (it stopped publishing
    # — crashed, wedged, or partitioned — even if its last numbers look
    # healthy). A class default so render-only monitors (tests build them
    # without __init__) work.
    publish_interval: float = DEFAULT_PUBLISH_INTERVAL

    def __init__(
        self,
        dht,
        key: str = DEFAULT_TELEMETRY_KEY,
        sink=None,
        publish_interval: float = DEFAULT_PUBLISH_INTERVAL,
    ):
        self.dht = dht
        self.key = key
        self.sink = sink
        self.publish_interval = publish_interval

    def poll(self) -> Dict[str, Any]:
        view = aggregate_swarm_view(fetch_swarm_telemetry(self.dht, self.key))
        view["time"] = round(time.time(), 3)
        if self.sink is not None:
            try:
                self.sink.log({"swarm_telemetry": view})
            except Exception as e:
                logger.debug(f"telemetry sink write failed: {e!r}")
        return view

    def render_report(self, view: Optional[Dict[str, Any]] = None) -> str:
        """Human-readable one-screen summary for log lines / CLIs. Peers whose
        snapshot carries tripped breakers or slow spans are flagged DEGRADED —
        the "which peer is the problem" line, not just its counters."""
        view = view if view is not None else self.poll()
        lines = [f"swarm telemetry: {view['num_peers']} peers"]
        for name, agg in sorted(view.get("metrics", {}).items()):
            extra = ""
            if "sum" in agg:
                extra = f", sum={agg['sum']:.3f}s"
            if "min" in agg and agg.get("min") != agg.get("max"):
                extra += f", min={agg['min']}, max={agg['max']}"
            lines.append(f"  {name} [{agg['type']}] total={agg['total']}{extra} ({agg['peers']} peers)")
        # recovery-path emergencies (docs/state_recovery.md): either of these
        # growing means the swarm is quietly diverging — a peer claimed epochs
        # it never trained, or adopted state no digest ever blessed
        for name, what in (
            ("hivemind_optimizer_epoch_adopted_without_state_total", "epoch(s) adopted WITHOUT state"),
            ("hivemind_state_sync_unverified_adoptions_total", "unverified (manifest-less) state adoption(s)"),
        ):
            agg = view.get("metrics", {}).get(name)
            if agg and agg.get("total"):
                lines.append(f"  RECOVERY ALERT: {agg['total']:g} {what} across the swarm")
        stale_after = STALE_AFTER_FACTOR * self.publish_interval
        for peer, health in sorted(view.get("peers", {}).items()):
            breakers = health.get("breakers") or {}
            slow = health.get("slow_spans") or []
            ledger = health.get("ledger") or {}
            watchdog = health.get("watchdog") or {}
            marker = " DEGRADED" if breakers or slow else ""
            if float(health.get("age_s", 0.0)) > stale_after:
                # stopped publishing: crashed, wedged, or partitioned — its
                # numbers below are a snapshot of the PAST, not the present
                marker = " STALE" + marker
            printable = {
                k: v for k, v in health.items() if k not in ("ledger", "watchdog", "serving")
            }
            lines.append(f"  peer {peer[:16]}…:{marker} {printable}")
            for board, state in sorted(breakers.items()):
                lines.append(f"    breaker {board}: {state.get('num_tripped', 0)} tripped {state.get('tripped')}")
            for span in slow:
                lines.append(
                    f"    slow span {span.get('name')}: {span.get('dur_ms')}ms events={span.get('events', [])}"
                )
            if watchdog.get("stalls"):
                lines.append(
                    f"    WATCHDOG: {watchdog['stalls']} event-loop stall(s), "
                    f"max lag {watchdog.get('max_lag_s', 0.0)}s — this peer's loop blocked; "
                    f"it is NOT a network straggler"
                )
            for victim, score in list((ledger.get("stragglers") or {}).items())[:3]:
                lines.append(
                    f"    straggler seen: {str(victim)[:16]} slowest in "
                    f"{score.get('rounds_slowest', 0)} round(s), +{score.get('excess_s', 0.0)}s excess"
                )
        serving_board = self.render_serving_board(view)
        if serving_board:
            lines.append(serving_board)
        timeline = self.render_epoch_timeline(view)
        if timeline:
            lines.append(timeline)
        return "\n".join(lines)

    def render_serving_board(self, view: Optional[Dict[str, Any]] = None) -> str:
        """The serving board (ISSUE 9): per-expert request counts / p95 / sheds
        merged across every peer's serving section, the saturation gauges
        (queue depth/age, session occupancy, shed totals), degraded client-side
        scorecards, and the slowest-request exemplars — which expert on which
        peer is eating serving time, as one screen. Parsing is shared with
        ``hivemind-top --serving`` (telemetry.serving.collect_swarm_serving)."""
        from hivemind_tpu.telemetry.serving import (
            collect_swarm_serving,
            format_saturation_parts,
            format_scorecard_line,
            format_slowest_line,
        )

        view = view if view is not None else self.poll()
        data = collect_swarm_serving(view.get("peers") or {})
        if not any(data[key] for key in ("experts", "saturation", "degraded_scorecards", "slowest", "malformed")):
            return ""
        lines = ["  serving board (expert @ peer / requests / p95 / sheds):"]
        for peer, uid, stats in data["experts"][:16]:
            p95 = stats["p95_s"]
            lines.append(
                f"    {uid[:24]:<24} @ {peer[:12]:<12} {stats['requests']:>6.0f} req "
                f"p95={f'{p95 * 1e3:.1f}ms' if p95 is not None else '-':>9}"
                + (f"  SHED x{stats['sheds']}" if stats["sheds"] else "")
            )
        for peer in data["malformed"]:
            lines.append(f"    {peer[:16]:<16} <malformed serving section>")
        if data["saturation"]:
            lines.append("  serving saturation:")
            lines.extend(
                f"    {peer[:16]:<16} {', '.join(format_saturation_parts(entry))}"
                for peer, entry in data["saturation"]
            )
        if data["degraded_scorecards"]:
            lines.append("  degraded expert scorecards (client view):")
            lines.extend(
                "    " + format_scorecard_line(peer, uid, card)
                for peer, uid, card in data["degraded_scorecards"][:8]
            )
        if data["slowest"]:
            lines.append("  slowest requests:")
            lines.extend(
                "    " + format_slowest_line(total_s, peer, record)
                for total_s, peer, record in data["slowest"][:5]
            )
        return "\n".join(lines)

    def render_epoch_timeline(self, view: Optional[Dict[str, Any]] = None) -> str:
        """Per-epoch swarm timeline with straggler attribution (ISSUE 8): every
        peer's ledger epoch records, grouped by epoch — one line per peer per
        epoch showing rounds run, averaging seconds spent, and which partner was
        slowest. This is "where did epoch N's wall time go" as one screen."""
        view = view if view is not None else self.poll()
        by_epoch: Dict[int, list] = {}
        for peer, health in (view.get("peers") or {}).items():
            for entry in (health.get("ledger") or {}).get("epochs") or ():
                # snapshots are DHT-supplied: one malformed (buggy/stale/hostile)
                # peer must not crash every operator's report
                if isinstance(entry, dict) and isinstance(entry.get("epoch"), (int, float)):
                    by_epoch.setdefault(int(entry["epoch"]), []).append((peer, entry))
        if not by_epoch:
            return ""
        lines = ["  epoch timeline (rounds / averaging seconds / slowest partner):"]
        for epoch in sorted(by_epoch)[-8:]:
            lines.append(f"    epoch {epoch}:")
            for peer, entry in sorted(by_epoch[epoch], key=lambda kv: kv[0]):
                try:
                    rounds = int(entry.get("rounds", 0) or 0)
                    round_s = float(entry.get("round_s", 0.0) or 0.0)
                except (TypeError, ValueError):
                    lines.append(f"      {str(peer)[:16]:<16} <malformed ledger entry>")
                    continue
                straggler = entry.get("straggler")
                attribution = f" slowest={str(straggler)[:16]}" if straggler else ""
                averaged = entry.get("averaged_ok")
                outcome = "" if averaged is None else (" ok" if averaged else " DEGRADED_TO_LOCAL")
                lines.append(
                    f"      {str(peer)[:16]:<16} {rounds} round(s) "
                    f"{round_s:.3f}s{attribution}{outcome}"
                )
        return "\n".join(lines)

    def render_timeline(self, records: Optional[Dict[str, Dict[str, Any]]] = None) -> str:
        """Cross-peer timeline: pull every peer's recent span summaries from the
        DHT, group them by trace, and print each trace's spans in start order —
        one line per span, labeled with the owning peer. This is how "why was
        THIS round slow" reads without collecting per-peer /trace dumps."""
        records = records if records is not None else fetch_swarm_telemetry(self.dht, self.key)
        by_trace: Dict[str, list] = {}
        for peer, snapshot in records.items():
            for span in snapshot.get("recent_spans") or ():
                if isinstance(span, dict) and span.get("trace"):
                    by_trace.setdefault(span["trace"], []).append((peer, span))
        lines = [f"swarm timeline: {len(by_trace)} traces from {len(records)} peers"]
        # most recently started traces first; spans within a trace in time order
        def trace_start(spans):
            return min(float(s.get("start", 0.0)) for _p, s in spans)

        for trace_id, spans in sorted(by_trace.items(), key=lambda kv: -trace_start(kv[1])):
            spans.sort(key=lambda item: float(item[1].get("start", 0.0)))
            origin = float(spans[0][1].get("start", 0.0))
            lines.append(f"trace {trace_id}:")
            for peer, span in spans:
                offset_ms = (float(span.get("start", 0.0)) - origin) * 1e3
                events = f" !{','.join(span['events'])}" if span.get("events") else ""
                lines.append(
                    f"  +{offset_ms:8.1f}ms {peer[:12]:<12} {span.get('name')}"
                    f" ({span.get('dur_ms')}ms){events}"
                )
        return "\n".join(lines)
