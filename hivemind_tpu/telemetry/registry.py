"""The cross-layer metrics registry: Counter / Gauge / Histogram with labels.

Zero dependencies, thread-safe, always-on. Design constraints (ISSUE 2):

- **Cheap on the hot path.** ``metric.labels(...)`` returns a child handle that
  callers cache; a cached child's ``inc``/``set``/``observe`` is one lock + one
  float op. Creating a child is a dict lookup under the metric lock. No string
  formatting happens until scrape/snapshot time.
- **Always-on.** There is no enabled flag to check: recording into the registry
  IS the disabled-exporter path, and it must stay within noise on
  ``benchmark_slice_step_overhead.py`` (acceptance criterion). Rendering cost is
  paid only by scrapers.
- **Prometheus-compatible.** Histograms keep cumulative ``le`` buckets plus
  ``_sum``/``_count``; the exporter (telemetry/exporter.py) renders the standard
  text exposition format.

The process-wide :data:`REGISTRY` is what instrumented modules use; tests build
private ``MetricsRegistry`` instances.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_LabelKey = Tuple[str, ...]

# latency-flavored default buckets (seconds): RPC and phase timings span ~100us..60s
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_VALID_METRIC_TYPES = ("counter", "gauge", "histogram")


class _Child:
    """One labeled time series of a Counter or Gauge."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)


class _HistogramChild:
    """One labeled histogram series: cumulative buckets + sum + count."""

    __slots__ = ("_lock", "_bounds", "_buckets", "_sum", "_count")

    def __init__(self, lock: threading.Lock, bounds: Sequence[float]):
        self._lock = lock
        self._bounds = bounds
        self._buckets = [0] * len(bounds)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            # linear scan: bucket lists are short (~14) and values skew small,
            # so this beats bisect's call overhead on the common case
            for i, bound in enumerate(self._bounds):
                if value <= bound:
                    self._buckets[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._buckets), self._sum, self._count


class Metric:
    """Base for one named metric family (all label combinations)."""

    metric_type = "untyped"

    def __init__(self, name: str, documentation: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.documentation = documentation
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[_LabelKey, object] = {}

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *labelvalues, **labelkwargs):
        """Get-or-create the child for one label combination. Accepts positional
        values (in declaration order) or keywords; callers on hot paths should
        cache the returned child."""
        if labelkwargs:
            assert not labelvalues, "pass labels positionally or by keyword, not both"
            labelvalues = tuple(labelkwargs[name] for name in self.labelnames)
        key = tuple(str(v) for v in labelvalues)
        assert len(key) == len(self.labelnames), (
            f"{self.name} expects labels {self.labelnames}, got {key}"
        )
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def _no_labels(self):
        assert not self.labelnames, f"{self.name} requires labels {self.labelnames}"
        return self.labels()

    def remove(self, *labelvalues, **labelkwargs) -> None:
        """Drop one label combination's series. For metrics whose label values
        are swarm-supplied (peer ids), callers MUST bound cardinality by
        evicting stale series — the registry itself keeps everything forever."""
        if labelkwargs:
            assert not labelvalues, "pass labels positionally or by keyword, not both"
            labelvalues = tuple(labelkwargs[name] for name in self.labelnames)
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            self._children.pop(key, None)

    def series(self) -> Iterable[Tuple[_LabelKey, object]]:
        with self._lock:
            return list(self._children.items())


class Counter(Metric):
    """Monotonically increasing value (rendered with a ``_total`` suffix)."""

    metric_type = "counter"

    def _make_child(self) -> _Child:
        return _Child(threading.Lock())

    def inc(self, amount: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels else self._no_labels()).inc(amount)

    def value(self, **labels) -> float:
        return (self.labels(**labels) if labels else self._no_labels()).value


class Gauge(Metric):
    """A value that can go up and down."""

    metric_type = "gauge"

    def _make_child(self) -> _Child:
        return _Child(threading.Lock())

    def set(self, value: float, **labels) -> None:
        (self.labels(**labels) if labels else self._no_labels()).set(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels else self._no_labels()).inc(amount)

    def dec(self, amount: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels else self._no_labels()).dec(amount)

    def value(self, **labels) -> float:
        return (self.labels(**labels) if labels else self._no_labels()).value


class Histogram(Metric):
    """Distribution with cumulative ``le`` buckets (Prometheus semantics)."""

    metric_type = "histogram"

    def __init__(
        self,
        name: str,
        documentation: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, documentation, labelnames)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(threading.Lock(), self.buckets)

    def observe(self, value: float, **labels) -> None:
        (self.labels(**labels) if labels else self._no_labels()).observe(value)

    def time(self, **labels):
        """Context manager observing the block's wall duration in seconds."""
        return _Timer(self.labels(**labels) if labels else self._no_labels())


class _Timer:
    __slots__ = ("_child", "_start")

    def __init__(self, child: _HistogramChild):
        self._child = child

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._child.observe(time.perf_counter() - self._start)
        return False


class MetricsRegistry:
    """Thread-safe get-or-create home for metrics. One process-wide instance
    (:data:`REGISTRY`) serves all instrumented layers; components may also carry
    a private registry (tests)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, documentation: str, labelnames: Sequence[str], **kwargs) -> Metric:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, documentation, labelnames, **kwargs)
                self._metrics[name] = metric
                return metric
        assert isinstance(metric, cls), (
            f"metric {name!r} is already registered as a {metric.metric_type}"
        )
        assert metric.labelnames == tuple(labelnames), (
            f"metric {name!r} is already registered with labels {metric.labelnames}"
        )
        return metric

    def counter(self, name: str, documentation: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, documentation, labelnames)

    def gauge(self, name: str, documentation: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, documentation, labelnames)

    def histogram(
        self,
        name: str,
        documentation: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, documentation, labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def snapshot(self) -> Dict[str, dict]:
        """Compact JSON-able view: per metric, per label-tuple value (histograms:
        count/sum only — the swarm view aggregates totals, not shapes). This is
        what the DHT publisher ships and what bench.py embeds in artifacts."""
        out: Dict[str, dict] = {}
        for metric in self.collect():
            series: Dict[str, object] = {}
            for key, child in metric.series():
                label = ",".join(f"{n}={v}" for n, v in zip(metric.labelnames, key)) or "_"
                if metric.metric_type == "histogram":
                    _buckets, total, count = child.snapshot()  # type: ignore[union-attr]
                    series[label] = {"count": count, "sum": round(total, 6)}
                else:
                    series[label] = round(child.value, 6)  # type: ignore[union-attr]
            out[metric.name] = {"type": metric.metric_type, "series": series}
        return out


REGISTRY = MetricsRegistry()
