"""Swarm-wide telemetry (ISSUE 2): a zero-dependency, thread-safe metrics
registry with a Prometheus text exporter and DHT-published peer snapshots.

- :mod:`~hivemind_tpu.telemetry.registry` — Counter / Gauge / Histogram with
  labels; the process-wide :data:`REGISTRY` all layers record into.
- :mod:`~hivemind_tpu.telemetry.exporter` — ``GET /metrics`` over stdlib HTTP
  (``--metrics-port`` in run_server.py / run_dht.py).
- :mod:`~hivemind_tpu.telemetry.monitor` — per-peer DHT snapshot publisher and
  the swarm-wide aggregation view.

See docs/observability.md for the metric catalog.
"""

from hivemind_tpu.telemetry.exporter import MetricsExporter, render_prometheus
from hivemind_tpu.telemetry.monitor import (
    DEFAULT_TELEMETRY_KEY,
    SwarmMonitor,
    TelemetryPublisher,
    aggregate_swarm_view,
    build_peer_snapshot,
    fetch_swarm_telemetry,
)
from hivemind_tpu.telemetry.registry import (
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

__all__ = [
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "DEFAULT_TELEMETRY_KEY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsExporter",
    "render_prometheus",
    "TelemetryPublisher",
    "SwarmMonitor",
    "build_peer_snapshot",
    "fetch_swarm_telemetry",
    "aggregate_swarm_view",
]
