"""Swarm-wide telemetry (ISSUE 2 + 4): a zero-dependency, thread-safe metrics
registry with a Prometheus text exporter, DHT-published peer snapshots, and
distributed tracing with a per-process flight recorder.

- :mod:`~hivemind_tpu.telemetry.registry` — Counter / Gauge / Histogram with
  labels; the process-wide :data:`REGISTRY` all layers record into.
- :mod:`~hivemind_tpu.telemetry.tracing` — cross-peer spans, the
  :data:`~hivemind_tpu.telemetry.tracing.RECORDER` ring buffer, and
  Chrome-trace/Perfetto export.
- :mod:`~hivemind_tpu.telemetry.exporter` — ``GET /metrics`` + ``GET /trace``
  over stdlib HTTP (``--metrics-port`` in run_server.py / run_dht.py).
- :mod:`~hivemind_tpu.telemetry.monitor` — per-peer DHT snapshot publisher and
  the swarm-wide aggregation view (now incl. breaker states + slow spans).
- :mod:`~hivemind_tpu.telemetry.ledger` — the per-round attribution ledger
  (ISSUE 8): one structured record per averaging round / optimizer epoch with
  per-peer straggler scores, served at ``GET /ledger``.
- :mod:`~hivemind_tpu.telemetry.watchdog` — event-loop lag probe with stall
  stack capture and executor-queue-depth gauges.
- :mod:`~hivemind_tpu.telemetry.serving` — the serving-path attribution layer
  (ISSUE 9): one record per expert request decomposed into queue-wait /
  batch-assembly / device-compute / serialize, per-expert quantiles, per-client
  attribution, plus client-side expert scorecards; served at ``GET /serving``.

- :mod:`~hivemind_tpu.telemetry.blackbox` — the black-box flight recorder
  (ISSUE 17): crash-durable on-disk telemetry spools (segment-rotated msgpack
  frames) fed from the span/ledger hooks, read back by ``hivemind-blackbox``
  and ``hivemind-top --from-spool`` for cross-peer post-mortems.

- :mod:`~hivemind_tpu.telemetry.device` — device-side observability
  (ISSUE 19): the jit compile tracker + recompile-storm detector, device
  memory/leak/transfer telemetry sampled by the watchdog tick, and the
  StepTimeline's comm/compute overlap-efficiency scoring (ROADMAP item 2's
  yardstick).

See docs/observability.md for the metric catalog and the span catalog.
"""

from hivemind_tpu.telemetry.device import (
    COMPILE_TRACKER,
    MEMORY_MONITOR,
    STEP_TIMELINE,
    DeviceMemoryMonitor,
    JitCompileTracker,
    StepTimeline,
    add_device_listener,
    arm_device_telemetry,
    device_snapshot,
    device_telemetry_armed,
    disarm_device_telemetry,
    record_transfer,
    remove_device_listener,
    reset_device_telemetry,
    span_lane,
)
from hivemind_tpu.telemetry.blackbox import (
    BlackBox,
    SpoolWriter,
    active_blackbox,
    arm_blackbox,
    disarm_blackbox,
    read_spool,
)
from hivemind_tpu.telemetry.exporter import MetricsExporter, render_prometheus
from hivemind_tpu.telemetry.ledger import LEDGER, RoundLedger
from hivemind_tpu.telemetry.serving import (
    SCORECARDS,
    SERVING_LEDGER,
    ExpertScorecards,
    ServingLedger,
    is_overload_error,
)
from hivemind_tpu.telemetry.watchdog import (
    EventLoopWatchdog,
    ensure_watchdog,
    watchdog_summary,
)
from hivemind_tpu.telemetry.tracing import (
    RECORDER,
    Span,
    SpanRecorder,
    current_span,
    finish_span,
    render_chrome_trace,
    set_slow_span_threshold,
    start_span,
    trace,
)
from hivemind_tpu.telemetry.monitor import (
    DEFAULT_TELEMETRY_KEY,
    SwarmMonitor,
    TelemetryPublisher,
    aggregate_swarm_view,
    build_peer_snapshot,
    fetch_swarm_telemetry,
)
from hivemind_tpu.telemetry.registry import (
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

__all__ = [
    "REGISTRY",
    "RECORDER",
    "COMPILE_TRACKER",
    "MEMORY_MONITOR",
    "STEP_TIMELINE",
    "JitCompileTracker",
    "DeviceMemoryMonitor",
    "StepTimeline",
    "add_device_listener",
    "remove_device_listener",
    "arm_device_telemetry",
    "disarm_device_telemetry",
    "device_telemetry_armed",
    "device_snapshot",
    "record_transfer",
    "reset_device_telemetry",
    "span_lane",
    "BlackBox",
    "SpoolWriter",
    "read_spool",
    "arm_blackbox",
    "disarm_blackbox",
    "active_blackbox",
    "LEDGER",
    "RoundLedger",
    "SERVING_LEDGER",
    "SCORECARDS",
    "ServingLedger",
    "ExpertScorecards",
    "is_overload_error",
    "EventLoopWatchdog",
    "ensure_watchdog",
    "watchdog_summary",
    "DEFAULT_BUCKETS",
    "DEFAULT_TELEMETRY_KEY",
    "Span",
    "SpanRecorder",
    "trace",
    "current_span",
    "start_span",
    "finish_span",
    "render_chrome_trace",
    "set_slow_span_threshold",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsExporter",
    "render_prometheus",
    "TelemetryPublisher",
    "SwarmMonitor",
    "build_peer_snapshot",
    "fetch_swarm_telemetry",
    "aggregate_swarm_view",
]
