"""Device-side observability (ISSUE 19): the accelerator's sibling of the
ledger/watchdog stack.

Every telemetry layer before this one watches the *host* — asyncio loops, wire
bytes, span trees — while the device was a black box: nothing counted jit
recompiles, live HBM, host↔device transfer cost, or whether the averaging round
actually overlaps compute. Three instruments fix that:

- :class:`JitCompileTracker` — fed by :func:`~hivemind_tpu.utils.profiling.tracked_jit`
  wrappers around every hot jit entry point (and by ``jax.monitoring`` compile
  events where the jaxlib exposes them). Records every compile's site, abstract
  signature, and duration; detects **recompile storms** (N compiles of one site
  inside a window → loud warning, exactly once per window) — the decode-bucket
  and batching paths are the known at-risk sites.
- :class:`DeviceMemoryMonitor` — live-buffer bytes / peak per device from
  ``jax.live_arrays()`` plus ``device.memory_stats()`` where available, sampled
  by the watchdog tick (never imports jax itself: a process that has not paid
  for a backend must not start paying because telemetry looked). A
  monotonic-growth heuristic flags suspected leaks across averaging rounds.
- :class:`StepTimeline` — assembled from finished spans: comm wall-time
  (``allreduce.round``, ``averaging.matchmaking``) intersected with compute
  intervals (``optimizer.update``, ``device.compute``) yields an **overlap
  efficiency** scalar — the fraction of comm hidden under compute, the
  before/after yardstick for ROADMAP item 2. Ratios are stamped onto the
  RoundLedger's round records and epoch rollups.

Counting (tracked_jit, :func:`record_transfer`, span listeners) is always-on
and hot-path cheap; :func:`arm_device_telemetry` additionally hooks the
watchdog memory sampler and the ``jax.monitoring`` listener. Everything
surfaces through :func:`device_snapshot` (DHT peer snapshot / hivemind-top
device board) and through device listeners (the black-box spool's ``device``
frames).
"""

from __future__ import annotations

import sys
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from hivemind_tpu.telemetry.registry import REGISTRY
from hivemind_tpu.telemetry import tracing as _tracing
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_COMPILES = REGISTRY.counter(
    "hivemind_device_compiles_total",
    "jit compiles observed, by site (a tracked_jit label or 'jax' for "
    "unattributed jax.monitoring events)",
    ("site",),
)
_COMPILE_SECONDS = REGISTRY.histogram(
    "hivemind_device_compile_seconds",
    "wall seconds per observed jit compile (tracked_jit measures the whole "
    "triggering call: trace + lower + compile)",
    ("site",),
)
_STORMS = REGISTRY.counter(
    "hivemind_device_recompile_storms_total",
    "recompile storms detected: >= storm_threshold compiles of one site inside "
    "storm_window_s (fires once per window per site)",
    ("site",),
)
_MEMORY_BYTES = REGISTRY.gauge(
    "hivemind_device_memory_bytes",
    "live jax buffer bytes per device (from jax.live_arrays; sharded arrays "
    "split evenly across their devices)",
    ("device",),
)
_MEMORY_PEAK_BYTES = REGISTRY.gauge(
    "hivemind_device_memory_peak_bytes",
    "peak device memory per device: backend peak_bytes_in_use where the "
    "runtime exposes it (TPU/GPU), else the high-water mark of sampled live bytes",
    ("device",),
)
_LIVE_BUFFERS = REGISTRY.gauge(
    "hivemind_device_live_buffers",
    "live jax arrays per device at the last watchdog sample",
    ("device",),
)
_LEAKS = REGISTRY.counter(
    "hivemind_device_memory_leak_suspected_total",
    "times the monotonic-growth heuristic fired: live bytes grew on every one "
    "of leak_samples consecutive watchdog samples by >= leak_min_growth total",
)
_TRANSFER = REGISTRY.counter(
    "hivemind_device_transfer_bytes_total",
    "bytes crossing the host<->device boundary on instrumented hot paths "
    "(expert batch upload/download, decode KV steps, state averaging mirrors)",
    ("direction",),
)
_OVERLAP = REGISTRY.gauge(
    "hivemind_device_overlap_ratio",
    "overlap efficiency of the most recent comm round: fraction of its wall "
    "time hidden under recorded compute intervals (ROADMAP item 2 yardstick)",
)

# cached children: record_transfer sits on per-batch/per-token paths
_TRANSFER_H2D = _TRANSFER.labels(direction="host_to_device")
_TRANSFER_D2H = _TRANSFER.labels(direction="device_to_host")

_H2D = "host_to_device"
_D2H = "device_to_host"

# Prometheus counters are process-cumulative by contract, but device_snapshot()
# promises "empty when nothing device-side has happened" after a reset — so the
# snapshot view subtracts the baseline captured by reset_device_telemetry().
_TRANSFER_BASELINE = {_H2D: 0, _D2H: 0}

# device-record listeners: the black-box spool subscribes here so compile /
# storm / leak / overlap / memory records survive a crash as ``device`` frames
_DEVICE_LISTENERS: List[Callable[[str, Dict[str, Any]], None]] = []


def add_device_listener(listener: Callable[[str, Dict[str, Any]], None]) -> None:
    """Subscribe ``listener(kind, record)`` to device telemetry records. Kinds:
    ``compile`` | ``storm`` | ``memory`` | ``leak`` | ``overlap``."""
    if listener not in _DEVICE_LISTENERS:
        _DEVICE_LISTENERS.append(listener)


def remove_device_listener(listener: Callable[[str, Dict[str, Any]], None]) -> None:
    try:
        _DEVICE_LISTENERS.remove(listener)
    except ValueError:
        pass


def _notify(kind: str, record: Dict[str, Any]) -> None:
    for listener in list(_DEVICE_LISTENERS):
        try:
            listener(kind, record)
        except Exception as e:  # a broken subscriber must not break the hot path
            logger.warning(f"device listener failed on {kind}: {e!r}")


def record_transfer(nbytes: int, direction: str) -> None:
    """Account ``nbytes`` crossing the host↔device boundary. Direction is
    ``host_to_device`` or ``device_to_host``. One cached-child counter inc —
    cheap enough for per-batch and per-token call sites."""
    if nbytes <= 0:
        return
    if direction == _H2D:
        _TRANSFER_H2D.inc(nbytes)
    elif direction == _D2H:
        _TRANSFER_D2H.inc(nbytes)
    else:
        raise ValueError(f"unknown transfer direction {direction!r}")


def transfer_totals() -> Dict[str, int]:
    """Bytes transferred since the last :func:`reset_device_telemetry` (the raw
    ``hivemind_device_transfer_bytes_total`` counters never reset)."""
    return {
        _H2D: int(_TRANSFER_H2D.value) - _TRANSFER_BASELINE[_H2D],
        _D2H: int(_TRANSFER_D2H.value) - _TRANSFER_BASELINE[_D2H],
    }


# ------------------------------------------------------------------ compiles


class JitCompileTracker:
    """Process-wide compile ledger. ``tracked_jit`` wrappers report every cache
    miss here; ``jax.monitoring`` events (armed processes) accrue as the
    un-attributed ``jax`` site. Detects recompile storms: ``storm_threshold``
    compiles of one site within ``storm_window_s`` fires a loud warning and a
    counter — exactly once per window, so a runaway site cannot also flood the
    logs."""

    def __init__(self, storm_threshold: int = 5, storm_window_s: float = 60.0):
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._seconds: Dict[str, float] = {}
        self._signatures: Dict[str, str] = {}  # last abstract signature per site
        self._recent: Dict[str, deque] = {}  # site -> recent compile timestamps
        self._storm_fired_at: Dict[str, float] = {}
        self._storms = 0
        self._last: Optional[Dict[str, Any]] = None

    def record_compile(
        self, site: str, duration_s: float = 0.0, signature: Optional[str] = None
    ) -> None:
        now = _tracing.telemetry_time()
        storm = False
        with self._lock:
            self._counts[site] = self._counts.get(site, 0) + 1
            self._seconds[site] = self._seconds.get(site, 0.0) + float(duration_s)
            if signature:
                self._signatures[site] = signature
            recent = self._recent.get(site)
            if recent is None:
                recent = self._recent[site] = deque(maxlen=max(self.storm_threshold * 4, 16))
            recent.append(now)
            in_window = sum(1 for t in recent if now - t <= self.storm_window_s)
            if in_window >= self.storm_threshold:
                fired = self._storm_fired_at.get(site)
                if fired is None or now - fired >= self.storm_window_s:
                    self._storm_fired_at[site] = now
                    self._storms += 1
                    storm = True
            record = {
                "site": site,
                "count": self._counts[site],
                "dur_s": round(float(duration_s), 6),
                "signature": signature,
            }
            self._last = record
        _COMPILES.inc(site=site)
        _COMPILE_SECONDS.observe(float(duration_s), site=site)
        span = _tracing.current_span()
        if span is not None:
            span.add_event("device.compile", site=site, dur_ms=round(duration_s * 1e3, 3))
        if storm:
            _STORMS.inc(site=site)
            logger.warning(
                f"RECOMPILE STORM at jit site {site!r}: >= {self.storm_threshold} compiles "
                f"within {self.storm_window_s:.0f}s (total {self._counts[site]}; last "
                f"signature {signature!r}) — the abstract signature is churning; bucket "
                f"shapes or hoist the jit (docs/observability.md 'Device telemetry')"
            )
            _notify("storm", {"site": site, "count": self._counts[site]})
        _notify("compile", record)

    def record_jax_event(self, event: str, duration_s: float) -> None:
        """Un-attributed compile-flavored ``jax.monitoring`` event (e.g. backend
        compile time). Accrued under the reserved site ``jax`` — kept out of the
        per-site storm detector (one user-visible site can emit several backend
        events per compile)."""
        with self._lock:
            self._counts["jax"] = self._counts.get("jax", 0) + 1
            self._seconds["jax"] = self._seconds.get("jax", 0.0) + float(duration_s)
        _COMPILES.inc(site="jax")
        _COMPILE_SECONDS.observe(float(duration_s), site="jax")

    # ------------------------------------------------------------- inspection

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def total(self, include_jax_events: bool = False) -> int:
        """Cumulative compiles across sites (the benchmark steady-state mark).
        ``jax.monitoring`` backend events are excluded by default so the count
        matches 'distinct tracked_jit cache misses'."""
        with self._lock:
            return sum(
                count
                for site, count in self._counts.items()
                if include_jax_events or site != "jax"
            )

    def storm_count(self) -> int:
        with self._lock:
            return self._storms

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            sites = {
                site: {
                    "count": count,
                    "seconds": round(self._seconds.get(site, 0.0), 4),
                    **(
                        {"signature": self._signatures[site]}
                        if site in self._signatures
                        else {}
                    ),
                }
                for site, count in sorted(self._counts.items())
            }
            return {
                "total": sum(self._counts.values()),
                "seconds": round(sum(self._seconds.values()), 4),
                "storms": self._storms,
                "sites": sites,
                "last": dict(self._last) if self._last else None,
            }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._seconds.clear()
            self._signatures.clear()
            self._recent.clear()
            self._storm_fired_at.clear()
            self._storms = 0
            self._last = None


COMPILE_TRACKER = JitCompileTracker()


# ------------------------------------------------------------------- memory


class DeviceMemoryMonitor:
    """Live/peak device memory, sampled from whatever jax state already exists.

    ``sample()`` NEVER imports jax or initializes a backend: it reads
    ``sys.modules`` (the same discipline as the watchdog's executor sampler) and
    walks ``jax.live_arrays()`` — so a lightweight process pays nothing, and a
    jax process pays one python loop per watchdog tick. Peak per device is the
    backend's ``peak_bytes_in_use`` where the runtime exposes one (TPU/GPU),
    else a host-side high-water mark of sampled live bytes (CPU).

    Leak heuristic: live bytes strictly grew on ``leak_samples`` consecutive
    samples AND the total growth exceeds ``leak_min_growth`` bytes → warn +
    counter, then restart the episode (no refiring every tick)."""

    def __init__(self, leak_samples: int = 8, leak_min_growth: int = 8 << 20):
        self.leak_samples = int(leak_samples)
        self.leak_min_growth = int(leak_min_growth)
        self._lock = threading.Lock()
        self._trend: deque = deque(maxlen=max(self.leak_samples, 2))
        self._peak: Dict[str, int] = {}
        self._leaks = 0
        self.last_sample: Optional[Dict[str, Any]] = None

    def sample(self, modules=None) -> Optional[Dict[str, Any]]:
        jax = (modules if modules is not None else sys.modules).get("jax")
        if jax is None:
            return None
        try:
            arrays = jax.live_arrays()
        except Exception:
            return None
        per_device: Dict[str, List[int]] = {}  # device -> [bytes, buffers]
        device_objs: Dict[str, Any] = {}
        for array in arrays:
            try:
                devices = list(array.devices())
                nbytes = int(array.nbytes)
            except Exception:
                continue  # deleted/donated buffers can race the walk
            if not devices:
                continue
            share = nbytes // len(devices)
            for device in devices:
                key = str(device)
                entry = per_device.setdefault(key, [0, 0])
                entry[0] += share
                entry[1] += 1
                device_objs.setdefault(key, device)
        snapshot: Dict[str, Any] = {"devices": {}, "total_bytes": 0, "buffers": 0}
        with self._lock:
            for key, (nbytes, buffers) in sorted(per_device.items()):
                stats = None
                try:
                    stats = device_objs[key].memory_stats()
                except Exception:
                    stats = None
                backend_peak = int((stats or {}).get("peak_bytes_in_use", 0))
                self._peak[key] = max(self._peak.get(key, 0), nbytes, backend_peak)
                entry = {"bytes": nbytes, "buffers": buffers, "peak_bytes": self._peak[key]}
                if stats and "bytes_in_use" in stats:
                    entry["backend_bytes_in_use"] = int(stats["bytes_in_use"])
                snapshot["devices"][key] = entry
                snapshot["total_bytes"] += nbytes
                snapshot["buffers"] += buffers
                _MEMORY_BYTES.set(nbytes, device=key)
                _MEMORY_PEAK_BYTES.set(self._peak[key], device=key)
                _LIVE_BUFFERS.set(buffers, device=key)
            self._trend.append(snapshot["total_bytes"])
            leak = (
                len(self._trend) == self._trend.maxlen
                and all(b > a for a, b in zip(self._trend, list(self._trend)[1:]))
                and self._trend[-1] - self._trend[0] >= self.leak_min_growth
            )
            if leak:
                self._leaks += 1
                growth = self._trend[-1] - self._trend[0]
                self._trend.clear()  # restart the episode: fire once, not every tick
            self.last_sample = snapshot
        if leak:
            _LEAKS.inc()
            logger.warning(
                f"suspected device memory leak: live buffer bytes grew monotonically "
                f"across {self.leak_samples} samples (+{growth} bytes, now "
                f"{snapshot['total_bytes']}) — check for caches pinned across "
                f"averaging rounds"
            )
            _notify("leak", {"growth_bytes": growth, "total_bytes": snapshot["total_bytes"]})
        return snapshot

    def leak_count(self) -> int:
        with self._lock:
            return self._leaks

    def reset(self) -> None:
        with self._lock:
            self._trend.clear()
            self._peak.clear()
            self._leaks = 0
            self.last_sample = None


MEMORY_MONITOR = DeviceMemoryMonitor()


# ------------------------------------------------------------------ timeline

# top-level comm spans only: peer_exchange / local_reduce are CHILDREN of
# allreduce.round — counting them too would double-count comm wall time
COMM_SPAN_NAMES = frozenset({"allreduce.round", "averaging.matchmaking", "averaging.aggregate"})
COMPUTE_SPAN_NAMES = frozenset({"optimizer.update", "device.compute", "moe.forward", "moe.backward"})
# child spans that still belong on the comm LANE in the Perfetto export
_COMM_LANE_PREFIXES = ("allreduce.", "averaging.")


def span_lane(name: str) -> Optional[str]:
    """Perfetto lane for a span name: ``comm`` / ``compute`` / None (default
    lane). Used by the chrome-trace exports to render compute-vs-comm rows."""
    if name in COMPUTE_SPAN_NAMES:
        return "compute"
    if name in COMM_SPAN_NAMES or name.startswith(_COMM_LANE_PREFIXES):
        return "comm"
    return None


class StepTimeline:
    """Comm/compute correlation from finished spans (registered as a span
    listener at import, like the RoundLedger).

    Compute spans (``optimizer.update``, ``device.compute``, expert
    forward/backward) append intervals to a bounded per-peer ring. When a
    top-level comm span finishes, its wall window is intersected with the union
    of that peer's recorded compute intervals: ``overlap_ratio`` = overlapped
    seconds / comm seconds — 0.0 when the round ran bare, 1.0 when it hid
    entirely under compute. Each ratio is stamped onto the RoundLedger (round
    records + epoch rollups) and pushed to device listeners; ``optimizer.step``
    spans additionally close per-step records carrying the grad-ready offset."""

    def __init__(self, capacity: int = 256, step_capacity: int = 64):
        self._lock = threading.Lock()
        self._compute: Dict[str, deque] = {}  # peer -> deque[(start, end)]
        self._records: deque = deque(maxlen=capacity)  # comm overlap records
        self._steps: deque = deque(maxlen=step_capacity)
        self._grad_ready: Dict[str, float] = {}
        self._capacity = capacity
        self._overlap_sum = 0.0
        self._overlap_count = 0

    # ------------------------------------------------------------ span intake

    def on_span(self, span) -> None:
        name = span.name
        if name in COMPUTE_SPAN_NAMES:
            self._on_compute(span)
        elif name in COMM_SPAN_NAMES:
            self._on_comm(span)
        elif name == "optimizer.step":
            self._on_step(span)

    def _peer_of(self, span) -> str:
        attrs = span.attributes or {}
        return str(attrs.get("peer", ""))

    def _on_compute(self, span) -> None:
        peer = self._peer_of(span)
        end = span.end if span.end is not None else _tracing.telemetry_time()
        with self._lock:
            ring = self._compute.get(peer)
            if ring is None:
                ring = self._compute[peer] = deque(maxlen=self._capacity)
            ring.append((span.start, end))

    def note_grad_ready(self, peer: str = "") -> None:
        """Optimizers mark the moment gradients finished accumulating; the next
        ``optimizer.step`` record carries the offset (backward → comm handoff)."""
        with self._lock:
            self._grad_ready[str(peer)] = _tracing.telemetry_time()

    def _on_comm(self, span) -> None:
        peer = self._peer_of(span)
        end = span.end if span.end is not None else _tracing.telemetry_time()
        start, dur = span.start, max(end - span.start, 0.0)
        with self._lock:
            intervals = [
                iv
                for iv in self._compute.get(peer, ())
                if iv[1] > start and iv[0] < end
            ]
            overlapped = _union_overlap(intervals, start, end)
            ratio = round(overlapped / dur, 4) if dur > 0 else 0.0
            record = {
                "kind": span.name,
                "peer": peer,
                "start": round(start, 6),
                "dur_s": round(dur, 6),
                "overlap_s": round(overlapped, 6),
                "overlap_ratio": ratio,
            }
            self._records.append(record)
            self._overlap_sum += ratio
            self._overlap_count += 1
        _OVERLAP.set(ratio)
        if span.name == "allreduce.round":
            # stamp the ledger lazily: device → ledger is a one-way dependency
            from hivemind_tpu.telemetry.ledger import LEDGER

            LEDGER.note_overlap(peer, ratio)
        _notify("overlap", record)

    def _on_step(self, span) -> None:
        peer = self._peer_of(span)
        end = span.end if span.end is not None else _tracing.telemetry_time()
        record = {
            "peer": peer,
            "start": round(span.start, 6),
            "dur_s": round(max(end - span.start, 0.0), 6),
        }
        attrs = span.attributes or {}
        if "epoch" in attrs:
            record["epoch"] = attrs["epoch"]
        with self._lock:
            grad_ready = self._grad_ready.get(peer)
            if grad_ready is not None and span.start <= grad_ready <= end:
                record["grad_ready_s"] = round(grad_ready - span.start, 6)
            self._steps.append(record)

    # ------------------------------------------------------------- inspection

    def overlap_summary(self) -> Dict[str, Any]:
        with self._lock:
            if not self._overlap_count:
                return {"rounds": 0}
            return {
                "rounds": self._overlap_count,
                "last": self._records[-1]["overlap_ratio"] if self._records else None,
                "mean": round(self._overlap_sum / self._overlap_count, 4),
            }

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def steps(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._steps)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            records = list(self._records)[-5:]
            steps = len(self._steps)
        out = {"overlap": self.overlap_summary(), "steps": steps}
        if records:
            out["recent"] = records
        return out

    def clear(self) -> None:
        with self._lock:
            self._compute.clear()
            self._records.clear()
            self._steps.clear()
            self._grad_ready.clear()
            self._overlap_sum = 0.0
            self._overlap_count = 0


def _union_overlap(intervals: List[Tuple[float, float]], start: float, end: float) -> float:
    """Seconds of [start, end] covered by the union of ``intervals``."""
    total = 0.0
    cursor = start
    for iv_start, iv_end in sorted(intervals):
        lo, hi = max(iv_start, cursor), min(iv_end, end)
        if hi > lo:
            total += hi - lo
            cursor = hi
        if cursor >= end:
            break
    return total


STEP_TIMELINE = StepTimeline()
_tracing.add_span_listener(STEP_TIMELINE.on_span)


# ------------------------------------------------------------------ snapshot


def device_snapshot() -> Dict[str, Any]:
    """The ``device`` section of the DHT peer snapshot / hivemind-top board:
    compile totals per site, last memory sample, transfer totals, overlap
    summary. Empty dict when nothing device-side has happened (lightweight
    peers publish no device section at all)."""
    out: Dict[str, Any] = {}
    compiles = COMPILE_TRACKER.summary()
    if compiles["total"]:
        out["compiles"] = compiles
    memory = MEMORY_MONITOR.last_sample
    if memory:
        out["memory"] = memory
    if MEMORY_MONITOR.leak_count():
        out["leaks_suspected"] = MEMORY_MONITOR.leak_count()
    transfers = transfer_totals()
    if any(transfers.values()):
        out["transfer_bytes"] = transfers
    overlap = STEP_TIMELINE.overlap_summary()
    if overlap.get("rounds"):
        out["overlap"] = overlap
    return out


def compact_device_snapshot(section: Dict[str, Any]) -> Dict[str, Any]:
    """Shrink a device section for snapshot budgets: drop per-site compile
    detail and the per-device memory map, keep the headline numbers."""
    out: Dict[str, Any] = {}
    compiles = section.get("compiles")
    if compiles:
        out["compiles"] = {
            "total": compiles.get("total"),
            "seconds": compiles.get("seconds"),
            "storms": compiles.get("storms"),
        }
    memory = section.get("memory")
    if memory:
        out["memory"] = {
            "total_bytes": memory.get("total_bytes"),
            "buffers": memory.get("buffers"),
        }
    for key in ("leaks_suspected", "transfer_bytes", "overlap"):
        if key in section:
            out[key] = section[key]
    return out


# -------------------------------------------------------------------- arming

_MONITORING_INSTALLED = False
_ARMED = False


def _watchdog_sampler() -> None:
    MEMORY_MONITOR.sample()
    memory = MEMORY_MONITOR.last_sample
    if memory:
        _notify("memory", memory)


def _install_jax_monitoring() -> None:
    """Hook ``jax.monitoring`` compile-duration events (where this jaxlib has
    them) into the tracker. Install-once per process: jax offers registration
    but no reliable unregistration across versions, so the trampoline stays and
    the tracker's reset() is what tests rely on."""
    global _MONITORING_INSTALLED
    if _MONITORING_INSTALLED:
        return
    jax = sys.modules.get("jax")
    if jax is None:
        return  # never import jax for telemetry's sake
    monitoring = getattr(jax, "monitoring", None)
    register = getattr(monitoring, "register_event_duration_secs_listener", None)
    if register is None:
        return
    def _on_event(event: str, duration: float, **_kwargs) -> None:
        if "compil" in event:  # matches compile/compilation event families
            COMPILE_TRACKER.record_jax_event(event, duration)

    try:
        register(_on_event)
        _MONITORING_INSTALLED = True
    except Exception as e:  # telemetry must never take the process down
        logger.warning(f"could not install jax.monitoring listener: {e!r}")


def arm_device_telemetry() -> None:
    """Turn on the sampled half of device telemetry: watchdog memory sampling +
    jax.monitoring compile events. The counting half (tracked_jit, transfers,
    the span timeline) is always-on. Idempotent."""
    global _ARMED
    from hivemind_tpu.telemetry import watchdog as _watchdog

    _install_jax_monitoring()
    _watchdog.add_tick_sampler(_watchdog_sampler)
    _ARMED = True


def disarm_device_telemetry() -> None:
    global _ARMED
    from hivemind_tpu.telemetry import watchdog as _watchdog

    _watchdog.remove_tick_sampler(_watchdog_sampler)
    _ARMED = False


def device_telemetry_armed() -> bool:
    return _ARMED


def reset_device_telemetry() -> None:
    """Test hygiene (conftest): zero the trackers and disarm the samplers, the
    device-side mirror of LEDGER.clear()/disarm_blackbox()."""
    disarm_device_telemetry()
    COMPILE_TRACKER.reset()
    MEMORY_MONITOR.reset()
    STEP_TIMELINE.clear()
    del _DEVICE_LISTENERS[:]
    _TRANSFER_BASELINE[_H2D] = int(_TRANSFER_H2D.value)
    _TRANSFER_BASELINE[_D2H] = int(_TRANSFER_D2H.value)
