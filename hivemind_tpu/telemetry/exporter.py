"""Prometheus text-format exposition over a stdlib HTTP endpoint.

``render_prometheus`` produces the text exposition format (version 0.0.4) from a
:class:`~hivemind_tpu.telemetry.registry.MetricsRegistry`; ``MetricsExporter``
serves it at ``GET /metrics`` from a daemon-threaded ``ThreadingHTTPServer`` —
no ``prometheus_client`` dependency (acceptance criterion), nothing async, and
zero cost to the instrumented process until something actually scrapes.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from hivemind_tpu.telemetry.ledger import LEDGER, RoundLedger
from hivemind_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from hivemind_tpu.telemetry.serving import SERVING_LEDGER, ServingLedger
from hivemind_tpu.telemetry.tracing import RECORDER, SpanRecorder, render_chrome_trace
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(labelnames, labelvalues, extra: str = "") -> str:
    parts = [f'{n}="{_escape_label_value(str(v))}"' for n, v in zip(labelnames, labelvalues)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _format_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if float(value).is_integer() and abs(value) < 2**53:
        return str(int(value))
    return repr(float(value))


def render_prometheus(registry: MetricsRegistry = REGISTRY) -> str:
    """The registry as Prometheus text exposition (one scrape)."""
    lines = []
    for metric in registry.collect():
        name = metric.name
        lines.append(f"# HELP {name} {metric.documentation or name}")
        lines.append(f"# TYPE {name} {metric.metric_type}")
        if metric.metric_type == "histogram":
            for key, child in metric.series():
                buckets, total, count = child.snapshot()
                for bound, cumulative in zip(metric.buckets, buckets):
                    labels = _format_labels(metric.labelnames, key, f'le="{_format_value(bound)}"')
                    lines.append(f"{name}_bucket{labels} {cumulative}")
                labels = _format_labels(metric.labelnames, key, 'le="+Inf"')
                lines.append(f"{name}_bucket{labels} {count}")
                plain = _format_labels(metric.labelnames, key)
                lines.append(f"{name}_sum{plain} {_format_value(total)}")
                lines.append(f"{name}_count{plain} {count}")
        else:
            # counters expose a _total sample; a declared ..._total name is kept as-is
            sample = name
            if metric.metric_type == "counter" and not name.endswith("_total"):
                sample = name + "_total"
            for key, child in metric.series():
                labels = _format_labels(metric.labelnames, key)
                lines.append(f"{sample}{labels} {_format_value(child.value)}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY  # overridden per-server
    recorder: SpanRecorder = RECORDER  # overridden per-server
    ledger: RoundLedger = LEDGER  # overridden per-server
    serving_ledger: ServingLedger = SERVING_LEDGER  # overridden per-server

    def do_GET(self):  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = render_prometheus(self.registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
        elif path == "/metrics.json":
            body = json.dumps(self.registry.snapshot()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif path == "/ledger":
            # raw round/epoch attribution records + straggler scores (ISSUE 8):
            # "where did epoch N's wall time go, and which peer caused it" —
            # serialization happens HERE, never on the record path
            body = json.dumps(self.ledger.export(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif path == "/serving":
            # per-request serving attribution (ISSUE 9): records with their
            # queue-wait/assembly/compute/serialize decomposition, per-expert
            # quantiles, per-client attribution, slowest exemplars, the live
            # saturation gauges, and this process's client-side scorecards
            body = json.dumps(self.serving_ledger.export(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif path == "/trace":
            # the flight recorder as Chrome trace-event JSON: save the response
            # to a file and open it in Perfetto / chrome://tracing (one pid row
            # per peer; serialization happens HERE, never on the record path)
            body = json.dumps(render_chrome_trace(self.recorder.snapshot()), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif path == "/healthz":
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 (stdlib API)
        pass  # scrapes must not spam the training logs


class MetricsExporter:
    """Serve ``/metrics`` (Prometheus text), ``/metrics.json`` (compact
    snapshot), ``/trace`` (Chrome trace-event JSON from the span flight
    recorder), ``/ledger`` (raw per-round attribution records), ``/serving``
    (raw per-request serving attribution + scorecards) and ``/healthz`` on a
    daemon thread.

    :param port: TCP port; 0 picks a free one (read it back via ``.port``)
    :param host: bind host; default loopback — pass "0.0.0.0" for remote scrapers
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: MetricsRegistry = REGISTRY,
        recorder: SpanRecorder = RECORDER,
        ledger: RoundLedger = LEDGER,
        serving_ledger: ServingLedger = SERVING_LEDGER,
        start: bool = True,
    ):
        self.registry = registry
        self.recorder = recorder
        self.ledger = ledger
        self.serving_ledger = serving_ledger
        handler = type(
            "_BoundMetricsHandler",
            (_MetricsHandler,),
            {"registry": registry, "recorder": recorder, "ledger": ledger,
             "serving_ledger": serving_ledger},
        )
        self._server = ThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="metrics-exporter", daemon=True
        )
        self._thread.start()
        logger.info(f"metrics exporter listening on :{self.port}/metrics")

    def shutdown(self) -> None:
        thread, self._thread = self._thread, None
        if thread is not None:
            self._server.shutdown()
            thread.join(timeout=5.0)
        self._server.server_close()
